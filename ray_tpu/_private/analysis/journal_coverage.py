"""Pass 8: journal coverage — every GCS mutator reaches journal_hook.

The gcs-mutation pass (gcs_mutation.py) guarantees the journaled tables
are only written INSIDE gcs.py; this pass closes the other half of the
durability contract: a mutator inside gcs.py that writes a journaled
table but never calls `self._journal(...)` would mutate memory without
ever reaching the journal hook — the mutation silently would not survive
a head bounce, and no chaos round is guaranteed to catch the one table it
forgot.  The hole got more interesting with group commit
(gcs_storage.MutationJournal batches appends): the journal write is now
decoupled from the mutation in TIME, so a dropped entry KIND would look
identical to normal linger in any manual test.

Two checks:

  * mutator coverage — every GlobalState method that writes a journaled
    table (same write-shape detection as gcs-mutation: subscript/del/
    augassign/mutating method calls on `self.<table>`) must contain a
    `self._journal(...)` call.  Restore-path bulk loaders that apply
    ALREADY-journaled entries are exempt by name (_RESTORE_EXEMPT) — they
    must NOT re-journal what they replay;
  * kind catalog — every literal entry kind handed to `_journal(...)` /
    `_journal_append(...)` anywhere in the package must be in
    KNOWN_KINDS.  A new kind is a REVIEW EVENT: the author must decide
    its restore-time handling (apply, like actor_state; or ignore, like
    lease) and add it here — an unreviewed kind replays as silence.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu._private.analysis.common import (
    Violation,
    parse_file,
    terminal_name,
)

PASS = "journal-coverage"

# Keep in sync with gcs_mutation._JOURNALED_TABLES.
_JOURNALED_TABLES = frozenset({
    "actors", "named_actors", "jobs", "functions", "placement_groups",
})
_MUTATING_METHODS = frozenset({"pop", "popitem", "update", "setdefault", "clear"})
_MUTATOR_MODULE = "ray_tpu/_private/gcs.py"

# Bulk loaders on the RESTORE path: they apply entries that came FROM the
# journal/snapshot being replayed; journaling them again would double
# every entry at the next compaction.
_RESTORE_EXEMPT = frozenset({"import_functions", "restore_pg"})

# Reviewed journal entry kinds with their restore-time handling:
#   actor_register / actor_state / job_state / function / lineage —
#     applied by Runtime._restore_snapshot;
#   pg_register / pg_state — applied by Runtime._restore_snapshot (PG
#     record upsert / lifecycle merge); a PG that died mid-RESHAPING
#     replays as RESHAPING and re-enters the reshape sweep with a fresh
#     wait deadline (the deadline itself is head-local, never persisted);
#   lease — diagnostic only: leases are runtime state that cannot outlive
#     the workers' resource reservations, a restarted head re-grants from
#     live traffic (restore ignores them by design);
#   node_lifecycle — applied by Runtime._restore_snapshot (per-node state
#     merge onto Runtime.node_lifecycle): DEPARTED is terminal; DRAINING
#     resumes draining after a head bounce (the daemon's re-registration
#     re-marks NodeInfo.draining and the reconciler re-arms FRESH drain
#     windows — wall-clock deadlines are head-local and never persisted);
#     REQUESTED/STARTING are re-checked against the provider by the
#     reconciler; ACTIVE is re-confirmed by daemon reconnect or flipped
#     DEPARTED by the death path;
#   demand — advisory demand-summary trail (throttled by the autoscaler
#     reconciler) for post-mortem "why did it scale" analysis; restore
#     ignores it by design: demand is recomputed from live queues.
KNOWN_KINDS = frozenset({
    "actor_register", "actor_state", "job_state", "function", "lineage",
    "lease", "pg_register", "pg_state", "node_lifecycle", "demand",
})


def _self_table_write(node: ast.AST) -> Optional[str]:
    """Table name when `node` is a write-shaped access on
    `self.<journaled table>`."""
    def table_of(expr) -> Optional[str]:
        if (
            isinstance(expr, ast.Attribute)
            and expr.attr in _JOURNALED_TABLES
            and terminal_name(expr.value) == "self"
        ):
            return expr.attr
        return None

    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                got = table_of(t.value)
                if got:
                    return got
    elif isinstance(node, ast.AugAssign):
        if isinstance(node.target, ast.Subscript):
            return table_of(node.target.value)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                got = table_of(t.value)
                if got:
                    return got
    elif isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
            return table_of(f.value)
    return None


def _journal_call_kinds(tree: ast.AST):
    """(call_node, literal_kind_or_None) for every `*._journal(...)` /
    `*._journal_append(...)` / `journal_hook(...)`-shaped call."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if name not in ("_journal", "_journal_append"):
            continue
        kind = None
        if node.args and isinstance(node.args[0], ast.Tuple) and node.args[0].elts:
            first = node.args[0].elts[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                kind = first.value
        yield node, kind


def scan_file(path: str, rel: str) -> List[Violation]:
    tree = parse_file(path)
    if tree is None:
        return []
    out: List[Violation] = []

    # Kind catalog (package-wide): unreviewed literal kinds fail.
    for node, kind in _journal_call_kinds(tree):
        if kind is not None and kind not in KNOWN_KINDS:
            key = f"{PASS}:{rel}:kind:{kind}"
            out.append(Violation(
                PASS, rel, node.lineno, key,
                f"{rel}:{node.lineno}: journal entry kind {kind!r} is not "
                "in journal_coverage.KNOWN_KINDS — decide its restore-time "
                "handling (apply or explicitly ignore) and add it to the "
                "reviewed catalog; an unreviewed kind replays as silence",
            ))

    # Mutator coverage: gcs.py only.
    if rel != _MUTATOR_MODULE:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in _RESTORE_EXEMPT or node.name.startswith("__"):
            continue
        written = None
        for sub in ast.walk(node):
            written = _self_table_write(sub)
            if written:
                break
        if not written:
            continue
        has_journal = any(True for _n, _k in _journal_call_kinds(node))
        if not has_journal:
            key = f"{PASS}:{rel}:{node.name}:{written}"
            out.append(Violation(
                PASS, rel, node.lineno, key,
                f"{rel}:{node.lineno}: GlobalState.{node.name} writes "
                f"journaled table `{written}` but never calls "
                "self._journal(...) — the mutation would not survive a "
                "head bounce (batched or not, every mutator must reach "
                "journal_hook); restore-path bulk loaders belong in "
                "_RESTORE_EXEMPT instead",
            ))
    return out
