"""Pass 10: wire-schema conformance — static send/recv checks vs SCHEMAS.

The protocol's worst bugs have all been silent schema drift caught only
by runtime accidents: the `ready` arity cap that broke every reconnect
(a recv handler assumed more fields than the schema guaranteed), and the
unregistered `refs_push` kind whose whole coalesced batch — innocent
`task_events` riding along — was rejected at the boundary.  The
reference avoids the class with generated protobuf stubs; our
hand-maintained `wire.SCHEMAS` table gets this cross-check instead.

Three sub-checks:

  * send sites — every tuple literal passed directly to `.send(...)` /
    `.oneway(...)` (or to wire.encode/encode_body/encode_native) in the
    wire-speaking modules: the kind must be registered in SCHEMAS
    (unknown kinds poison whole batches), the literal arity must fall in
    the schema's [min,max], and leading typed fields must match where
    the literal's type is statically inferable;
  * recv dispatch — per-function `kind == "x"` / `kind in (...)` chains
    over a received message variable: a subscript `msg[N]` or an exact
    tuple unpack inside a handler that assumes more fields than the
    schema's MIN guarantees (and is not under a `len(msg)` guard) fails
    — exactly the PR-4 bug class;
  * native table — wire_native.KIND_IDS must be a subset of SCHEMAS with
    ids in 1..0x7F (0x80 is pickle's discriminator), and the kinds whose
    payload the native codec shapes with an EXACT arity
    (wire_native.NATIVE_ARITIES) must agree with the schema bounds.

Dynamically built frames (vars, *args splats) are out of static reach
and skipped — `wire._validate` still rejects them at runtime.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu._private.analysis.common import Violation, parse_file

PASS = "wire-schema"

# Modules that speak the wire protocol.  Send/recv scanning is scoped to
# these (plus fixture trees, which reuse the names): `.send(...)` on
# non-wire channels elsewhere (mp pipes, queues) is not a frame.
WIRE_MODULES = frozenset(
    {
        "ray_tpu/_private/runtime.py",
        "ray_tpu/_private/worker_proc.py",
        "ray_tpu/_private/peer.py",
        "ray_tpu/_private/io_shard.py",
        "ray_tpu/_private/node_daemon.py",
        "ray_tpu/_private/driver_client.py",
        "ray_tpu/_private/pubsub.py",
        "ray_tpu/_private/telemetry.py",
        "ray_tpu/_private/head.py",
        "ray_tpu/_private/object_plane.py",
        "ray_tpu/_private/zygote.py",
        "ray_tpu/_private/wire.py",
        "ray_tpu/rllib/policy_client.py",
    }
)

# Call attrs whose first positional argument is a wire frame.
_SEND_ATTRS = frozenset({"send", "oneway"})
_ENCODE_FUNCS = frozenset({"encode", "encode_body", "encode_native"})


def _schemas() -> Dict[str, Tuple[int, Optional[int], tuple]]:
    from ray_tpu._private import wire

    return wire.SCHEMAS


# --- literal type inference -------------------------------------------------

# Known-constructor call results, by terminal callee name.  Deliberately
# small: only names whose return type is unambiguous in this codebase.
_CTOR_TYPES = {
    "dict": dict,
    "list": list,
    "tuple": tuple,
    "set": set,
    "str": str,
    "repr": str,
    "int": int,
    "len": int,
    "float": float,
    "bool": bool,
    "bytes": bytes,
    "getpid": int,
    "time": float,
    "monotonic": float,
}


def _infer_type(node: ast.AST) -> Optional[type]:
    """Static type of a literal-ish expression, or None = unknowable."""
    if isinstance(node, ast.Constant):
        return type(node.value)
    if isinstance(node, ast.JoinedStr):
        return str
    if isinstance(node, ast.List):
        return list
    if isinstance(node, ast.Dict):
        return dict
    if isinstance(node, ast.Tuple):
        return tuple
    if isinstance(node, ast.Set):
        return set
    if isinstance(node, ast.Call):
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        return _CTOR_TYPES.get(name) if name else None
    return None


def _field_type_ok(node: ast.AST, want: Optional[type]) -> bool:
    if want is None:
        return True
    got = _infer_type(node)
    if got is None:
        return True  # unknowable: runtime _validate is the backstop
    if got is type(None):
        return False  # isinstance(None, t) is False for every schema type
    return issubclass(got, want)


# --- send side --------------------------------------------------------------


class _Scanner(ast.NodeVisitor):
    """Shared scope-tracking base (qualname like metric_names)."""

    def __init__(self, rel: str):
        self.rel = rel
        self.scope: List[str] = []
        self.violations: Dict[str, Violation] = {}

    def qualname(self) -> str:
        return ".".join(self.scope) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def _visit_func(self, node) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def add(self, line: int, key: str, message: str) -> None:
        if key not in self.violations:
            self.violations[key] = Violation(PASS, self.rel, line, key, message)


class _SendScanner(_Scanner):
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        is_send = isinstance(func, ast.Attribute) and func.attr in _SEND_ATTRS
        is_encode = (
            isinstance(func, ast.Attribute) and func.attr in _ENCODE_FUNCS
        ) or (isinstance(func, ast.Name) and func.id in _ENCODE_FUNCS)
        if (is_send or is_encode) and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Tuple) and arg.elts:
                head = arg.elts[0]
                if isinstance(head, ast.Constant) and isinstance(head.value, str):
                    self._check_frame(arg, head.value)
        self.generic_visit(node)

    def _check_frame(self, tup: ast.Tuple, kind: str) -> None:
        schemas = _schemas()
        scope = self.qualname()
        spec = schemas.get(kind)
        if spec is None:
            self.add(
                tup.lineno,
                f"{PASS}:send-kind:{self.rel}:{scope}:{kind}",
                f"{self.rel}:{tup.lineno}: send of unregistered frame kind "
                f"{kind!r} — wire._validate rejects it at decode, poisoning "
                "the whole coalesced batch it rides in (the refs_push bug "
                "class); register it in wire.SCHEMAS",
            )
            return
        lo, hi, types = spec
        extras = tup.elts[1:]
        if any(isinstance(e, ast.Starred) for e in extras):
            return  # splat: arity not static
        n = len(extras)
        if n < lo or (hi is not None and n > hi):
            self.add(
                tup.lineno,
                f"{PASS}:send-arity:{self.rel}:{scope}:{kind}",
                f"{self.rel}:{tup.lineno}: {kind!r} frame sent with {n} "
                f"field(s), schema allows [{lo}, "
                f"{hi if hi is not None else 'inf'}] — the receiver rejects "
                "it at the boundary (the ready-arity bug class)",
            )
        for i, want in enumerate(types):
            if i >= len(extras):
                break
            if not _field_type_ok(extras[i], want):
                self.add(
                    tup.lineno,
                    f"{PASS}:send-type:{self.rel}:{scope}:{kind}:field{i}",
                    f"{self.rel}:{tup.lineno}: {kind!r} frame field {i} is "
                    f"statically not a {want.__name__} — wire._validate "
                    "rejects the frame at decode",
                )


# --- recv side --------------------------------------------------------------


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _terminates(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


class _RecvScanner(_Scanner):
    """Per-function dispatch analysis: find `kind == "x"` chains over a
    message variable and check that each handler's accesses stay within
    what the schema's MIN arity guarantees."""

    def _visit_func(self, node) -> None:
        self.scope.append(node.name)
        _FuncRecv(self, node).run()
        # Nested defs get their own dispatch analysis (closures handling
        # frames are common in the recv loops).
        for stmt in node.body:
            for child in ast.walk(stmt):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._visit_nested(child)
        self.scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _visit_nested(self, node) -> None:
        self.scope.append(node.name)
        _FuncRecv(self, node).run()
        self.scope.pop()


class _FuncRecv:
    def __init__(self, scanner: _RecvScanner, func) -> None:
        self.s = scanner
        self.func = func
        # name -> message var it aliases the kind of (`kind = msg[0]`)
        self.kind_alias: Dict[str, str] = {}
        # name -> message var it aliases the LENGTH of (`n = len(msg)`)
        self.len_alias: Dict[str, str] = {}

    def run(self) -> None:
        self._collect_aliases(self.func.body)
        self._walk_block(self.func.body)

    # -- alias collection (own statements only, not nested defs) --

    def _collect_aliases(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name):
                        src = self._msg_sub0(node.value)
                        if src is not None:
                            self.kind_alias[tgt.id] = src
                        src = self._len_of(node.value)
                        if src is not None:
                            self.len_alias[tgt.id] = src

    @staticmethod
    def _msg_sub0(node: ast.AST) -> Optional[str]:
        """`msg[0]` -> "msg" (the kind position)."""
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and isinstance(node.slice, ast.Constant)
            and node.slice.value == 0
        ):
            return node.value.id
        return None

    @staticmethod
    def _len_of(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)
        ):
            return node.args[0].id
        return None

    # -- kind-test extraction --

    def _kind_test(
        self, test: ast.AST
    ) -> Optional[Tuple[str, Set[str], bool, bool]]:
        """(msgvar, kinds, negated, len_guarded) for a kind test, else None."""
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            found = None
            guarded = False
            for v in test.values:
                sub = self._kind_test(v)
                if sub is not None and found is None:
                    found = sub
                if self._mentions_len(v, sub[0] if sub else None):
                    guarded = True
            if found is not None:
                msgvar, kinds, neg, g = found
                return (msgvar, kinds, neg, g or guarded)
            return None
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            sub = self._kind_test(test.operand)
            if sub is not None:
                msgvar, kinds, neg, g = sub
                return (msgvar, kinds, not neg, g)
            return None
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            op = test.ops[0]
            left, right = test.left, test.comparators[0]
            if isinstance(op, (ast.Eq, ast.NotEq)):
                for a, b in ((left, right), (right, left)):
                    msgvar = self._kind_expr(a)
                    k = _const_str(b)
                    if msgvar is not None and k is not None:
                        return (msgvar, {k}, isinstance(op, ast.NotEq), False)
            if isinstance(op, (ast.In, ast.NotIn)):
                msgvar = self._kind_expr(left)
                if msgvar is not None and isinstance(
                    right, (ast.Tuple, ast.List, ast.Set)
                ):
                    kinds = {
                        s
                        for s in (_const_str(e) for e in right.elts)
                        if s is not None
                    }
                    if kinds:
                        return (msgvar, kinds, isinstance(op, ast.NotIn), False)
        return None

    def _kind_expr(self, node: ast.AST) -> Optional[str]:
        """The message var whose kind this expr reads: `msg[0]` or a
        `kind = msg[0]` alias name."""
        src = self._msg_sub0(node)
        if src is not None:
            return src
        if isinstance(node, ast.Name):
            return self.kind_alias.get(node.id)
        return None

    def _mentions_len(self, node: ast.AST, msgvar: Optional[str]) -> bool:
        """Does this expression read len(<msgvar>) (or a len alias)?"""
        for sub in ast.walk(node):
            src = self._len_of(sub)
            if src is not None and (msgvar is None or src == msgvar):
                return True
            if (
                isinstance(sub, ast.Name)
                and sub.id in self.len_alias
                and (msgvar is None or self.len_alias[sub.id] == msgvar)
            ):
                return True
        return False

    # -- block walking --

    def _walk_block(self, stmts: List[ast.stmt]) -> None:
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, ast.If):
                kt = self._kind_test(stmt.test)
                if kt is not None:
                    msgvar, kinds, negated, guarded = kt
                    if negated:
                        # `if msg[0] != "ready": ...return` — the REST of
                        # the block is the "ready" handler.
                        if _terminates(stmt.body):
                            self._check_handler(
                                msgvar, kinds, stmts[i + 1 :], guarded
                            )
                        self._walk_block(stmt.body)
                        self._walk_block(stmt.orelse)
                        continue
                    self._check_handler(msgvar, kinds, stmt.body, guarded)
                    self._walk_block(stmt.body)
                    self._walk_block(stmt.orelse)
                    continue
            for block in self._sub_blocks(stmt):
                self._walk_block(block)

    @staticmethod
    def _sub_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
        out = []
        for attr in ("body", "orelse", "finalbody"):
            block = getattr(stmt, attr, None)
            if isinstance(block, list) and not isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                out.append(block)
        for h in getattr(stmt, "handlers", ()) or ():
            out.append(h.body)
        return out

    # -- handler checking --

    def _check_handler(
        self,
        msgvar: str,
        kinds: Set[str],
        body: List[ast.stmt],
        pre_guarded: bool,
    ) -> None:
        schemas = _schemas()
        wire_kinds = sorted(k for k in kinds if k in schemas)
        if not wire_kinds:
            return
        lo = min(schemas[k][0] for k in wire_kinds)
        kind0 = wire_kinds[0]
        scope = self.s.qualname()
        self._scan_accesses(
            msgvar, kinds, wire_kinds, lo, kind0, scope, body, pre_guarded
        )

    def _scan_accesses(
        self,
        msgvar: str,
        kinds: Set[str],
        wire_kinds: List[str],
        lo: int,
        kind0: str,
        scope: str,
        body: List[ast.stmt],
        guarded: bool,
    ) -> None:
        schemas = _schemas()
        for stmt in body:
            # Exact tuple unpack: `_, wid, renv = msg` requires len(msg)
            # to be EXACTLY n — legal frames at any other schema arity
            # raise ValueError in the handler, not ProtocolError at the
            # boundary.
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], (ast.Tuple, ast.List))
                and isinstance(stmt.value, ast.Name)
                and stmt.value.id == msgvar
                and not guarded
            ):
                elts = stmt.targets[0].elts
                starred = any(isinstance(e, ast.Starred) for e in elts)
                if starred:
                    need = len(elts) - 2  # fixed extras before/after star
                    if need > lo:
                        self.s.add(
                            stmt.lineno,
                            f"{PASS}:recv-unpack:{self.s.rel}:{scope}:{kind0}",
                            f"{self.s.rel}:{stmt.lineno}: handler for "
                            f"{kind0!r} star-unpacks {need} fixed extra "
                            f"field(s) but the schema only guarantees {lo}",
                        )
                else:
                    need = len(elts) - 1
                    bad = [
                        k
                        for k in wire_kinds
                        if schemas[k][0] != need or schemas[k][1] != need
                    ]
                    if bad:
                        self.s.add(
                            stmt.lineno,
                            f"{PASS}:recv-unpack:{self.s.rel}:{scope}:{kind0}",
                            f"{self.s.rel}:{stmt.lineno}: handler for "
                            f"{bad[0]!r} exact-unpacks {need} extra field(s) "
                            f"but the schema allows [{schemas[bad[0]][0]}, "
                            f"{schemas[bad[0]][1] if schemas[bad[0]][1] is not None else 'inf'}] "
                            "— a legal frame at another arity raises in the "
                            "handler instead of rejecting at the boundary "
                            "(the ready-arity bug class)",
                        )
            # len-guarded regions: anything under a test that reads
            # len(msgvar) is assumed bounds-checked.
            if isinstance(stmt, ast.If) and self._mentions_len(
                stmt.test, msgvar
            ):
                self._scan_accesses(
                    msgvar, kinds, wire_kinds, lo, kind0, scope,
                    stmt.body, True,
                )
                self._scan_accesses(
                    msgvar, kinds, wire_kinds, lo, kind0, scope,
                    stmt.orelse, True,
                )
                continue
            # Everything else: walk expressions for subscripts.
            self._scan_exprs(stmt, msgvar, lo, kind0, scope, guarded)
            for block in _FuncRecv._sub_blocks(stmt):
                self._scan_accesses(
                    msgvar, kinds, wire_kinds, lo, kind0, scope,
                    block, guarded,
                )

    @staticmethod
    def _stmt_exprs(stmt: ast.stmt) -> List[ast.expr]:
        """A statement's IMMEDIATE expressions (not nested stmt bodies —
        those are walked separately so inner len-guards keep working)."""
        out: List[ast.expr] = []
        for _field, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                out.append(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        out.append(v)
                    elif isinstance(v, ast.withitem):
                        out.append(v.context_expr)
        return out

    def _scan_exprs(
        self,
        stmt: ast.stmt,
        msgvar: str,
        lo: int,
        kind0: str,
        scope: str,
        guarded: bool,
    ) -> None:
        if guarded:
            return
        exprs = self._stmt_exprs(stmt)
        nodes = [n for e in exprs for n in ast.walk(e)]
        skip: Set[int] = set()
        for node in nodes:
            if isinstance(node, ast.IfExp) and self._mentions_len(
                node.test, msgvar
            ):
                for sub in ast.walk(node.body):
                    skip.add(id(sub))
                for sub in ast.walk(node.orelse):
                    skip.add(id(sub))
            elif isinstance(node, ast.BoolOp):
                # `len(msg) > 4 and msg[4]` short-circuit guard
                guard_seen = False
                for v in node.values:
                    if self._mentions_len(v, msgvar):
                        guard_seen = True
                    elif guard_seen:
                        for sub in ast.walk(v):
                            skip.add(id(sub))
        for node in nodes:
            if id(node) in skip:
                continue
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == msgvar
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, int)
                and not isinstance(node.slice.value, bool)
                and node.slice.value > lo
            ):
                n = node.slice.value
                self.s.add(
                    node.lineno,
                    f"{PASS}:recv-arity:{self.s.rel}:{scope}:{kind0}:field{n}",
                    f"{self.s.rel}:{node.lineno}: handler for {kind0!r} "
                    f"reads {msgvar}[{n}] but the schema only guarantees "
                    f"{lo} extra field(s) — guard with len({msgvar}) or "
                    "raise the schema min (the ready-arity bug class)",
                )


# --- entry points -----------------------------------------------------------


def scan_file(path: str, rel: str) -> List[Violation]:
    if rel not in WIRE_MODULES and not rel.startswith("fixture"):
        return []
    tree = parse_file(path)
    if tree is None:
        return []
    send = _SendScanner(rel)
    send.visit(tree)
    recv = _RecvScanner(rel)
    recv.visit(tree)
    out = list(send.violations.values()) + list(recv.violations.values())
    return out


def check_native() -> List[Violation]:
    """wire_native.KIND_IDS must be a registered subset of SCHEMAS with
    wire-safe ids, and its exact payload arities must fit the schema."""
    from ray_tpu._private import wire_native

    schemas = _schemas()
    out: List[Violation] = []
    rel = "ray_tpu/_private/wire_native.py"
    seen_ids: Dict[int, str] = {}
    for kind, kid in sorted(wire_native.KIND_IDS.items()):
        if kind not in schemas:
            out.append(
                Violation(
                    PASS, rel, 0,
                    f"{PASS}:native-kind:{kind}",
                    f"{rel}: native kind {kind!r} (id {kid}) is not "
                    "registered in wire.SCHEMAS — its frames decode then "
                    "fail validation",
                )
            )
        if not (1 <= kid <= 0x7F):
            out.append(
                Violation(
                    PASS, rel, 0,
                    f"{PASS}:native-id:{kind}",
                    f"{rel}: native kind {kind!r} id {kid} is outside "
                    "1..0x7F (0x80 is pickle's discriminator byte)",
                )
            )
        if kid in seen_ids:
            out.append(
                Violation(
                    PASS, rel, 0,
                    f"{PASS}:native-dup:{kind}",
                    f"{rel}: native id {kid} is claimed by both "
                    f"{seen_ids[kid]!r} and {kind!r}",
                )
            )
        seen_ids.setdefault(kid, kind)
    for kind, arity in sorted(
        getattr(wire_native, "NATIVE_ARITIES", {}).items()
    ):
        spec = schemas.get(kind)
        if spec is None:
            continue  # already reported above
        lo, hi, _types = spec
        if arity < lo or (hi is not None and arity > hi):
            out.append(
                Violation(
                    PASS, rel, 0,
                    f"{PASS}:native-arity:{kind}",
                    f"{rel}: native codec packs {kind!r} at exact arity "
                    f"{arity}, but wire.SCHEMAS allows [{lo}, "
                    f"{hi if hi is not None else 'inf'}] — one of the two "
                    "tables is stale",
                )
            )
    return out
