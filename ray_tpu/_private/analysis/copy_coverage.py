"""Pass 9: bytes-per-copy counter coverage of the object plane.

The bytes-per-copy counters (telemetry.count_copy — object_copies /
object_copy_bytes{path=put|seal|pull|relay|spill|restore|promote|
arena_map}) are the object plane's HONESTY CHECK: ray_perf and the tier-1
broadcast tests assert "exactly one sealed copy per receiving node" off
their deltas.  That claim only holds while every byte-moving path in the
store / transfer-plane / arena modules ticks the counters — a future PR
adding a new transfer or staging path that skips count_copy silently
un-counts real copies, and the one-copy proofs keep passing while the
system does more work than they attest.

This pass catalogs every function in the object-plane modules that MOVES
BYTES — calls to recv_into / pack_into / os.write / sendfile /
copyfileobj, or a slice-assignment into a buffer (`view[a:b] = ...`, the
mmap/memoryview fill idiom) — and requires each to either call
telemetry.count_copy itself or be a REVIEWED allowlist entry whose
justification names the site that counts it (usually the single
fetch-side or OwnerStore-level counter).  Keys carry module + enclosing
function only, so unrelated edits don't churn the allowlist.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu._private.analysis.common import (
    Violation,
    parse_file,
    terminal_name,
)

PASS = "copy-coverage"

# The object-plane modules: every byte a user object moves through the
# runtime moves through one of these files.
COPY_MODULES = frozenset(
    {
        "ray_tpu/_private/store.py",
        "ray_tpu/_private/object_plane.py",
        "ray_tpu/_private/spill_storage.py",
        "ray_tpu/_native/arena.py",
    }
)

# Call attributes that move object bytes when invoked on anything.
_MOVER_ATTRS = frozenset(
    {"recv_into", "pack_into", "sendfile", "copyfileobj", "readinto"}
)


def _is_mover_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr in _MOVER_ATTRS:
            # struct.pack_into writes fixed-width header METADATA (board
            # watermarks), not object bytes.
            return not (
                func.attr == "pack_into"
                and terminal_name(func.value) == "struct"
            )
        # os.write(fd, buf) — the transfer plane's send syscall.
        if func.attr == "write" and terminal_name(func.value) == "os":
            return True
    elif isinstance(func, ast.Name) and func.id in ("pack_into",):
        return True
    return False


def _is_buffer_fill(node: ast.Assign) -> bool:
    """`view[a:b] = data` — the mmap/memoryview fill idiom (arena slot or
    tmpfs segment writes).  Plain index stores (`d[k] = v`) don't match:
    only slice targets."""
    for tgt in node.targets:
        if isinstance(tgt, ast.Subscript) and isinstance(tgt.slice, ast.Slice):
            return True
    return False


def _counts_copies(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr == "count_copy":
                return True
            if isinstance(f, ast.Name) and f.id == "count_copy":
                return True
    return False


class _Scanner(ast.NodeVisitor):
    def __init__(self, rel: str):
        self.rel = rel
        self.scope: List[str] = []
        self.violations: List[Violation] = []

    def qualname(self) -> str:
        return ".".join(self.scope) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def _visit_fn(self, node) -> None:
        self.scope.append(node.name)
        # Walk this function's OWN body only (nested defs get their own
        # verdicts — double-charging the parent would churn two allowlist
        # entries per site).
        moves = False
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(sub, ast.Call) and _is_mover_call(sub):
                moves = True
            elif isinstance(sub, ast.Assign) and _is_buffer_fill(sub):
                moves = True
            stack.extend(ast.iter_child_nodes(sub))
        if moves and not _counts_copies(node):
            key = f"{PASS}:{self.rel}:{self.qualname()}"
            self.violations.append(
                Violation(
                    PASS,
                    self.rel,
                    node.lineno,
                    key,
                    f"{self.rel}:{node.lineno}: {self.qualname()} moves "
                    "object bytes (recv_into/pack_into/os.write/buffer "
                    "fill) without ticking telemetry.count_copy — tick "
                    "the bytes-per-copy counters here, or allowlist with "
                    "a justification naming the site that counts this "
                    "path",
                )
            )
        self.generic_visit(node)
        self.scope.pop()

    visit_FunctionDef = _visit_fn  # type: ignore[assignment]
    visit_AsyncFunctionDef = _visit_fn  # type: ignore[assignment]


def scan_file(path: str, rel: str) -> List[Violation]:
    if rel not in COPY_MODULES:
        return []
    tree = parse_file(path)
    if tree is None:
        return []
    s = _Scanner(rel)
    s.visit(tree)
    return s.violations
