"""Shared plumbing for the concurrency analysis passes."""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, List, Optional, Tuple

# A `with` item (or acquire()/release() receiver) counts as a lock when its
# terminal name looks lock-ish.  Conditions constructed around a lock keep
# "lock" out of their names in this codebase (_available, _not_empty), so
# condition-wait idioms don't register as lock regions.
_LOCKISH = re.compile(r"lock|mutex", re.IGNORECASE)

# Dedicated wire-serialization locks: their entire purpose is wrapping one
# send/recv so concurrent frames don't interleave on a shared connection.
# A send under ONLY such a lock is the idiom working as designed, not a
# blocking-under-lock hazard (it still participates in lock-order).
IO_SERIALIZATION_LOCKS = frozenset(
    {"send_lock", "_send_lock", "conn_lock", "_conn_lock"}
)


class Violation:
    """One finding.  `key` is the stable allowlist identity: it contains
    no line numbers, so unrelated edits don't churn the allowlist."""

    __slots__ = ("pass_name", "rel", "line", "key", "message")

    def __init__(self, pass_name: str, rel: str, line: int, key: str, message: str):
        self.pass_name = pass_name
        self.rel = rel
        self.line = line
        self.key = key
        self.message = message

    def __repr__(self) -> str:
        return f"<Violation {self.key} @{self.rel}:{self.line}>"


def iter_py_files(root: str) -> List[Tuple[str, str]]:
    """(abspath, display-relpath) for every .py under root (or root itself).

    The display path is relative to root's PARENT (so scanning `ray_tpu/`
    yields `ray_tpu/_private/store.py`) — allowlist keys stay stable no
    matter the CWD the lint runs from."""
    root = os.path.abspath(root)
    parent = os.path.dirname(root)
    out: List[Tuple[str, str]] = []
    if os.path.isfile(root):
        return [(root, os.path.relpath(root, parent).replace(os.sep, "/"))]
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                out.append((p, os.path.relpath(p, parent).replace(os.sep, "/")))
    return out


def parse_file(path: str) -> Optional[ast.Module]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None


def dotted_name(expr: ast.AST) -> Optional[str]:
    """`self.state.lock` -> "self.state.lock"; None for non-name chains."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def is_lockish(expr: ast.AST) -> bool:
    t = terminal_name(expr)
    return bool(t) and bool(_LOCKISH.search(t))


def call_repr(call: ast.Call) -> str:
    name = dotted_name(call.func)
    if name is None:
        t = terminal_name(call.func)
        name = f"...{t}" if t else "<call>"
    return name
