"""Project-specific concurrency static analysis (SURVEY §5.2).

The reference hardens its C++ concurrency with clang thread-safety
annotations (GUARDED_BY) + TSAN in CI; this Python runtime gets the
equivalent as an AST lint over the package, run by tier-1 tests and
`scripts/ray_tpu_lint.py`.  Five passes:

  * blocking-under-lock (blocking.py) — calls from a catalog of blocking
    operations (time.sleep, conn.recv/sock.recv, .result(), wire
    send/recv, subprocess, faults.point delay-capable sites) made
    lexically inside a `with <lock>` body or between explicit
    acquire()/release();
  * lock-order (lock_order.py) — the per-module lock-acquisition graph
    from nested `with` statements plus same-module call edges; cycles are
    potential ABBA deadlock inversions;
  * fault-registry (fault_registry.py) — every faults.point("name") call
    site collected into a generated catalog
    (ray_tpu/_private/analysis/fault_points.txt), and every literal
    RAY_TPU_FAULT_SPEC / faults.configure() spec in tests+scripts
    validated against it (a typo'd spec silently injects nothing — false
    robustness);
  * hot-send (hot_send.py) — direct `conn.send(...)` calls in the hot
    streaming modules are reviewed allowlist entries: a new one must
    route through wire.BatchingConn or justify bypassing coalescing
    (silent regressions back to one-syscall-per-frame fail CI);
  * gcs-mutation (gcs_mutation.py) — the journaled GCS tables (actor /
    named-binding / job) may only be written through the mutators in
    gcs.py: a direct dict write elsewhere takes effect in memory but
    skips the durability journal, so the mutation silently would not
    survive a head bounce.

Existing, reviewed sites live in allowlist.txt with one-line
justifications; the lint fails only on NEW violations.  The runtime twin
of the static side is the opt-in lock watchdog
(ray_tpu/_private/lock_watchdog.py, RAY_TPU_LOCK_WATCHDOG=1).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ray_tpu._private.analysis.common import Violation, iter_py_files
from ray_tpu._private.analysis import (
    blocking,
    copy_coverage,
    fault_registry,
    gcs_mutation,
    hot_send,
    journal_coverage,
    lock_order,
    metric_names,
    span_names,
)
from ray_tpu._private.analysis import allowlist as allowlist_mod

PASSES = (
    "blocking-under-lock",
    "lock-order",
    "fault-registry",
    "hot-send",
    "gcs-mutation",
    "journal-coverage",
    "metric-names",
    "span-names",
    "copy-coverage",
)


class AnalysisResult:
    """All findings plus the allowlist split applied to them."""

    def __init__(self, violations: List[Violation], allowed: Dict[str, str]):
        self.violations = violations
        self.allowlist = allowed
        keys = {v.key for v in violations}
        self.new = [v for v in violations if v.key not in allowed]
        self.allowlisted = [v for v in violations if v.key in allowed]
        self.stale_allowlist = sorted(k for k in allowed if k not in keys)

    @property
    def ok(self) -> bool:
        return not self.new


def run_analysis(
    roots: Sequence[str],
    spec_roots: Optional[Sequence[str]] = None,
    allowlist_path: Optional[str] = None,
    catalog_path: Optional[str] = None,
    metric_catalog_path: Optional[str] = None,
    span_catalog_path: Optional[str] = None,
) -> AnalysisResult:
    """Run every pass over `roots` (package dirs or files).

    spec_roots: where fault-spec literals are validated (tests/scripts);
    catalog_path / metric_catalog_path / span_catalog_path: committed
    generated catalogs to check for staleness (None = skip, e.g. on
    fixture trees)."""
    files = []
    for root in roots:
        files.extend(iter_py_files(root))
    violations: List[Violation] = []
    for path, rel in files:
        violations.extend(blocking.scan_file(path, rel))
        violations.extend(lock_order.scan_file(path, rel))
        violations.extend(hot_send.scan_file(path, rel))
        violations.extend(gcs_mutation.scan_file(path, rel))
        violations.extend(journal_coverage.scan_file(path, rel))
        violations.extend(metric_names.scan_file(path, rel))
        violations.extend(copy_coverage.scan_file(path, rel))
    points = fault_registry.collect_points(files)
    if catalog_path is not None:
        violations.extend(fault_registry.check_catalog(points, catalog_path))
    metrics = metric_names.collect_metrics(files)
    violations.extend(metric_names.check_duplicates(metrics))
    if metric_catalog_path is not None:
        violations.extend(
            metric_names.check_catalog(metrics, metric_catalog_path)
        )
    spans = span_names.collect_spans(files)
    violations.extend(span_names.check_duplicates(spans))
    if span_catalog_path is not None:
        violations.extend(span_names.check_catalog(spans, span_catalog_path))
    spec_files = []
    for root in spec_roots or ():
        spec_files.extend(iter_py_files(root))
    # Specs validate against package points PLUS points the spec tree
    # itself visits (tests exercise the fault plane with synthetic
    # faults.point("p.x") calls; those are real points for their specs).
    known = dict(points)
    for name, locs in fault_registry.collect_points(spec_files).items():
        known.setdefault(name, []).extend(locs)
    violations.extend(fault_registry.validate_spec_files(spec_files, known))
    allowed = (
        allowlist_mod.load(allowlist_path) if allowlist_path and os.path.exists(allowlist_path)
        else {}
    )
    violations.sort(key=lambda v: (v.rel, v.line, v.key))
    return AnalysisResult(violations, allowed)
