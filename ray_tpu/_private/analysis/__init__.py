"""Project-specific concurrency static analysis (SURVEY §5.2).

The reference hardens its C++ concurrency with clang thread-safety
annotations (GUARDED_BY) + TSAN in CI; this Python runtime gets the
equivalent as an AST lint over the package, run by tier-1 tests and
`scripts/ray_tpu_lint.py`.  The original five passes:

  * blocking-under-lock (blocking.py) — calls from a catalog of blocking
    operations (time.sleep, conn.recv/sock.recv, .result(), wire
    send/recv, subprocess, faults.point delay-capable sites) made
    lexically inside a `with <lock>` body or between explicit
    acquire()/release();
  * lock-order (lock_order.py) — the per-module lock-acquisition graph
    from nested `with` statements plus same-module call edges; cycles are
    potential ABBA deadlock inversions;
  * fault-registry (fault_registry.py) — every faults.point("name") call
    site collected into a generated catalog
    (ray_tpu/_private/analysis/fault_points.txt), and every literal
    RAY_TPU_FAULT_SPEC / faults.configure() spec in tests+scripts
    validated against it (a typo'd spec silently injects nothing — false
    robustness);
  * hot-send (hot_send.py) — direct `conn.send(...)` calls in the hot
    streaming modules are reviewed allowlist entries: a new one must
    route through wire.BatchingConn or justify bypassing coalescing
    (silent regressions back to one-syscall-per-frame fail CI);
  * gcs-mutation (gcs_mutation.py) — the journaled GCS tables (actor /
    named-binding / job) may only be written through the mutators in
    gcs.py: a direct dict write elsewhere takes effect in memory but
    skips the durability journal, so the mutation silently would not
    survive a head bounce.

Later passes extend the same machinery beyond locks: journal-coverage,
metric-names, span-names, copy-coverage, and the protocol conformance
plane —

  * wire-schema (wire_schema.py) — send sites and recv dispatch handlers
    cross-checked against the hand-maintained wire.SCHEMAS table
    (unknown kinds, arity drift, handlers assuming more fields than the
    schema min guarantees — the historical ready-arity and refs_push bug
    classes), plus the wire_native.KIND_IDS table as a schema subset;
  * knob-registry (knob_registry.py) — every literal RAY_TPU_* env
    access must resolve to a declared knob, alias, or wiring name
    (config.py); typo'd knobs silently no-op.  Generated catalog:
    knob_names.txt; dead knobs (declared, never read) also fail.

Existing, reviewed sites live in allowlist.txt with one-line
justifications; the lint fails only on NEW violations.  The runtime
twins of the static side are the opt-in lock watchdog
(ray_tpu/_private/lock_watchdog.py, RAY_TPU_LOCK_WATCHDOG=1) and the
seeded wire fuzzer (scripts/wire_fuzz.py, tests/test_wire_fuzz.py).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

from ray_tpu._private.analysis.common import Violation, iter_py_files
from ray_tpu._private.analysis import (
    blocking,
    copy_coverage,
    fault_registry,
    gcs_mutation,
    hot_send,
    journal_coverage,
    knob_registry,
    lock_order,
    metric_names,
    span_names,
    wire_schema,
)
from ray_tpu._private.analysis import allowlist as allowlist_mod

PASSES = (
    "blocking-under-lock",
    "lock-order",
    "fault-registry",
    "hot-send",
    "gcs-mutation",
    "journal-coverage",
    "metric-names",
    "span-names",
    "copy-coverage",
    "wire-schema",
    "knob-registry",
)


class AnalysisResult:
    """All findings plus the allowlist split applied to them."""

    def __init__(
        self,
        violations: List[Violation],
        allowed: Dict[str, str],
        timings: Optional[Dict[str, float]] = None,
    ):
        self.violations = violations
        self.allowlist = allowed
        self.timings = timings or {}
        keys = {v.key for v in violations}
        self.new = [v for v in violations if v.key not in allowed]
        self.allowlisted = [v for v in violations if v.key in allowed]
        self.stale_allowlist = sorted(k for k in allowed if k not in keys)

    @property
    def ok(self) -> bool:
        return not self.new


def run_analysis(
    roots: Sequence[str],
    spec_roots: Optional[Sequence[str]] = None,
    allowlist_path: Optional[str] = None,
    catalog_path: Optional[str] = None,
    metric_catalog_path: Optional[str] = None,
    span_catalog_path: Optional[str] = None,
    knob_catalog_path: Optional[str] = None,
) -> AnalysisResult:
    """Run every pass over `roots` (package dirs or files).

    spec_roots: where fault-spec literals and knob env names are
    validated (tests/scripts); catalog_path / metric_catalog_path /
    span_catalog_path / knob_catalog_path: committed generated catalogs
    to check for staleness (None = skip, e.g. on fixture trees).
    Dead-knob detection is gated on knob_catalog_path too: fixture trees
    don't contain the package's knob readers."""
    timings: Dict[str, float] = {p: 0.0 for p in PASSES}

    def timed(pass_name, fn, *args):
        t0 = time.perf_counter()
        try:
            return fn(*args)
        finally:
            timings[pass_name] += time.perf_counter() - t0

    files = []
    for root in roots:
        files.extend(iter_py_files(root))
    violations: List[Violation] = []
    for path, rel in files:
        violations.extend(timed("blocking-under-lock", blocking.scan_file, path, rel))
        violations.extend(timed("lock-order", lock_order.scan_file, path, rel))
        violations.extend(timed("hot-send", hot_send.scan_file, path, rel))
        violations.extend(timed("gcs-mutation", gcs_mutation.scan_file, path, rel))
        violations.extend(timed("journal-coverage", journal_coverage.scan_file, path, rel))
        violations.extend(timed("metric-names", metric_names.scan_file, path, rel))
        violations.extend(timed("copy-coverage", copy_coverage.scan_file, path, rel))
        violations.extend(timed("wire-schema", wire_schema.scan_file, path, rel))
        violations.extend(timed("knob-registry", knob_registry.scan_file, path, rel))
    violations.extend(timed("wire-schema", wire_schema.check_native))
    points = timed("fault-registry", fault_registry.collect_points, files)
    if catalog_path is not None:
        violations.extend(
            timed("fault-registry", fault_registry.check_catalog, points, catalog_path)
        )
    metrics = timed("metric-names", metric_names.collect_metrics, files)
    violations.extend(timed("metric-names", metric_names.check_duplicates, metrics))
    if metric_catalog_path is not None:
        violations.extend(
            timed("metric-names", metric_names.check_catalog, metrics, metric_catalog_path)
        )
    spans = timed("span-names", span_names.collect_spans, files)
    violations.extend(timed("span-names", span_names.check_duplicates, spans))
    if span_catalog_path is not None:
        violations.extend(
            timed("span-names", span_names.check_catalog, spans, span_catalog_path)
        )
    if knob_catalog_path is not None:
        violations.extend(
            timed("knob-registry", knob_registry.check_dead_knobs, files)
        )
        violations.extend(
            timed("knob-registry", knob_registry.check_catalog, knob_catalog_path)
        )
    spec_files = []
    for root in spec_roots or ():
        spec_files.extend(iter_py_files(root))
    # Specs validate against package points PLUS points the spec tree
    # itself visits (tests exercise the fault plane with synthetic
    # faults.point("p.x") calls; those are real points for their specs).
    known = dict(points)
    for name, locs in fault_registry.collect_points(spec_files).items():
        known.setdefault(name, []).extend(locs)
    violations.extend(
        timed("fault-registry", fault_registry.validate_spec_files, spec_files, known)
    )
    for path, rel in spec_files:
        violations.extend(
            timed("knob-registry", knob_registry.scan_spec_file, path, rel)
        )
    allowed = (
        allowlist_mod.load(allowlist_path) if allowlist_path and os.path.exists(allowlist_path)
        else {}
    )
    violations.sort(key=lambda v: (v.rel, v.line, v.key))
    return AnalysisResult(violations, allowed, timings)
