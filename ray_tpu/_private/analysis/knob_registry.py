"""Pass 11: knob registry — every RAY_TPU_* env literal must be declared.

The config table (config.py _DEFS) is the single source of truth for
runtime knobs, env-overridable as RAY_TPU_<NAME>.  But nothing
cross-checked the literals: a typo'd env name in code or a test
(`RAY_TPU_WIRE_BATCH_BYTE`) silently no-ops — the exact failure mode the
fault-registry pass killed for fault specs.  This pass closes it:

  * unknown — a literal RAY_TPU_* env name in an ACCESS position
    (environ get/setdefault/pop/subscript/membership, env-dict keys,
    setenv calls) that is neither a knob env form, a declared alias
    (config._ENV_ALIASES), nor declared process wiring
    (config.WIRING_ENV) fails the lint;
  * bypass — a READ of a knob's env form outside config.py skips the
    resolution order (_system_config > env > default) and the type
    coercion config.get() gives; deliberate ones (pre-config boot reads,
    bench save/restore of the env form) carry allowlist justifications;
  * get-unknown — config.get("name") with an undeclared literal raises
    KeyError at runtime; the lint finds it before a rarely-exercised
    path does;
  * dead — a knob declared in _DEFS that no config.get("name") literal
    anywhere in the package reads is dead weight (or a sign the reader
    was renamed and the table wasn't).

The generated catalog (knob_names.txt, one `<ENV_NAME> <kind>` line,
kind in knob|alias|wiring) is the greppable inventory; staleness against
the committed file fails the lint like the other catalogs.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu._private.analysis.common import Violation, dotted_name, parse_file

PASS = "knob-registry"

_ENV_RE = re.compile(r"^RAY_TPU_[A-Z0-9_]+$")

def _config_receivers(tree: ast.Module) -> Set[str]:
    """Names the config MODULE is bound to in this file — derived from
    its imports, so a local dict that happens to be called `config`
    never false-positives."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("_private"):
                for alias in node.names:
                    if alias.name == "config":
                        out.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("_private.config") and alias.asname:
                    out.add(alias.asname)
    return out

CATALOG_HEADER = (
    "# Generated knob catalog — do not edit by hand.\n"
    "# Regenerate with: python scripts/ray_tpu_lint.py --fix-allowlist\n"
    "# One `<ENV_NAME> <kind>` per line; kind: knob (config._DEFS row),\n"
    "# alias (config._ENV_ALIASES back-compat name), wiring\n"
    "# (config.WIRING_ENV process-bootstrap plumbing, not a knob).\n"
)


def _tables() -> Tuple[Set[str], Set[str], Set[str], Set[str]]:
    """(knob_names, knob_env, alias_env, wiring_env) from config.py."""
    from ray_tpu._private import config

    knob_names = set(config._DEFS)
    knob_env = {f"RAY_TPU_{n.upper()}" for n in knob_names}
    alias_env = {a for t in config._ENV_ALIASES.values() for a in t}
    wiring_env = set(config.WIRING_ENV)
    return knob_names, knob_env, alias_env, wiring_env


class _Access:
    __slots__ = ("name", "line", "is_read")

    def __init__(self, name: str, line: int, is_read: bool):
        self.name = name
        self.line = line
        self.is_read = is_read


def _lit(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _environish(node: ast.AST) -> bool:
    """Is this expression os.environ (or a renamed import of it / os)?"""
    name = dotted_name(node)
    if name is None:
        return False
    return name.endswith("environ") or name in ("os", "_os", "_os2")


def _collect_accesses(tree: ast.Module) -> List[_Access]:
    """Every RAY_TPU_* string literal in an env ACCESS position.
    Mentions in docstrings/messages don't count; dict keys, setdefault,
    setenv and subscript writes count as plumbing (checked for typos but
    not as resolution bypasses)."""
    out: List[_Access] = []

    def env_name(node: ast.AST) -> Optional[str]:
        s = _lit(node)
        if s is not None and _ENV_RE.match(s):
            return s
        return None

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) else None
            if attr in ("get", "getenv", "setdefault", "pop") and node.args:
                s = env_name(node.args[0])
                if s is not None:
                    is_read = attr in ("get", "getenv") and _environish(
                        func.value
                    )
                    out.append(_Access(s, node.lineno, is_read))
            elif attr in ("setenv", "delenv") and node.args:
                # pytest monkeypatch plumbing in spec roots
                s = env_name(node.args[0])
                if s is not None:
                    out.append(_Access(s, node.lineno, False))
        elif isinstance(node, ast.Subscript):
            s = env_name(node.slice)
            if s is not None:
                is_read = isinstance(node.ctx, ast.Load) and _environish(
                    node.value
                )
                out.append(_Access(s, node.lineno, is_read))
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if k is None:
                    continue
                s = env_name(k)
                if s is not None:
                    out.append(_Access(s, node.lineno, False))
        elif isinstance(node, ast.Compare) and len(node.ops) == 1:
            if isinstance(node.ops[0], (ast.In, ast.NotIn)):
                s = env_name(node.left)
                if s is not None and _environish(node.comparators[0]):
                    out.append(_Access(s, node.lineno, True))
    return out


def _config_get_literals(tree: ast.Module) -> List[Tuple[str, int]]:
    """(knob_name, line) for every <config receiver>.get("literal")."""
    receivers = _config_receivers(tree)
    if not receivers:
        return []
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in receivers
            and node.args
        ):
            s = _lit(node.args[0])
            if s is not None:
                out.append((s, node.lineno))
    return out


def scan_file(path: str, rel: str) -> List[Violation]:
    """Per-file checks: unknown env names, knob-env bypass reads, and
    config.get of undeclared knobs.  config.py itself is the registry and
    is exempt from the bypass check (it IS the resolver)."""
    tree = parse_file(path)
    if tree is None:
        return []
    knob_names, knob_env, alias_env, wiring_env = _tables()
    declared = knob_env | alias_env | wiring_env
    out: List[Violation] = []
    seen: Set[str] = set()
    is_config = rel.endswith("_private/config.py")
    for acc in _collect_accesses(tree):
        if acc.name not in declared:
            key = f"{PASS}:unknown:{rel}:{acc.name}"
            if key not in seen:
                seen.add(key)
                out.append(
                    Violation(
                        PASS, rel, acc.line, key,
                        f"{rel}:{acc.line}: env var {acc.name!r} is neither "
                        "a declared knob (config._DEFS), an alias "
                        "(config._ENV_ALIASES), nor declared wiring "
                        "(config.WIRING_ENV) — a typo'd knob silently "
                        "no-ops",
                    )
                )
        elif (
            acc.is_read
            and acc.name in (knob_env | alias_env)
            and not is_config
        ):
            key = f"{PASS}:bypass:{rel}:{acc.name}"
            if key not in seen:
                seen.add(key)
                out.append(
                    Violation(
                        PASS, rel, acc.line, key,
                        f"{rel}:{acc.line}: reads knob env {acc.name!r} "
                        "directly, bypassing config.get() resolution "
                        "(_system_config > env > default) and type "
                        "coercion — use config.get, or justify in the "
                        "allowlist",
                    )
                )
    for name, line in _config_get_literals(tree):
        if name not in knob_names:
            key = f"{PASS}:get-unknown:{rel}:{name}"
            if key not in seen:
                seen.add(key)
                out.append(
                    Violation(
                        PASS, rel, line, key,
                        f"{rel}:{line}: config.get({name!r}) — no such knob "
                        "in config._DEFS; this raises KeyError when the "
                        "path runs",
                    )
                )
    return out


def scan_spec_file(path: str, rel: str) -> List[Violation]:
    """Spec roots (tests/scripts): unknown-name check only.  Tests read
    and set env freely — that's harness plumbing, not a bypass — but a
    typo'd knob name in a test silently tests the default."""
    tree = parse_file(path)
    if tree is None:
        return []
    _knob_names, knob_env, alias_env, wiring_env = _tables()
    declared = knob_env | alias_env | wiring_env
    out: List[Violation] = []
    seen: Set[str] = set()
    for acc in _collect_accesses(tree):
        if acc.name not in declared:
            key = f"{PASS}:unknown:{rel}:{acc.name}"
            if key not in seen:
                seen.add(key)
                out.append(
                    Violation(
                        PASS, rel, acc.line, key,
                        f"{rel}:{acc.line}: env var {acc.name!r} is not a "
                        "declared knob/alias/wiring name — the test or "
                        "script silently exercises the default",
                    )
                )
    return out


def check_dead_knobs(
    files: Sequence[Tuple[str, str]]
) -> List[Violation]:
    """Knobs declared in _DEFS that no config.get("name") literal in the
    package reads.  (Readers always go through config.get — children
    receive the env form but still resolve it there.)"""
    knob_names, _knob_env, _alias_env, _wiring_env = _tables()
    read: Set[str] = set()
    for path, rel in files:
        tree = parse_file(path)
        if tree is None:
            continue
        for name, _line in _config_get_literals(tree):
            read.add(name)
    out: List[Violation] = []
    rel = "ray_tpu/_private/config.py"
    for name in sorted(knob_names - read):
        out.append(
            Violation(
                PASS, rel, 0,
                f"{PASS}:dead:{name}",
                f"{rel}: knob {name!r} is declared but no "
                f"config.get({name!r}) literal in the package reads it — "
                "dead weight, or the reader was renamed without the table",
            )
        )
    return out


# --- catalog ----------------------------------------------------------------


def catalog_lines() -> List[str]:
    """`<ENV_NAME> <kind>` rows, sorted.  Derived from the config tables
    alone, so the catalog is deterministic for a given config.py."""
    _knob_names, knob_env, alias_env, wiring_env = _tables()
    rows = (
        [(n, "knob") for n in knob_env]
        + [(n, "alias") for n in alias_env]
        + [(n, "wiring") for n in wiring_env]
    )
    return [f"{n} {kind}" for n, kind in sorted(rows)]


def load_catalog(path: str) -> List[str]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        return [
            line.strip()
            for line in f
            if line.strip() and not line.lstrip().startswith("#")
        ]


def write_catalog(path: str) -> int:
    lines = catalog_lines()
    with open(path, "w", encoding="utf-8") as f:
        f.write(CATALOG_HEADER)
        for line in lines:
            f.write(line + "\n")
    return len(lines)


def check_catalog(path: str) -> List[Violation]:
    committed = load_catalog(path)
    actual = catalog_lines()
    if committed == actual:
        return []
    missing = sorted(set(actual) - set(committed))
    extra = sorted(set(committed) - set(actual))
    parts = []
    if missing:
        parts.append(f"missing {missing}")
    if extra:
        parts.append(f"stale {extra}")
    rel = os.path.basename(path)
    return [
        Violation(
            PASS, rel, 0,
            f"{PASS}:catalog:{rel}",
            f"{rel}: knob catalog is stale ({'; '.join(parts)}) — "
            "regenerate with scripts/ray_tpu_lint.py --fix-allowlist",
        )
    ]
