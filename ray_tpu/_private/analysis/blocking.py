"""Pass 1: blocking calls made while a lock is held.

Flags calls from a catalog of blocking operations (sleep, socket/pipe
recv+send, Future.result, subprocess, ray_tpu.get/wait, faults.point —
any injection point can carry a delay action) that occur LEXICALLY inside
a `with <lock>` body or between explicit lock.acquire()/lock.release()
statements.  The spill freed-race delete (PR 1) and the relayed-actor
requeue both had this shape; each cost a minutes-scale chaos soak to
surface, and this pass turns the shape into a pre-commit failure.

Scope rules:
  * nested function/lambda bodies reset the held-lock context (a closure
    defined under a lock runs later, not under it);
  * a send/recv wrapped ONLY by a dedicated wire-serialization lock
    (send_lock/conn_lock — see common.IO_SERIALIZATION_LOCKS) is the
    serialization idiom working as designed, and exempt;
  * `cond.wait()` on the held lock — or on a Condition CONSTRUCTED from
    the held lock (`self.c = threading.Condition(self.lock)` is resolved
    by a pre-scan) — is the condition idiom (wait releases the lock while
    blocked), and exempt;
  * `.wait(timeout=0)` is a poll, not a block, and exempt.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ray_tpu._private.analysis.common import (
    IO_SERIALIZATION_LOCKS,
    Violation,
    call_repr,
    dotted_name,
    is_lockish,
    parse_file,
    terminal_name,
)

PASS = "blocking-under-lock"

# Attribute calls that block (or can block) the calling thread.
_BLOCKING_ATTRS = frozenset(
    {
        "sleep",
        "recv",
        "recv_into",
        "recv_bytes",
        "recv_bytes_into",
        "readline",
        "readexactly",
        "accept",
        "result",
        "communicate",
        "send",
        "sendall",
        "send_bytes",
        "connect",
    }
)
_SEND_RECV_ATTRS = frozenset(
    {"send", "sendall", "send_bytes", "recv", "recv_into", "recv_bytes",
     "recv_bytes_into"}
)
_SUBPROCESS_FUNCS = frozenset({"Popen", "run", "call", "check_call", "check_output"})
# Receivers whose EVERY method is disk/network I/O (the pluggable spill
# backend may be an fsspec URI — a network call under the store lock
# stalls every store operation: the exact PR 1 soak-found bug shape).
_IO_RECEIVER_TERMS = frozenset({"_spill_storage", "spill_storage"})


def _is_zero_timeout(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout" and isinstance(kw.value, ast.Constant):
            return kw.value.value == 0
    # positional timeout=0 (e.g. wait([...], n, 0))
    for a in call.args:
        if isinstance(a, ast.Constant) and a.value == 0:
            return True
    return False


def _blocking_reason(
    call: ast.Call,
    held: List[Tuple[str, str]],
    cond_aliases: dict,
) -> Optional[str]:
    """Why this call blocks, or None when it is not in the catalog (or an
    exempt idiom).  `held` is [(full_name, terminal)] innermost-last;
    cond_aliases maps condition attrs to the lock they wrap."""
    func = call.func
    dotted = dotted_name(func)
    if isinstance(func, ast.Attribute):
        attr = func.attr
        recv_term = terminal_name(func.value)
        if dotted in ("faults.point", "_faults.point") or (
            attr == "point" and recv_term in ("faults", "_faults")
        ):
            return "fault-injection point (delay/crash-capable)"
        if dotted is not None and dotted.startswith("subprocess.") and attr in _SUBPROCESS_FUNCS:
            return "subprocess spawn/wait"
        if attr == "get" and isinstance(func.value, ast.Name) and func.value.id == "ray_tpu":
            return "blocking ray_tpu.get"
        if attr == "request":
            return "blocking control-plane request"
        if recv_term in _IO_RECEIVER_TERMS:
            return "spill-storage I/O (may be a network backend)"
        if attr == "spill" and recv_term == "self":
            return "spill I/O"
        if attr == "wait":
            if _is_zero_timeout(call):
                return None  # a poll, not a block
            recv_full = dotted_name(func.value)
            recv_full = cond_aliases.get(recv_full, recv_full)
            if recv_full is not None and any(full == recv_full for full, _t in held):
                return None  # condition-wait on the held lock releases it
            return "blocking wait"
        if attr in _BLOCKING_ATTRS:
            if attr in _SEND_RECV_ATTRS and held and all(
                t in IO_SERIALIZATION_LOCKS for _f, t in held
            ):
                return None  # the wire-serialization-lock idiom
            return f"blocking .{attr}()"
    elif isinstance(func, ast.Name):
        if func.id == "sleep":
            return "blocking sleep"
    return None


def _collect_condition_aliases(tree: ast.Module) -> dict:
    """`self.c = threading.Condition(self.lock)` (or module-level
    `c = threading.Condition(lock)`) -> {"self.c": "self.lock", ...}.
    One module-wide map: attr names are unique enough in practice, and a
    false alias merely suppresses a wait-under-lock finding for the
    condition idiom it exists to recognize."""
    aliases: dict = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        callee = dotted_name(node.value.func)
        if callee not in ("threading.Condition", "Condition"):
            continue
        if not node.value.args:
            continue
        wrapped = dotted_name(node.value.args[0])
        if wrapped is None:
            continue
        for target in node.targets:
            t = dotted_name(target)
            if t is not None:
                aliases[t] = wrapped
    return aliases


class _Scanner:
    def __init__(self, rel: str, cond_aliases: dict):
        self.rel = rel
        self.cond_aliases = cond_aliases
        self.violations: List[Violation] = []
        self.scope: List[str] = []  # class/function names
        self.held: List[Tuple[str, str]] = []  # (full, terminal), innermost last

    # -- scope plumbing ------------------------------------------------------

    def qualname(self) -> str:
        return ".".join(self.scope) or "<module>"

    def scan_module(self, tree: ast.Module) -> None:
        self._body(tree.body)

    # -- statement walking ---------------------------------------------------

    def _body(self, stmts: List[ast.stmt]) -> None:
        """Walk one statement list, tracking explicit acquire()/release()
        pairs at this nesting level (lexical region = acquire stmt ..
        release stmt, or end of the list when release is missing)."""
        explicit: List[str] = []  # full names acquired in this list
        for stmt in stmts:
            kind, lock = self._acquire_release_stmt(stmt)
            if kind == "acquire":
                self.held.append(lock)
                explicit.append(lock[0])
                continue
            if kind == "release":
                if explicit and explicit[-1] == lock[0]:
                    explicit.pop()
                    self.held.pop()
                continue
            self._stmt(stmt)
        for _ in explicit:  # unbalanced acquire: region ran to end of list
            self.held.pop()

    def _acquire_release_stmt(self, stmt: ast.stmt):
        if not isinstance(stmt, ast.Expr) or not isinstance(stmt.value, ast.Call):
            return None, None
        func = stmt.value.func
        if not isinstance(func, ast.Attribute) or func.attr not in ("acquire", "release"):
            return None, None
        if not is_lockish(func.value):
            return None, None
        full = dotted_name(func.value) or terminal_name(func.value) or "<lock>"
        return func.attr, (full, terminal_name(func.value) or full)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._nested_function(stmt)
            return
        if isinstance(stmt, ast.ClassDef):
            self.scope.append(stmt.name)
            saved, self.held = self.held, []
            self._body(stmt.body)
            self.held = saved
            self.scope.pop()
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._with(stmt)
            return
        # Generic statement: check expressions, then walk nested bodies.
        for expr in self._stmt_exprs(stmt):
            self._expr(expr)
        for name in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, name, None)
            if sub:
                self._body(sub)
        for handler in getattr(stmt, "handlers", ()):
            self._body(handler.body)

    def _stmt_exprs(self, stmt: ast.stmt):
        for field, value in ast.iter_fields(stmt):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.expr):
                yield value
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        yield v

    def _with(self, stmt) -> None:
        pushed = 0
        for item in stmt.items:
            self._expr(item.context_expr)  # evaluated before the acquire
            if is_lockish(item.context_expr):
                full = dotted_name(item.context_expr) or terminal_name(
                    item.context_expr
                ) or "<lock>"
                term = terminal_name(item.context_expr) or full
                self.held.append((full, term))
                pushed += 1
        self._body(stmt.body)
        for _ in range(pushed):
            self.held.pop()

    def _nested_function(self, stmt) -> None:
        self.scope.append(stmt.name)
        saved, self.held = self.held, []  # closures run later, not under the lock
        self._body(stmt.body)
        self.held = saved
        self.scope.pop()

    # -- expression walking --------------------------------------------------

    def _expr(self, expr: ast.expr) -> None:
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue  # lambda body runs later, not under the lock
            if isinstance(node, ast.Call):
                self._check_call(node)
            stack.extend(ast.iter_child_nodes(node))

    def _check_call(self, call: ast.Call) -> None:
        if not self.held:
            return
        reason = _blocking_reason(call, self.held, self.cond_aliases)
        if reason is None:
            return
        lock_full, lock_term = self.held[-1]
        name = call_repr(call)
        key = f"{PASS}:{self.rel}:{self.qualname()}:{lock_term}:{name}"
        self.violations.append(
            Violation(
                PASS,
                self.rel,
                call.lineno,
                key,
                f"{self.rel}:{call.lineno}: {reason} — {name}() called while "
                f"holding `{lock_full}` in {self.qualname()}",
            )
        )


def scan_file(path: str, rel: str) -> List[Violation]:
    tree = parse_file(path)
    if tree is None:
        return []
    s = _Scanner(rel, _collect_condition_aliases(tree))
    s.scan_module(tree)
    return s.violations
