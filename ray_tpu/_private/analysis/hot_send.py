"""Pass 4: direct conn sends on hot streaming paths.

The control-plane coalescing win (wire.BatchingConn — one physical write
per batch of reply/pub/done/refop/pdone/log frames) only holds while the
hot streaming modules route their sends through batching conns.  A future
PR adding `some_conn.send(...)` on one of these paths silently regresses
it back to one syscall + one receiver wakeup per frame — exactly the
steady-state cost PROFILE_r5.md measured.

This pass catalogs every `.send(...)` call on a conn-ish receiver inside
the hot modules.  Each existing site is a REVIEWED allowlist entry (most
are fine: the receiver is a BatchingConn at runtime, or a deliberately
unbatched handshake/one-shot conn); a NEW site fails the lint until the
author either routes it through the batching layer or justifies why this
send must bypass coalescing.

Keys carry module + enclosing scope + receiver (no line numbers), so
unrelated edits don't churn the allowlist.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu._private.analysis.common import (
    Violation,
    dotted_name,
    parse_file,
    terminal_name,
)

PASS = "hot-send"

# The hot streaming modules: every long-lived control conn they write to
# is (or feeds) a coalesced stream.  wire.py itself is the batching layer
# and pubsub.py holds no conns.
HOT_MODULES = frozenset(
    {
        "ray_tpu/_private/runtime.py",
        "ray_tpu/_private/worker_proc.py",
        "ray_tpu/_private/node_daemon.py",
        "ray_tpu/_private/peer.py",
        "ray_tpu/_private/driver_client.py",
        # io-shard fabric: every owned conn and the head-ward ctl channel
        # are coalesced streams; an unbatched send here regresses the
        # whole slice of conns the shard owns.
        "ray_tpu/_private/io_shard.py",
    }
)


def _conn_ish(expr: ast.AST) -> bool:
    t = terminal_name(expr)
    return bool(t) and "conn" in t.lower()


class _Scanner(ast.NodeVisitor):
    def __init__(self, rel: str):
        self.rel = rel
        self.scope: List[str] = []
        self.violations: List[Violation] = []

    def qualname(self) -> str:
        return ".".join(self.scope) or "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "send"
            and _conn_ish(func.value)
        ):
            recv = dotted_name(func.value) or terminal_name(func.value) or "<conn>"
            key = f"{PASS}:{self.rel}:{self.qualname()}:{recv}.send"
            self.violations.append(
                Violation(
                    PASS,
                    self.rel,
                    node.lineno,
                    key,
                    f"{self.rel}:{node.lineno}: direct {recv}.send() on a hot "
                    f"streaming path ({self.qualname()}) — route through the "
                    "batching layer (wire.BatchingConn / an existing batched "
                    "sender) or justify bypassing coalescing in the allowlist",
                )
            )
        self.generic_visit(node)


def scan_file(path: str, rel: str) -> List[Violation]:
    if rel not in HOT_MODULES:
        return []
    tree = parse_file(path)
    if tree is None:
        return []
    s = _Scanner(rel)
    s.visit(tree)
    return s.violations
