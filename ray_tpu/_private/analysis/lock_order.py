"""Pass 2: lock-acquisition order graph + deadlock-inversion cycles.

Builds a per-module directed graph over lock identities: an edge A -> B
means "B was acquired while A was held" — from lexically nested `with`
statements, and from call edges (a function called with A held acquires B,
directly or transitively through same-module callees).  A cycle in that
graph is a potential ABBA deadlock: two threads entering it from
different nodes can each hold the lock the other wants (the runtime.py
`self.lock -> state.lock` comment documents exactly this invariant by
hand; this pass checks every module's invariants mechanically).

Lock identity is textual, scoped to the module: `self.X` inside class C
becomes "C.X"; other dotted names keep their (self-stripped) spelling.
Re-acquisition of the same identity (RLock re-entry) never makes an edge.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.analysis.common import (
    Violation,
    dotted_name,
    is_lockish,
    parse_file,
    terminal_name,
)

PASS = "lock-order"


def _lock_id(expr: ast.AST, cls: Optional[str]) -> Optional[str]:
    full = dotted_name(expr)
    if full is None:
        full = terminal_name(expr)
        if full is None:
            return None
    if full == "self" or full.startswith("self."):
        rest = full[5:] or terminal_name(expr) or "lock"
        return f"{cls}.{rest}" if cls else rest
    return full


class _FuncInfo:
    __slots__ = ("qualname", "cls", "acquired", "nested_edges", "calls_under", "callees")

    def __init__(self, qualname: str, cls: Optional[str]):
        self.qualname = qualname
        self.cls = cls
        self.acquired: Set[str] = set()  # locks acquired anywhere in body
        # (held_lock, acquired_lock, line) from lexical nesting
        self.nested_edges: List[Tuple[str, str, int]] = []
        # (held_locks_tuple, callee_key, line) for calls made under a lock
        self.calls_under: List[Tuple[Tuple[str, ...], Tuple[str, str], int]] = []
        self.callees: Set[Tuple[str, str]] = set()  # every same-module call


class _Collector:
    """One pass over a module: per-function acquisition facts."""

    def __init__(self):
        self.funcs: Dict[Tuple[str, str], _FuncInfo] = {}  # (cls or "", name)

    def collect(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(stmt, None)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._function(sub, stmt.name)

    def _function(self, fn, cls: Optional[str]) -> None:
        qual = f"{cls}.{fn.name}" if cls else fn.name
        info = _FuncInfo(qual, cls)
        self.funcs[(cls or "", fn.name)] = info
        self._body(fn.body, cls, info, [])

    def _body(self, stmts, cls, info: _FuncInfo, held: List[str]) -> None:
        explicit = 0
        for stmt in stmts:
            lock = self._acquire_stmt(stmt, cls)
            if lock is not None:
                self._acquire(lock, cls, info, held, stmt.lineno)
                held.append(lock)
                explicit += 1
                continue
            if self._release_stmt(stmt, cls, held):
                held.pop()
                explicit -= 1
                continue
            self._stmt(stmt, cls, info, held)
        for _ in range(max(explicit, 0)):
            held.pop()

    def _acquire_stmt(self, stmt, cls) -> Optional[str]:
        call = self._lock_method_call(stmt, "acquire")
        return _lock_id(call.func.value, cls) if call is not None else None

    def _release_stmt(self, stmt, cls, held: List[str]) -> bool:
        call = self._lock_method_call(stmt, "release")
        if call is None:
            return False
        lid = _lock_id(call.func.value, cls)
        return bool(held) and held[-1] == lid

    @staticmethod
    def _lock_method_call(stmt, name: str) -> Optional[ast.Call]:
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == name
            and is_lockish(stmt.value.func.value)
        ):
            return stmt.value
        return None

    def _acquire(self, lock: str, cls, info: _FuncInfo, held: List[str], line: int) -> None:
        info.acquired.add(lock)
        for h in held:
            if h != lock:
                info.nested_edges.append((h, lock, line))

    def _stmt(self, stmt, cls, info: _FuncInfo, held: List[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs execute later; closures analyzed separately
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                self._exprs(item.context_expr, cls, info, held)
                lid = _lock_id(item.context_expr, cls) if is_lockish(item.context_expr) else None
                if lid is not None:
                    self._acquire(lid, cls, info, held, stmt.lineno)
                    held.append(lid)
                    pushed += 1
            self._body(stmt.body, cls, info, held)
            for _ in range(pushed):
                held.pop()
            return
        for field, value in ast.iter_fields(stmt):
            if field in ("body", "orelse", "finalbody"):
                continue
            if isinstance(value, ast.expr):
                self._exprs(value, cls, info, held)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.expr):
                        self._exprs(v, cls, info, held)
        for name in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, name, None)
            if sub:
                self._body(sub, cls, info, held)
        for handler in getattr(stmt, "handlers", ()):
            self._body(handler.body, cls, info, held)

    def _exprs(self, expr: ast.expr, cls, info: _FuncInfo, held: List[str]) -> None:
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Call):
                callee = self._callee_key(node, cls)
                if callee is not None:
                    info.callees.add(callee)
                    if held:
                        info.calls_under.append((tuple(held), callee, node.lineno))
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _callee_key(call: ast.Call, cls) -> Optional[Tuple[str, str]]:
        func = call.func
        if isinstance(func, ast.Name):
            return ("", func.id)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and cls
        ):
            return (cls, func.attr)
        return None


def _transitive_acquired(funcs: Dict[Tuple[str, str], _FuncInfo]) -> Dict[Tuple[str, str], Set[str]]:
    """Fixed point of "locks this function may acquire, including through
    same-module callees"."""
    closure = {k: set(v.acquired) for k, v in funcs.items()}
    for _ in range(len(funcs) + 1):
        changed = False
        for k, info in funcs.items():
            for callee in info.callees:
                extra = closure.get(callee)
                if extra and not extra <= closure[k]:
                    closure[k] |= extra
                    changed = True
        if not changed:
            break
    return closure


def _find_cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with >1 node (self-edges are never
    recorded, so singleton SCCs are acyclic)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (analysis runs over arbitrary user graphs)
        work = [(v, iter(sorted(edges.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(edges):
        if v not in index:
            strongconnect(v)
    return sccs


def scan_file(path: str, rel: str) -> List[Violation]:
    tree = parse_file(path)
    if tree is None:
        return []
    col = _Collector()
    col.collect(tree)
    closure = _transitive_acquired(col.funcs)

    edges: Dict[str, Set[str]] = {}
    examples: Dict[Tuple[str, str], Tuple[int, str]] = {}
    def add_edge(a: str, b: str, line: int, where: str) -> None:
        if a == b:
            return
        edges.setdefault(a, set()).add(b)
        edges.setdefault(b, set())
        examples.setdefault((a, b), (line, where))

    for key, info in col.funcs.items():
        for a, b, line in info.nested_edges:
            add_edge(a, b, line, info.qualname)
        for held, callee, line in info.calls_under:
            for b in closure.get(callee, ()):
                for a in held:
                    add_edge(a, b, line, f"{info.qualname} -> {'.'.join(filter(None, callee))}")

    out: List[Violation] = []
    for scc in _find_cycles(edges):
        detail = "; ".join(
            f"{a}->{b} at :{examples[(a, b)][0]} ({examples[(a, b)][1]})"
            for a in scc
            for b in sorted(edges.get(a, ()))
            if b in scc and (a, b) in examples
        )
        first_line = min(
            examples[(a, b)][0]
            for a in scc
            for b in edges.get(a, ())
            if b in scc and (a, b) in examples
        )
        key = f"{PASS}:{rel}:{'<->'.join(scc)}"
        out.append(
            Violation(
                PASS,
                rel,
                first_line,
                key,
                f"{rel}:{first_line}: potential lock-order inversion among "
                f"{{{', '.join(scc)}}}: {detail}",
            )
        )
    return out
