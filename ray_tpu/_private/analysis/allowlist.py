"""Allowlist of reviewed concurrency-lint findings.

Format — one entry per line, key and a MANDATORY one-line justification:

    <violation key> | <why this site is acceptable>

Keys carry no line numbers (pass:file:scope:detail), so unrelated edits
don't churn the file.  Hand-edit justifications freely; regenerate the
key set deliberately with `scripts/ray_tpu_lint.py --fix-allowlist`
(which preserves existing justifications and marks new keys TODO).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

HEADER = (
    "# Concurrency-lint allowlist — reviewed findings with justifications.\n"
    "# Format: <violation key> | <one-line justification>\n"
    "# Regenerate keys with: python scripts/ray_tpu_lint.py --fix-allowlist\n"
)

TODO_JUSTIFICATION = "TODO: justify"


def load(path: str) -> Dict[str, str]:
    entries: Dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            key, sep, why = line.partition("|")
            entries[key.strip()] = why.strip() if sep else ""
    return entries


def save(path: str, entries: Dict[str, str]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(HEADER)
        for key in sorted(entries):
            f.write(f"{key} | {entries[key] or TODO_JUSTIFICATION}\n")


def unjustified(entries: Dict[str, str]) -> List[str]:
    return sorted(
        k for k, why in entries.items()
        if not why or why == TODO_JUSTIFICATION
    )


def regenerate(
    existing: Dict[str, str], current_keys: List[str]
) -> Tuple[Dict[str, str], List[str], List[str]]:
    """(new entries, added keys, dropped keys): current violations become
    the key set; justifications survive for keys that persist."""
    new = {
        k: existing.get(k, TODO_JUSTIFICATION) for k in current_keys
    }
    added = sorted(set(current_keys) - set(existing))
    dropped = sorted(set(existing) - set(current_keys))
    return new, added, dropped
