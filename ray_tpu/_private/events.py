"""Structured cluster events: severity + source, durably appended.

ray: src/ray/util/event.h:102 (EventManager) + event.proto — components
RAY_EVENT important transitions (node death, worker OOM kills, actor
restarts) into per-source event files that operators grep after the fact.
Here one JSONL file per session (`events.jsonl` in the session dir) plus a
bounded in-memory ring for the state API / dashboard.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")


class EventLog:
    """Append-only structured event sink (one per runtime)."""

    def __init__(self, path: Optional[str], ring_size: int = 1000):
        self._path = path
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring_size)
        self._f = None
        if path:
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                self._f = open(path, "a", buffering=1)  # line-buffered
            except OSError:
                self._f = None

    def emit(
        self,
        severity: str,
        source: str,
        message: str,
        **fields: Any,
    ) -> None:
        """Record one event; never raises (observability must not take the
        control plane down)."""
        if severity not in SEVERITIES:
            severity = "INFO"
        ev = {
            "timestamp": time.time(),
            "severity": severity,
            "source": source,
            "message": message,
            **fields,
        }
        with self._lock:
            self._ring.append(ev)
            if self._f is not None:
                try:
                    self._f.write(json.dumps(ev, default=str) + "\n")
                except (OSError, ValueError):
                    pass
        # Mirror into the flight-recorder ring (telemetry.py): a crash
        # dump carries the control-plane transitions this process saw.
        try:
            from ray_tpu._private import telemetry

            telemetry.note(
                "event", severity=severity, source=source, message=message
            )
        except Exception:
            pass

    def recent(
        self, limit: int = 100, severity: Optional[str] = None,
        source: Optional[str] = None,
    ) -> List[Dict]:
        with self._lock:
            evs = list(self._ring)
        if severity:
            severity = str(severity).upper()  # curl users type lowercase
            if severity not in SEVERITIES:
                raise ValueError(
                    f"severity {severity!r} not one of {SEVERITIES}"
                )
            floor = SEVERITIES.index(severity)
            evs = [e for e in evs if SEVERITIES.index(e["severity"]) >= floor]
        if source:
            evs = [e for e in evs if e["source"] == source]
        if limit <= 0:  # evs[-0:] would be EVERYTHING, the opposite of "none"
            return []
        return evs[-limit:]

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
