"""Elastic capacity: the head-side demand-driven autoscaler.

Analogue of the reference's StandardAutoscaler reconcile loop
(ray: autoscaler/_private/autoscaler.py:168) against a pluggable
NodeProvider (ray: autoscaler/node_provider.py:13), rebuilt around this
repo's journaled control plane:

  * DEMAND comes from Runtime.demand_summary() — queued SchedulingKey
    buckets with wait-age, pending/RESHAPING placement-group bundles,
    serve replica targets — and is mirrored into the mutation journal
    (kind "demand", advisory) whenever it materially changes, so a
    post-mortem can replay WHY the fleet moved.
  * The RECONCILER runs on its own thread, OFF the runtime lock: every
    tick compares demand against the provider-managed fleet, launches
    within [autoscale_min_nodes, autoscale_max_nodes] after the
    autoscale_up_wait_s hysteresis, and drains nodes idle past
    autoscale_idle_s back toward the floor.
  * Node lifecycle (REQUESTED -> STARTING -> ACTIVE -> DRAINING ->
    DEPARTED) is journaled by the runtime (kind "node_lifecycle") and
    replayed across head bounces; per-transition wall clock lands in the
    autoscale_seconds{stage=...} histogram.  All TIMING here is
    head-local monotonic state — never journaled — so a restarted head
    re-arms fresh windows instead of acting on stale clocks.
  * Scale-DOWN is the loss-proof drain protocol (runtime.py): DRAINING
    stops new leases, running tasks get drain_timeout_s to finish,
    sole-copy objects evacuate to the head store over the transfer
    plane, and only then does the daemon depart.  A node that dies
    mid-drain falls back to the ordinary death path (lineage/retry).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ray_tpu._private import faults, ids

__all__ = [
    "NodeProvider",
    "LocalProcessProvider",
    "Autoscaler",
    "attach_autoscaler",
]


class NodeProvider:
    """What the reconciler drives (ray: node_provider.py:13).  launch()
    must be NON-BLOCKING: it starts the node coming up and returns; the
    node is ACTIVE when its daemon registers with the head, and the
    reconciler times the gap out via autoscale_launch_timeout_s."""

    def launch(self, node_id: str) -> None:
        raise NotImplementedError

    def terminate(self, node_id: str) -> None:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError


class LocalProcessProvider(NodeProvider):
    """Spawns/kills real `node_daemon` processes on this machine — the
    test and single-host provider (the production analogue points the
    same interface at a cloud instance API).  Spawned procs are shared
    into Runtime._daemon_procs so head shutdown reaps them."""

    def __init__(
        self,
        runtime,
        num_cpus: float = 1.0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        store_root: Optional[str] = None,
    ):
        self._rt = runtime
        self.num_cpus = num_cpus
        self.resources = dict(resources or {})
        self.labels = dict(labels or {})
        self.store_root = store_root
        self._procs: Dict[str, object] = {}

    def launch(self, node_id: str) -> None:
        import json
        import subprocess
        import sys

        env = self._rt._child_env(
            {
                "RAY_TPU_NODE_CONFIG": json.dumps(
                    {
                        "node_id": node_id,
                        "session": self._rt.session_name,
                        "num_cpus": self.num_cpus,
                        "resources": self.resources,
                        "labels": self.labels,
                        "store_root": self.store_root,
                    }
                ),
            }
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_daemon"],
            env=env,
            close_fds=True,
        )
        self._procs[node_id] = proc
        self._rt._daemon_procs[node_id] = proc

    def terminate(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        self._rt._daemon_procs.pop(node_id, None)
        if proc is not None:
            try:
                proc.terminate()
            except OSError:
                pass

    def is_running(self, node_id: str) -> bool:
        proc = self._procs.get(node_id)
        return proc is not None and proc.poll() is None


class Autoscaler:
    """The reconcile loop.  One daemon thread; every mutation step takes
    the runtime lock briefly and re-checks — the loop itself never
    blocks under it (subprocess spawns and evacuation pulls are long)."""

    def __init__(self, runtime, provider: Optional[NodeProvider] = None):
        from ray_tpu._private import config

        self._rt = runtime
        self.provider = provider or LocalProcessProvider(runtime)
        self.min_nodes = config.get("autoscale_min_nodes")
        self.max_nodes = config.get("autoscale_max_nodes")
        self.interval_s = config.get("autoscale_interval_s")
        self.up_wait_s = config.get("autoscale_up_wait_s")
        self.idle_s = config.get("autoscale_idle_s")
        self.launch_timeout_s = config.get("autoscale_launch_timeout_s")
        self.drain_timeout_s = config.get("autoscale_drain_timeout_s")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Head-local monotonic bookkeeping — NEVER journaled (a bounced
        # head re-arms every window fresh; see node_lifecycle restore).
        self._requested_at: Dict[str, float] = {}
        self._idle_since: Dict[str, float] = {}
        self._drain: Dict[str, dict] = {}
        self._unmet_since: Optional[float] = None
        self._last_demand_key = None
        self._last_demand_t = 0.0
        self.ticks = 0  # observability for tests/soaks

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="raytpu-autoscaler"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self._rt._shutdown:
                return
            try:
                self.reconcile()
            except Exception:
                # The control loop must outlive any single bad tick; the
                # next tick re-reads the world.
                continue

    # -- one reconcile tick --------------------------------------------

    def reconcile(self) -> None:
        rt = self._rt
        now = time.monotonic()
        self.ticks += 1
        demand = rt.demand_summary()
        self._journal_demand(demand, now)
        with rt.lock:
            lifecycle = {
                nid: dict(rec) for nid, rec in rt.node_lifecycle.items()
            }
        for nid, rec in lifecycle.items():
            state = rec.get("state")
            if state in ("REQUESTED", "STARTING"):
                self._check_launch(nid, now)
            elif state == "ACTIVE":
                t0 = self._requested_at.pop(nid, None)
                if t0 is not None:
                    self._observe("launch", now - t0)
            elif state == "DRAINING":
                self._advance_drain(nid, now)
            elif state == "DEPARTED":
                self._requested_at.pop(nid, None)
                self._idle_since.pop(nid, None)
                self._drain.pop(nid, None)
        managed = {
            nid: rec
            for nid, rec in lifecycle.items()
            if rec.get("src") == "autoscaler"
            and rec.get("state") != "DEPARTED"
        }
        n = len(managed)
        unmet = bool(
            demand["queued_tasks"]
            or demand["pending_bundles"]
            or any(
                d.get("target", 0) > d.get("live", 0)
                for d in demand["serve_targets"].values()
            )
        )
        if n < self.min_nodes:
            for _ in range(self.min_nodes - n):
                self._launch_one("floor")
            self._unmet_since = None
            return
        if unmet:
            if self._unmet_since is None:
                self._unmet_since = now
            elif now - self._unmet_since >= self.up_wait_s:
                if n < self.max_nodes:
                    self._launch_one("demand")
                # Re-arm per launch: one node per hysteresis window, so a
                # slow-to-boot node doesn't trigger a launch stampede.
                self._unmet_since = now
            return
        self._unmet_since = None
        # Scale down: drain ONE idle node at a time back toward the floor
        # (serial drains keep the evacuation fan-in bounded).
        if n <= self.min_nodes or any(
            rec.get("state") == "DRAINING" for rec in managed.values()
        ):
            return
        for nid, rec in managed.items():
            if rec.get("state") != "ACTIVE":
                continue
            if not self._node_idle(nid):
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.setdefault(nid, now)
            if now - since >= self.idle_s:
                self._idle_since.pop(nid, None)
                rt.start_node_drain(nid)
                break

    # -- launches ------------------------------------------------------

    def _launch_one(self, reason: str) -> None:
        rt = self._rt
        nid = ids.node_id()
        if faults.ENABLED:
            faults.point("autoscale.launch", key=nid)
        with rt.lock:
            rt._set_node_lifecycle(nid, "REQUESTED", src="autoscaler")
        self._requested_at[nid] = time.monotonic()
        try:
            self.provider.launch(nid)
        except Exception:
            with rt.lock:
                rt._set_node_lifecycle(
                    nid, "DEPARTED", src="autoscaler", reason="launch-failed"
                )
            return
        with rt.lock:
            rt._set_node_lifecycle(nid, "STARTING", src="autoscaler")

    def _check_launch(self, nid: str, now: float) -> None:
        """Advance a REQUESTED/STARTING node: declare it failed when its
        process died pre-registration or the launch window expired (a
        head bounce re-arms the window — _requested_at is head-local)."""
        rt = self._rt
        with rt.lock:
            node = rt.state.nodes.get(nid)
            if node is not None and node.alive:
                # Providers that register in-process nodes (no daemon
                # hello) reach ACTIVE here; the daemon path flips it at
                # registration time.
                rt._set_node_lifecycle(nid, "ACTIVE")
                return
        t0 = self._requested_at.setdefault(nid, now)
        waited = now - t0
        if waited < 1.0:
            return  # give the spawn a beat before polling the provider
        dead = False
        try:
            dead = not self.provider.is_running(nid)
        except Exception:
            dead = False
        if dead or waited > self.launch_timeout_s:
            try:
                self.provider.terminate(nid)
            except Exception:
                pass
            self._requested_at.pop(nid, None)
            with rt.lock:
                rt._set_node_lifecycle(
                    nid, "DEPARTED",
                    reason="launch-died" if dead else "launch-timeout",
                )

    # -- drains --------------------------------------------------------

    def _node_idle(self, nid: str) -> bool:
        rt = self._rt
        with rt.lock:
            node = rt.state.nodes.get(nid)
            if node is None or not node.alive or node.draining:
                return False
            for h in rt.workers.values():
                if h.node_id != nid or h.state == "dead":
                    continue
                if h.current_task is not None or h.state == "actor":
                    return False
        return True

    def _advance_drain(self, nid: str, now: float) -> None:
        """One drain step for a DRAINING node: wait for running tasks
        (bounded), evacuate sole-copy objects, then depart.  Mid-drain
        death is detected here and simply abandoned — _on_daemon_death
        already flipped the lifecycle and lineage covers the bytes."""
        rt = self._rt
        st = self._drain.setdefault(nid, {"since": now})
        with rt.lock:
            node = rt.state.nodes.get(nid)
            gone = node is None or not node.alive
        if gone:
            # Died (or vanished across a head bounce) mid-drain.  If the
            # daemon is about to reconnect it will re-enter DRAINING via
            # registration; give it the launch window, then close the
            # record so it cannot dangle forever.
            if now - st["since"] > self.launch_timeout_s:
                self._drain.pop(nid, None)
                with rt.lock:
                    if (
                        rt.node_lifecycle.get(nid, {}).get("state")
                        == "DRAINING"
                    ):
                        rt._set_node_lifecycle(
                            nid, "DEPARTED", reason="lost-mid-drain"
                        )
            return
        busy = rt.node_busy_count(nid)
        if busy and now - st["since"] < self.drain_timeout_s:
            return  # running tasks get the drain window to finish
        if "quiesced_at" not in st:
            st["quiesced_at"] = now
            self._observe("drain_wait", now - st["since"])
        # Evacuate sole-copy objects (off-lock pulls into the head store).
        # Bounded per tick so the loop stays responsive; remaining objects
        # continue next tick.  The depart below happens ONLY on a clean
        # ledger or after the forced-depart deadline (2x drain window) —
        # then lineage/retry covers the loss like a node death.
        ev = rt.evacuate_node_objects(
            nid, deadline=time.monotonic() + self.drain_timeout_s
        )
        with rt.lock:
            node = rt.state.nodes.get(nid)
            if node is None or not node.alive:
                # Died UNDER the evacuation (its locations were purged,
                # so remaining==0 lies): the death path owns the record.
                return
        forced = now - st["since"] > 2 * self.drain_timeout_s
        if ev["remaining"] == 0 or forced:
            self._observe("evacuate", time.monotonic() - st["quiesced_at"])
            t_depart = time.monotonic()
            rt.depart_node(nid)
            self._observe("depart", time.monotonic() - t_depart)
            self._observe("total", time.monotonic() - st["since"])
            self._drain.pop(nid, None)

    # -- demand journal / telemetry ------------------------------------

    def _journal_demand(self, demand: dict, now: float) -> None:
        """Mirror a materially-changed demand summary into the journal
        (kind "demand", ADVISORY: restore ignores it — live queues are
        authoritative — it exists so a post-mortem journal read shows
        the demand the reconciler acted on).  Throttled to 1/s."""
        key = (
            demand["queued_tasks"],
            len(demand["pending_bundles"]),
            tuple(
                sorted(
                    (k, d.get("target", 0), d.get("live", 0))
                    for k, d in demand["serve_targets"].items()
                )
            ),
        )
        if key == self._last_demand_key or now - self._last_demand_t < 1.0:
            return
        self._last_demand_key = key
        self._last_demand_t = now
        self._rt._journal_append(
            ("demand", {
                "queued_tasks": demand["queued_tasks"],
                "max_wait_s": demand["max_wait_s"],
                "pending_bundles": len(demand["pending_bundles"]),
                "serve_targets": demand["serve_targets"],
            })
        )

    def _observe(self, stage: str, seconds: float) -> None:
        try:
            from ray_tpu._private import telemetry

            telemetry.autoscale_histogram().observe(
                max(seconds, 0.0), tags={"stage": stage}
            )
        except Exception:
            pass


def attach_autoscaler(runtime, provider: Optional[NodeProvider] = None):
    """Build + start an Autoscaler on `runtime` and flip the runtime into
    park-infeasible mode (the fleet may grow to fit parked tasks — ray's
    default posture when an autoscaler is present)."""
    a = Autoscaler(runtime, provider)
    runtime._autoscaler = a
    runtime.allow_pending_infeasible = True
    a.start()
    return a
