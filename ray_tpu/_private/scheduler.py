"""Resource scheduling: node selection policies + placement groups.

Analogue of the reference's two-level scheduler
(ray: src/ray/raylet/scheduling/cluster_resource_scheduler.h:44,
 cluster_task_manager.h:42). Policies mirror
ray: src/ray/raylet/scheduling/policy/:
  * hybrid  (hybrid_scheduling_policy.h:50)  -- prefer the head/local node
    until its utilization crosses a threshold, then least-utilized remote;
  * SPREAD  (spread_scheduling_policy.h)     -- round-robin over feasible;
  * node affinity (node_affinity_scheduling_policy.h);
  * placement-group bundles (bundle_scheduling_policy.h) with
    PACK/SPREAD/STRICT_PACK/STRICT_SPREAD -- and a TPU-native addition,
    "MESH": bundles must land on hosts forming a contiguous ICI sub-mesh
    (the reference has no topology-aware gang strategy; see SURVEY.md section 7).
"""

from __future__ import annotations

import itertools
import threading

from ray_tpu._private import lock_watchdog
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.gcs import GlobalState, NodeInfo, PlacementGroupInfo
from ray_tpu._private.task_spec import TaskSpec

def _feasible(node: NodeInfo, resources: Dict[str, float]) -> bool:
    return all(node.resources.get(k, 0.0) >= v for k, v in resources.items())


def _available(node: NodeInfo, resources: Dict[str, float]) -> bool:
    return all(node.available.get(k, 0.0) >= v - 1e-9 for k, v in resources.items())


def _utilization(node: NodeInfo) -> float:
    fracs = [
        1.0 - node.available.get(k, 0.0) / t
        for k, t in node.resources.items()
        if t > 0
    ]
    return max(fracs) if fracs else 0.0


class Scheduler:
    def __init__(self, state: GlobalState, head_node_id: str):
        from ray_tpu._private import config

        self.state = state
        self.head_node_id = head_node_id
        self._rr = itertools.count()
        self.lock = lock_watchdog.make_lock("Scheduler.lock", rlock=True)
        # resolved once: the knob is fixed by the time the runtime builds
        # its scheduler, and select_node is the dispatch hot path
        self._spread_threshold = config.get("scheduler_spread_threshold")
        # Set by the Runtime: deps -> {node_id: local-dep count} for
        # locality-aware placement (ray: locality_aware_leasing — the
        # lease policy prefers the node already holding the task's
        # arguments so big deps don't cross the wire).
        self.locality_fn = None
        # Set by the Runtime: its EventLog, so planning failures that need
        # operator attention (inconsistent mesh_coord labels) surface as
        # cluster events instead of a silent None.
        self.events = None
        # Label-inconsistency warnings are per offending node-set, not per
        # planning attempt: the pending-PG loop replans every tick.
        self._warned_dim_sets: set = set()

    # -- resource accounting -------------------------------------------------

    def acquire(self, node_id: str, resources: Dict[str, float]) -> bool:
        with self.lock:
            node = self.state.nodes.get(node_id)
            if node is None or not node.alive or not _available(node, resources):
                return False
            for k, v in resources.items():
                node.available[k] = node.available.get(k, 0.0) - v
            return True

    def release(self, node_id: str, resources: Dict[str, float]) -> None:
        with self.lock:
            node = self.state.nodes.get(node_id)
            if node is None:
                return
            for k, v in resources.items():
                node.available[k] = min(
                    node.available.get(k, 0.0) + v, node.resources.get(k, 0.0)
                )

    # -- node selection ------------------------------------------------------

    def select_node(self, spec: TaskSpec) -> Optional[str]:
        """Pick a node for the task; returns None if nothing can host it now.

        Raises ValueError if no node in the cluster is even feasible
        (infeasible task -- ray would park it and warn).
        """
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        resources = dict(spec.resources)
        strategy = spec.scheduling_strategy

        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            with self.lock:
                node = self.state.nodes.get(strategy.node_id)
                if node is None or not node.alive:
                    if strategy.soft:
                        return self._hybrid(resources, deps=spec.deps)
                    raise ValueError(f"affinity node {strategy.node_id} is dead")
                if node.draining:
                    # Drain-in-progress: no new placements land here.  Soft
                    # affinity re-drives elsewhere; hard affinity waits (the
                    # node either finishes draining and dies — then the dead
                    # branch above errors — or the drain is cancelled).
                    if strategy.soft:
                        return self._hybrid(resources, deps=spec.deps)
                    return None
                if _available(node, resources):
                    return node.node_id
                if strategy.soft:
                    return self._hybrid(resources, deps=spec.deps)
                return None

        if strategy == "SPREAD":
            return self._spread(resources)
        return self._hybrid(resources, deps=spec.deps)

    def _alive_feasible(self, resources) -> List[NodeInfo]:
        # Draining nodes are excluded from every candidate set: a scale-down
        # drain must converge, and new placements would re-busy it forever.
        # When the ONLY feasible nodes are draining the task is infeasible
        # for now — it parks under allow_pending (the autoscaler's demand
        # summary then shows it, prompting a scale-up) instead of landing on
        # capacity that is leaving.
        nodes = [
            n for n in self.state.alive_nodes()
            if not n.draining and _feasible(n, resources)
        ]
        if not nodes:
            raise ValueError(
                f"no node is feasible for resources {resources}; cluster has "
                f"{[{n.node_id: n.resources} for n in self.state.alive_nodes()]}"
            )
        return nodes

    def _hybrid(self, resources, deps=()) -> Optional[str]:
        with self.lock:
            nodes = self._alive_feasible(resources)
            # Locality first (ray: locality-aware leasing): among nodes
            # with capacity, one already holding this task's argument
            # objects beats the default head preference — re-reading a
            # local object is free, a cross-node pull is not.
            if deps and self.locality_fn is not None:
                counts = self.locality_fn(deps)
                if counts:
                    # Below-threshold guard: a node already busy past the
                    # spill point loses its locality pull — otherwise a
                    # fan-out sharing one driver-put ref would pile onto
                    # the head forever instead of spreading.
                    local = [
                        n for n in nodes
                        if counts.get(n.node_id)
                        and _available(n, resources)
                        and _utilization(n) < self._spread_threshold
                    ]
                    if local:
                        return min(
                            local,
                            key=lambda n: (-counts[n.node_id], _utilization(n)),
                        ).node_id
            # Prefer head node while below threshold, like ray's hybrid policy
            # prefers the local node (hybrid_scheduling_policy.h:50).
            head = next((n for n in nodes if n.node_id == self.head_node_id), None)
            if head and _available(head, resources) and _utilization(head) < self._spread_threshold:
                return head.node_id
            avail = [n for n in nodes if _available(n, resources)]
            if not avail:
                return None
            return min(avail, key=_utilization).node_id

    def _spread(self, resources) -> Optional[str]:
        with self.lock:
            nodes = self._alive_feasible(resources)
            avail = [n for n in nodes if _available(n, resources)]
            if not avail:
                return None
            return avail[next(self._rr) % len(avail)].node_id

    # -- placement groups ----------------------------------------------------

    @staticmethod
    def is_pg_task(spec: TaskSpec) -> bool:
        from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

        return bool(spec.placement_group_id) or isinstance(
            spec.scheduling_strategy, PlacementGroupSchedulingStrategy
        )

    def _pg_for_spec(self, spec: TaskSpec):
        from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

        strategy = spec.scheduling_strategy
        pg_id = spec.placement_group_id
        bundle_index = spec.placement_group_bundle_index
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg_id = strategy.placement_group.id
            bundle_index = strategy.placement_group_bundle_index
        return pg_id, bundle_index

    def select_pg(self, spec: TaskSpec, resources) -> Optional[Tuple[str, int]]:
        """Pick (node, bundle) for a PG-scheduled task and acquire from the
        bundle's reserved capacity. Returns None if nothing fits right now."""
        pg_id, bundle_index = self._pg_for_spec(spec)
        with self.lock:
            pg = self.state.placement_groups.get(pg_id)
            if pg is None or pg.state != "CREATED":
                return None
            indices = (
                list(pg.bundle_nodes.keys())
                if bundle_index is None or bundle_index < 0
                else [bundle_index]
            )
            want = {k: v for k, v in resources.items() if v > 0}
            for idx in indices:
                avail = pg.bundle_available.get(idx, {})
                node = self.state.nodes.get(pg.bundle_nodes[idx])
                if node is None or not node.alive:
                    continue
                if all(avail.get(k, 0.0) >= v - 1e-9 for k, v in want.items()):
                    for k, v in want.items():
                        avail[k] = avail.get(k, 0.0) - v
                    return pg.bundle_nodes[idx], idx
            return None

    def release_pg(self, pg_id: str, bundle_index: int, resources) -> None:
        with self.lock:
            pg = self.state.placement_groups.get(pg_id)
            if pg is None or pg.state != "CREATED":
                return
            avail = pg.bundle_available.get(bundle_index)
            if avail is None:
                return
            cap = pg.bundles[bundle_index]
            for k, v in resources.items():
                if v > 0:
                    avail[k] = min(avail.get(k, 0.0) + v, cap.get(k, 0.0))

    def reserve_placement_group(self, pg: PlacementGroupInfo) -> bool:
        """2-phase-commit-lite bundle reservation
        (ray: gcs_placement_group_scheduler.cc): all-or-nothing acquire."""
        with self.lock:
            if pg.state == "REMOVED":
                # Reshape sweep racing remove_placement_group: the removal
                # wins, the sweep must not resurrect the gang.
                return False
            assignment = self._plan_bundles(pg)
            if assignment is None:
                return False
            acquired: List[tuple] = []
            for idx, node_id in assignment.items():
                if self.acquire(node_id, pg.bundles[idx]):
                    acquired.append((node_id, pg.bundles[idx]))
                else:  # rollback
                    for nid, res in acquired:
                        self.release(nid, res)
                    return False
            pg.bundle_nodes = assignment
            pg.bundle_available = {
                i: dict(pg.bundles[i]) for i in range(len(pg.bundles))
            }
            # Journaled flip (PENDING|RESHAPING -> CREATED).  generation
            # bumps on EVERY successful reservation: a trainer that joined
            # generation g detects any subsequent re-reservation — the gang
            # it bootstrapped no longer exists even if the size matches.
            self.state.set_pg_state(
                pg.pg_id, "CREATED",
                generation=pg.generation + 1,
                lost_node=None, scale_up_ready=False, reshape_deadline=None,
            )
            return True

    def _plan_bundles(self, pg: PlacementGroupInfo) -> Optional[Dict[int, str]]:
        # Same drain exclusion as _alive_feasible: a gang reserved onto a
        # departing host would be torn down moments later.
        nodes = [n for n in self.state.alive_nodes() if not n.draining]
        strategy = pg.strategy
        bundles = pg.bundles

        def room(node, extra):
            """available minus already-planned extra on that node."""
            return all(
                node.available.get(k, 0.0) - extra.get(node.node_id, {}).get(k, 0.0) >= v - 1e-9
                for k, v in bundle.items()
            )

        if strategy in ("STRICT_PACK", "PACK", "MESH"):
            # try one node first
            for node in sorted(nodes, key=_utilization):
                ok = True
                extra: Dict[str, float] = {}
                for bundle in bundles:
                    if all(
                        node.available.get(k, 0.0) - extra.get(k, 0.0) >= v - 1e-9
                        for k, v in bundle.items()
                    ):
                        for k, v in bundle.items():
                            extra[k] = extra.get(k, 0.0) + v
                    else:
                        ok = False
                        break
                if ok:
                    return {i: node.node_id for i in range(len(bundles))}
            if strategy == "STRICT_PACK":
                return None
            if strategy == "MESH":
                # Multi-node MESH: hosts must form a contiguous axis-aligned
                # box of the ICI torus (node label "mesh_coord"), one bundle
                # per host, bundles ordered by host coordinate (lexicographic
                # — box contiguity, not a ring: adjacent ranks may still
                # cross a row boundary).  No fallback: a gang whose
                # collectives would cross non-adjacent hosts must FAIL to
                # place, not silently degrade (SURVEY.md §7 hard parts).
                return self._plan_mesh_box(bundles, nodes)
            # PACK falls through to best-effort spread.
        if strategy == "STRICT_SPREAD" and len(bundles) > len(nodes):
            return None
        assignment: Dict[int, str] = {}
        extra: Dict[str, Dict[str, float]] = {}
        used_nodes = set()
        for i, bundle in enumerate(bundles):
            cands = []
            for node in nodes:
                if strategy == "STRICT_SPREAD" and node.node_id in used_nodes:
                    continue
                if room(node, extra):
                    cands.append(node)
            if not cands:
                return None
            node = min(cands, key=_utilization)
            assignment[i] = node.node_id
            used_nodes.add(node.node_id)
            e = extra.setdefault(node.node_id, {})
            for k, v in bundle.items():
                e[k] = e.get(k, 0.0) + v
        return assignment

    def _plan_mesh_box(
        self, bundles: List[Dict[str, float]], nodes: List[NodeInfo]
    ) -> Optional[Dict[int, str]]:
        """Find len(bundles) hosts whose mesh_coord labels form a contiguous
        axis-aligned box, each with room for its bundle.

        The TPU-native analogue of STRICT_PACK: the reference packs for
        locality on one machine (bundle_scheduling_policy.h); on a pod,
        locality means ICI adjacency, which is a coordinate-box property.
        """
        n = len(bundles)
        by_coord: Dict[Tuple[int, ...], NodeInfo] = {}
        for node in nodes:
            raw = node.labels.get("mesh_coord")
            if raw is None:
                continue
            try:
                coord = tuple(int(x) for x in raw.split(","))
            except ValueError:
                continue
            by_coord[coord] = node
        if len(by_coord) < n:
            return None
        dims = {len(c) for c in by_coord}
        if len(dims) != 1:
            # Inconsistent label dimensionality ("0,1" next to "3") makes
            # every multi-host MESH gang unplaceable.  That is an operator
            # mistake, not a capacity shortfall — name the minority nodes
            # in a cluster event instead of failing silently forever.
            majority = max(
                dims, key=lambda k: sum(1 for c in by_coord if len(c) == k)
            )
            bad = sorted(
                by_coord[c].node_id for c in by_coord if len(c) != majority
            )
            if self.events is not None and frozenset(bad) not in self._warned_dim_sets:
                self._warned_dim_sets.add(frozenset(bad))
                self.events.emit(
                    "WARNING", "scheduler",
                    "MESH placement failing: inconsistent mesh_coord label "
                    "dimensionality across nodes",
                    nodes=bad, dims=sorted(dims),
                )
            return None
        d = dims.pop()
        # Torus extent per dim, inferred from the labeled population: hosts
        # at opposite label edges are ICI-adjacent through the wraparound
        # link, so a box may wrap (coords mod extent) — a gang can survive
        # losing a middle host by re-planning around it.
        extent = tuple(
            max(c[i] for c in by_coord) + 1 for i in range(d)
        )

        def factorizations(m: int, k: int):
            if k == 1:
                yield (m,)
                return
            for f in range(1, m + 1):
                if m % f == 0:
                    for rest in factorizations(m // f, k - 1):
                        yield (f,) + rest

        def frag_score(box: set) -> int:
            """Free labeled hosts torus-adjacent to the box: lower keeps
            the free region contiguous (a mid-mesh box fragments it)."""
            neighbors = set()
            for coord in box:
                for i in range(d):
                    for step in (-1, 1):
                        nb = list(coord)
                        nb[i] = (nb[i] + step) % extent[i]
                        nb = tuple(nb)
                        if nb in by_coord and nb not in box:
                            neighbors.add(nb)
            return len(neighbors)

        best: Optional[Dict[int, str]] = None
        best_score: Optional[int] = None
        for shape in sorted(factorizations(n, d)):
            if any(s > e for s, e in zip(shape, extent)):
                continue
            for anchor in sorted(by_coord):
                box = list(
                    itertools.product(
                        *[
                            [(a + i) % e for i in range(s)]
                            for a, s, e in zip(anchor, shape, extent)
                        ]
                    )
                )
                if any(c not in by_coord for c in box):
                    continue
                assignment: Dict[int, str] = {}
                ok = True
                for i, coord in enumerate(sorted(box)):
                    node = by_coord[coord]
                    if not _available(node, bundles[i]):
                        ok = False
                        break
                    assignment[i] = node.node_id
                if not ok:
                    continue
                score = frag_score(set(box))
                if best_score is None or score < best_score:
                    best, best_score = assignment, score
        return best

    def withdraw_gang(self, pg: PlacementGroupInfo, dead_node: str) -> bool:
        """Release a CREATED gang's reservations after a member host died
        (the dead host's share left with the node), leaving the PG ready
        to re-plan.  The caller flips state to RESHAPING (journaled)."""
        with self.lock:
            if pg.state != "CREATED":
                return False
            for idx, node_id in pg.bundle_nodes.items():
                if node_id != dead_node:
                    self.release(node_id, pg.bundles[idx])
            pg.bundle_nodes = {}
            pg.bundle_available = {}
            return True

    def can_plan_full(self, pg: PlacementGroupInfo) -> bool:
        """Would a full-size (orig_bundles) box be plannable right now,
        counting this gang's own reservations as free?  Read-only probe:
        reservations are returned to the pool, the plan is attempted, and
        the reservations re-acquired — all under the scheduler lock, so
        nothing can race into the temporarily-freed capacity."""
        with self.lock:
            if pg.state != "CREATED" or len(pg.bundles) >= len(pg.orig_bundles):
                return False
            held = [
                (node_id, pg.bundles[idx])
                for idx, node_id in pg.bundle_nodes.items()
            ]
            for node_id, res in held:
                self.release(node_id, res)
            try:
                probe = PlacementGroupInfo(
                    pg_id=pg.pg_id,
                    bundles=[dict(b) for b in pg.orig_bundles],
                    strategy=pg.strategy,
                )
                return self._plan_bundles(probe) is not None
            finally:
                for node_id, res in held:
                    self.acquire(node_id, res)

    def remove_placement_group(self, pg: PlacementGroupInfo) -> None:
        with self.lock:
            if pg.state == "CREATED":
                for idx, node_id in pg.bundle_nodes.items():
                    self.release(node_id, pg.bundles[idx])
            self.state.set_pg_state(pg.pg_id, "REMOVED", reshape_deadline=None)
