"""Cross-host object transfer: per-node object servers + chunked pull.

The reference moves objects between nodes with a push/pull object manager
attached to each raylet (ray: src/ray/object_manager/object_manager.h:117,
pull_manager.h:52, push_manager.h:29) and locates copies through an
ownership-based directory (ray: ownership_based_object_directory.h).  Here
the single-controller design makes the directory trivial — the driver
already sees every seal, so `Runtime.object_locations` IS the directory —
and transfer reduces to a pull protocol:

  * every node daemon runs an `ObjectServer` (a listener + a small bounded
    pool of serving threads) that streams the raw packed segment of any
    sealed object out of that node's local shm store in fixed-size chunks;
  * the driver serves its own (head-node) store through one-shot
    "object_fetch" connections on its main listener — no extra port;
  * a consumer that misses locally asks the owner, gets back a list of
    endpoints holding a copy, pulls from one into its OWN node store
    (allocate-then-fill, zero-copy into the arena when available), seals,
    and reports the new copy so siblings on its node skip the wire.

Admission control: the server bounds concurrent outbound transfers with a
semaphore (excess fetches queue on accept), and chunking keeps any single
send from pinning a whole object in socket buffers — the pull_manager's
"bounded in-flight bytes" intent at this design's scale.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Iterable, List, Optional, Tuple

from ray_tpu._private import config as _config
from ray_tpu._private import faults


def _chunk_size() -> int:
    return _config.get("object_transfer_chunk_bytes")


def stream_object(conn, read_raw: Callable[[str], Optional[tuple]], oid: str) -> None:
    """Stream one object out over an accepted transfer connection and close
    it.  ONE implementation of the wire protocol — the daemon ObjectServer
    and the head's handshake-thread handler both call this, so the framing
    cannot drift between them.

    read_raw(oid) -> (buffer, keepalive) | None; the buffer is the PACKED
    segment (header + payload + out-of-band buffers) exactly as stored, so
    the receiver can seal it byte-for-byte without re-serialization.
    (A sendfile() fast path was measured SLOWER than mmap write() on hot
    tmpfs pages — the fallback IS the fast path.)
    """
    try:
        # error -> the except below: the peer sees EOF mid-transfer and
        # retries another endpoint; crash kills the serving daemon here.
        if faults.ENABLED:
            faults.point("object.serve", key=oid)
        raw = read_raw(oid)
        if raw is None:
            conn.send(("missing",))
            return
        buf, _keepalive = raw
        total = len(buf)
        conn.send(("ok", total))
        fd = conn.fileno()
        chunk = _chunk_size()
        mv = memoryview(buf)
        off = 0
        while off < total:
            n = os.write(fd, mv[off : off + chunk])
            off += n
    except (OSError, EOFError, ValueError):
        pass  # peer vanished mid-transfer; it retries another endpoint
    finally:
        try:
            conn.close()
        except OSError:
            pass


def serve_fetch_conn(conn, read_raw: Callable[[str], Optional[tuple]]) -> None:
    """Recv one ("object_fetch", oid) request and stream the reply."""
    try:
        req = conn.recv()
    except (OSError, EOFError):
        try:
            conn.close()
        except OSError:
            pass
        return
    if not (isinstance(req, tuple) and req and req[0] == "object_fetch"):
        try:
            conn.close()
        except OSError:
            pass
        return
    stream_object(conn, read_raw, req[1])


class ObjectServer:
    """Per-node transfer server (daemon-side object manager).

    ray: object_manager.h:117 — ours serves only Pull (the driver's
    directory turns broadcast into N pulls; a dedicated push path is not
    needed when every consumer knows where copies live).
    """

    def __init__(
        self,
        read_raw: Callable[[str], Optional[tuple]],
        authkey: bytes,
        advertise_host: str,
        bind_host: str = "0.0.0.0",
    ):
        from multiprocessing.connection import Listener

        self._read_raw = read_raw
        self._sem = threading.BoundedSemaphore(
            _config.get("object_transfer_max_concurrency")
        )
        self.listener = Listener((bind_host, 0), backlog=64, authkey=authkey)
        self.endpoint: Tuple[str, int] = (advertise_host, self.listener.address[1])
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="raytpu-objserve"
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        from ray_tpu._private.netutil import set_nodelay
        from ray_tpu._private.wire import wrap

        while not self._shutdown:
            try:
                conn = self.listener.accept()
            except (OSError, EOFError):
                if self._shutdown:
                    return
                continue
            except Exception:
                continue  # stranger failed the auth challenge
            set_nodelay(conn)
            threading.Thread(
                target=self._serve_one, args=(wrap(conn),), daemon=True,
                name="raytpu-objserve-conn",
            ).start()

    def _serve_one(self, conn) -> None:
        with self._sem:
            serve_fetch_conn(conn, self._read_raw)

    def close(self) -> None:
        self._shutdown = True
        try:
            self.listener.close()
        except OSError:
            pass


def _connect_with_deadline(endpoint: Tuple[str, int], authkey: bytes, timeout: float):
    """TCP connect with a bound, then the stdlib mutual-auth handshake.

    The connect phase (SYN to a dead/partitioned host would otherwise hang
    for the kernel's minutes-long default) is bounded by a socket timeout;
    the auth exchange runs against a live accept loop that answers inline,
    so it completes or EOFs promptly once connected.
    """
    import socket
    from multiprocessing import connection as mpc

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(max(timeout, 0.01))
        s.connect(tuple(endpoint))
    except BaseException:
        s.close()
        raise
    s.setblocking(True)  # Connection does raw fd reads: no O_NONBLOCK
    conn = mpc.Connection(s.detach())
    try:
        mpc.answer_challenge(conn, authkey)
        mpc.deliver_challenge(conn, authkey)
    except BaseException:
        conn.close()
        raise
    from ray_tpu._private.wire import wrap

    return wrap(conn)


def _raw_chunks(conn, total: int, deadline: float):
    """Yield the raw transfer body as memoryview chunks read with
    recv_into on a reusable buffer — one kernel read per chunk, and the
    store's allocate-then-fill copies each chunk straight into the arena
    mmap (one copy total on the receive side)."""
    import socket
    import time

    s = socket.socket(fileno=os.dup(conn.fileno()))
    try:
        buf = bytearray(min(_chunk_size(), total) or 1)
        mv = memoryview(buf)
        got = 0
        while got < total:
            if faults.ENABLED:
                faults.point("object.chunk")  # error -> pull fails mid-body
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise OSError("object transfer timed out")
            s.settimeout(remaining)
            want = min(len(buf), total - got)
            try:
                n = s.recv_into(mv[:want])
            except socket.timeout as e:
                raise OSError("object transfer timed out") from e
            if n == 0:
                raise EOFError("transfer connection closed mid-body")
            got += n
            yield mv[:n]
    finally:
        s.close()


def _recv_body_into(conn, total: int, deadline: float, view) -> None:
    """Receive the raw transfer body DIRECTLY into `view` (the arena /
    tmpfs mmap): the kernel's copy-out is the only receive-side copy.
    At single-core loopback ceilings the staging bounce buffer this
    replaces was ~40% of broadcast wall time."""
    import socket
    import time

    s = socket.socket(fileno=os.dup(conn.fileno()))
    try:
        got = 0
        while got < total:
            if faults.ENABLED:
                faults.point("object.chunk")  # error -> pull fails mid-body
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise OSError("object transfer timed out")
            s.settimeout(remaining)
            try:
                n = s.recv_into(view[got:total])
            except socket.timeout as e:
                raise OSError("object transfer timed out") from e
            if n == 0:
                raise EOFError("transfer connection closed mid-body")
            got += n
    finally:
        s.close()


def fetch_object(
    endpoint: Tuple[str, int],
    authkey: bytes,
    oid: str,
    write_chunks: Optional[Callable[[str, int, Iterable[bytes]], None]] = None,
    timeout: Optional[float] = None,
    create_stream: Optional[Callable[[str, int, Callable], None]] = None,
) -> Optional[int]:
    """Pull one object from a remote ObjectServer endpoint.

    Preferred sink: create_stream(oid, total, fill) — the store allocates
    and hands `fill` a writable view that the socket recv_intos directly
    (ShmStore.create_from_stream / OwnerStore.ingest_stream).  Legacy
    sink: write_chunks(oid, total, chunk_iter) stages through a bounce
    buffer (ShmStore.create_from_chunks / OwnerStore.ingest_packed).
    Returns the transferred size, or None when the endpoint lacks a copy.
    Raises OSError/EOFError on transport failure or deadline overrun —
    caller tries the next endpoint.  Every blocking step is bounded by
    `timeout` (default: object_transfer_timeout_s), so a wedged server can
    never hang a get() forever.
    """
    import time

    if timeout is None:
        timeout = _config.get("object_transfer_timeout_s")
    deadline = time.monotonic() + timeout
    if faults.ENABLED:
        # error -> OSError out of the fetch: pull_from_any tries the next
        # copy, or the consumer falls to lineage reconstruction.
        faults.point("object.fetch", key=oid)
    conn = _connect_with_deadline(endpoint, authkey, timeout)
    try:
        conn.send(("object_fetch", oid))
        if not conn.poll(max(deadline - time.monotonic(), 0.0)):
            raise OSError("object transfer timed out awaiting header")
        hdr = conn.recv()
        if hdr[0] != "ok":
            return None
        total = int(hdr[1])
        if create_stream is not None:
            def fill(view):
                if view is None:
                    return  # already sealed locally; abandon the body
                _recv_body_into(conn, total, deadline, view)

            create_stream(oid, total, fill)
        else:
            write_chunks(oid, total, _raw_chunks(conn, total, deadline))
        return total
    finally:
        try:
            conn.close()
        except OSError:
            pass


def pull_from_any(
    endpoints: List[Tuple[str, int]],
    authkey: bytes,
    oid: str,
    write_chunks: Optional[Callable[[str, int, Iterable[bytes]], None]] = None,
    timeout: Optional[float] = None,
    create_stream: Optional[Callable[[str, int, Callable], None]] = None,
) -> Optional[int]:
    """Try each endpoint in order until one yields the object."""
    for ep in endpoints:
        try:
            n = fetch_object(
                tuple(ep), authkey, oid, write_chunks, timeout=timeout,
                create_stream=create_stream,
            )
        except (OSError, EOFError):
            continue  # node died / wedged / conn refused: next copy
        if n is not None:
            return n
    return None
