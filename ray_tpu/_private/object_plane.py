"""Cross-host object transfer: per-node object servers + pipelined pulls.

The reference moves objects between nodes with a push/pull object manager
attached to each raylet (ray: src/ray/object_manager/object_manager.h:117,
pull_manager.h:52, push_manager.h:29) and locates copies through an
ownership-based directory (ray: ownership_based_object_directory.h).  Here
the single-controller design makes the directory trivial — the driver
already sees every seal, so `Runtime.object_locations` IS the directory —
and transfer reduces to a pull protocol:

  * every node daemon runs an `ObjectServer` (a listener + a small bounded
    pool of serving threads) that streams the raw packed segment of any
    sealed object out of that node's local shm store in fixed-size chunks;
  * the driver serves its own (head-node) store through one-shot
    "object_fetch" connections on its main listener — no extra port;
  * a consumer that misses locally asks the owner, gets back a TRANSFER
    PLAN (a feed endpoint + sealed-source fallbacks), pulls into its OWN
    node store (allocate-then-fill, zero-copy into the arena when
    available), seals, and reports the new copy.

PIPELINED RELAY (PushManager-style chunk pipelining, SURVEY.md §2.1): a
node that is still PULLING an object re-serves the chunks it has already
landed.  The puller publishes progress through a transfer board
(store.py: a tiny mmap'd watermark file whose data region IS the pull's
receive buffer), and this module's relay server streams verified bytes
out of the board as the watermark advances — so an N-node broadcast forms
a chain/tree where every hop transfers concurrently instead of in
log2(N) staggered whole-object rounds.  Relay-served chunks carry a
per-chunk integrity checksum (`u32 len | bytes | u32 sum`, zlib.adler32);
a receiver verifies each chunk BEFORE advancing its own board, so a relay
never propagates a torn chunk downstream.  When a relay
dies mid-serve, the downstream receiver falls back to the sealed sources
in its plan (or re-asks the owner for a fresh plan) — re-plan, not wedge.

Admission control: the server bounds concurrent outbound transfers with a
semaphore (excess fetches queue on accept), and the owner's transfer plan
bounds the downstreams per feed (relay_fanout) — the pull_manager's
"bounded in-flight bytes" intent at this design's scale.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Callable, List, Optional, Tuple

from ray_tpu._private import config as _config
from ray_tpu._private import faults


def _chunk_size() -> int:
    return _config.get("object_transfer_chunk_bytes")


def _stall_timeout() -> float:
    return _config.get("relay_stall_timeout_s")


# Per-chunk integrity checksum for relay-served bytes.  adler32, not
# crc32: measured 1.5x faster per byte on the bench host where crc32
# costs as much as an extra memcpy of the chunk — the check exists to
# catch TORN reads out of a live board (a protocol/race bug), not
# adversarial corruption, and adler32 catches those with the same
# certainty at a fraction of the relay hop's CPU.
_chunk_sum = zlib.adler32


def _write_all(fd: int, mv: memoryview) -> None:
    off = 0
    total = len(mv)
    while off < total:
        off += os.write(fd, mv[off:total])


def stream_object(
    conn,
    read_raw: Callable[[str], Optional[tuple]],
    oid: str,
    read_board: Optional[Callable[[str], object]] = None,
) -> None:
    """Stream one object out over an accepted transfer connection and close
    it.  ONE implementation of the wire protocol — the daemon ObjectServer
    and the head's handshake-thread handler both call this, so the framing
    cannot drift between them.

    read_raw(oid) -> (buffer, keepalive) | None; the buffer is the PACKED
    segment (header + payload + out-of-band buffers) exactly as stored, so
    the receiver can seal it byte-for-byte without re-serialization.
    (A sendfile() fast path was measured SLOWER than mmap write() on hot
    tmpfs pages — the fallback IS the fast path.)

    read_board(oid) -> store.BoardReader | None: when the object is not
    sealed here but an in-flight pull's transfer board exists, the relay
    path serves the landed prefix mid-transfer (pipelined broadcast).
    """
    try:
        # error -> the except below: the peer sees EOF mid-transfer and
        # retries another endpoint; crash kills the serving daemon here.
        if faults.ENABLED:
            faults.point("object.serve", key=oid)
        raw = read_raw(oid)
        if raw is None and read_board is not None:
            # The owner's plan told the downstream THIS node is pulling,
            # but its puller may not have allocated yet (plans are handed
            # out before the first byte moves).  Wait briefly for the
            # board (or a seal) to appear instead of answering "missing"
            # — without this the whole chain degrades to source pulls in
            # the first milliseconds of a broadcast.
            import time as _time

            wait_until = _time.monotonic() + min(1.0, _stall_timeout())
            board = read_board(oid)
            while board is None and raw is None and _time.monotonic() < wait_until:
                _time.sleep(0.005)
                raw = read_raw(oid)
                if raw is None:
                    board = read_board(oid)
            if board is not None:
                try:
                    _stream_relay(conn, read_raw, board, oid)
                finally:
                    board.close()
                return
        if raw is None:
            conn.send(("missing",))
            return
        buf, _keepalive = raw
        total = len(buf)
        conn.send(("ok", total))
        fd = conn.fileno()
        chunk = _chunk_size()
        mv = memoryview(buf)
        off = 0
        while off < total:
            n = os.write(fd, mv[off : off + chunk])
            off += n
    except (OSError, EOFError, ValueError):
        pass  # peer vanished mid-transfer; it retries another endpoint
    finally:
        try:
            conn.close()
        except OSError:
            pass


def _stream_relay(conn, read_raw, board, oid: str) -> None:
    """Serve an object OUT OF AN IN-FLIGHT PULL: chunks up to the board's
    verified watermark stream immediately; the loop then chases the
    watermark as the upstream transfer lands more bytes.  Every chunk is
    framed `u32 len | bytes | u32 sum` (_chunk_sum) — the downstream
    receiver verifies before advancing its own board, so a torn read here
    can never propagate.  If the writer dies (board failed/gone without a
    seal) the conn just closes: the downstream falls back to a sealed
    source."""
    import time

    total = board.total
    conn.send(("relay", total, _chunk_size()))
    fd = conn.fileno()
    chunk = _chunk_size()
    off = 0
    deadline = time.monotonic() + _config.get("object_transfer_timeout_s")
    stall_at = time.monotonic() + _stall_timeout()
    while off < total:
        wm = board.watermark()
        if wm > off:
            n = min(chunk, wm - off)
            view = board.data(off, n)
            if faults.ENABLED:
                # error -> downstream sees EOF mid-relay and falls back to
                # a sealed source; crash kills the serving daemon exactly
                # here (the CHAOS_r10 mid-relay clause).
                faults.point("transfer.chunk_relay", key=oid)
            _write_all(fd, struct.pack("<I", n))
            _write_all(fd, view)
            _write_all(fd, struct.pack("<I", _chunk_sum(view)))
            off += n
            stall_at = time.monotonic() + _stall_timeout()
            continue
        if board.failed():
            return  # upstream pull aborted: close; downstream re-plans
        if board.gone():
            # Writer finished (sealed) or died.  A sealed copy serves the
            # remainder through the same crc framing; otherwise abort.
            raw = read_raw(oid)
            if raw is None:
                return
            buf, _keepalive = raw
            if len(buf) != total:
                return  # respilled/re-sealed different image: bail out
            mv = memoryview(buf)
            while off < total:
                n = min(chunk, total - off)
                view = mv[off : off + n]
                _write_all(fd, struct.pack("<I", n))
                _write_all(fd, view)
                _write_all(fd, struct.pack("<I", _chunk_sum(view)))
                off += n
            return
        now = time.monotonic()
        if now > deadline or now > stall_at:
            return  # wedged upstream: close; downstream falls back
        time.sleep(0.002)


def serve_fetch_conn(
    conn,
    read_raw: Callable[[str], Optional[tuple]],
    read_board: Optional[Callable[[str], object]] = None,
) -> None:
    """Recv one ("object_fetch", oid[, relay_ok]) request and stream the
    reply.  relay_ok (protocol extension, same-session peers only) lets
    the server answer from an in-flight pull's transfer board."""
    try:
        req = conn.recv()
    except (OSError, EOFError):
        try:
            conn.close()
        except OSError:
            pass
        return
    if not (isinstance(req, tuple) and req and req[0] == "object_fetch"):
        try:
            conn.close()
        except OSError:
            pass
        return
    relay_ok = len(req) > 2 and bool(req[2])
    stream_object(conn, read_raw, req[1], read_board if relay_ok else None)


class ObjectServer:
    """Per-node transfer server (daemon-side object manager).

    ray: object_manager.h:117 — ours serves Pull plus the mid-transfer
    RELAY path (the owner's transfer plan points downstream pullers at
    nodes that are still pulling; this server streams their boards)."""

    def __init__(
        self,
        read_raw: Callable[[str], Optional[tuple]],
        authkey: bytes,
        advertise_host: str,
        bind_host: str = "0.0.0.0",
        read_board: Optional[Callable[[str], object]] = None,
    ):
        from multiprocessing.connection import Listener

        self._read_raw = read_raw
        self._read_board = read_board
        self._sem = threading.BoundedSemaphore(
            _config.get("object_transfer_max_concurrency")
        )
        self.listener = Listener((bind_host, 0), backlog=64, authkey=authkey)
        self.endpoint: Tuple[str, int] = (advertise_host, self.listener.address[1])
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="raytpu-objserve"
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        from ray_tpu._private.netutil import set_nodelay
        from ray_tpu._private.wire import wrap

        while not self._shutdown:
            try:
                conn = self.listener.accept()
            except (OSError, EOFError):
                if self._shutdown:
                    return
                continue
            except Exception:
                continue  # stranger failed the auth challenge
            set_nodelay(conn)
            threading.Thread(
                target=self._serve_one, args=(wrap(conn),), daemon=True,
                name="raytpu-objserve-conn",
            ).start()

    def _serve_one(self, conn) -> None:
        with self._sem:
            serve_fetch_conn(conn, self._read_raw, self._read_board)

    def close(self) -> None:
        self._shutdown = True
        try:
            self.listener.close()
        except OSError:
            pass


def _connect_with_deadline(endpoint: Tuple[str, int], authkey: bytes, timeout: float):
    """TCP connect with a bound, then the stdlib mutual-auth handshake.

    The connect phase (SYN to a dead/partitioned host would otherwise hang
    for the kernel's minutes-long default) is bounded by a socket timeout;
    the auth exchange runs against a live accept loop that answers inline,
    so it completes or EOFs promptly once connected.
    """
    import socket
    from multiprocessing import connection as mpc

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(max(timeout, 0.01))
        s.connect(tuple(endpoint))
    except BaseException:
        s.close()
        raise
    s.setblocking(True)  # Connection does raw fd reads: no O_NONBLOCK
    conn = mpc.Connection(s.detach())
    try:
        mpc.answer_challenge(conn, authkey)
        mpc.deliver_challenge(conn, authkey)
    except BaseException:
        conn.close()
        raise
    from ray_tpu._private.wire import wrap

    return wrap(conn)


def _recv_exact(sock, view, deadline) -> None:
    """recv_into `view` completely; bounded by deadline AND the relay
    stall window (each successful recv resets neither — the per-call
    socket timeout is min(remaining, stall), so a wedged upstream fails
    in stall-time while a slow-but-flowing one keeps going)."""
    import socket as _socket
    import time

    got = 0
    total = len(view)
    while got < total:
        if faults.ENABLED:
            faults.point("object.chunk")  # error -> pull fails mid-body
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise OSError("object transfer timed out")
        sock.settimeout(min(remaining, _stall_timeout()))
        try:
            n = sock.recv_into(view[got:total])
        except _socket.timeout as e:
            raise OSError("object transfer stalled") from e
        if n == 0:
            raise EOFError("transfer connection closed mid-body")
        got += n


def _recv_body(conn, total: int, deadline: float, sink) -> None:
    """Classic sealed-source body: raw bytes straight into the sink's
    buffer (the kernel's copy-out is the only receive-side copy), with
    the sink's board advanced per recv so downstream relays chase us."""
    import socket

    s = socket.socket(fileno=os.dup(conn.fileno()))
    try:
        view = sink.view
        got = 0
        import time

        while got < total:
            if faults.ENABLED:
                faults.point("object.chunk")  # error -> pull fails mid-body
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise OSError("object transfer timed out")
            s.settimeout(remaining)
            try:
                n = s.recv_into(view[got:total])
            except socket.timeout as e:
                raise OSError("object transfer timed out") from e
            if n == 0:
                raise EOFError("transfer connection closed mid-body")
            got += n
            sink.advance(n)
    finally:
        s.close()


def _recv_relay_body(conn, total: int, deadline: float, sink) -> None:
    """Relay-framed body: `u32 len | bytes | u32 crc32` per chunk.  Each
    chunk lands straight in the sink's buffer, is crc-VERIFIED in place,
    and only then advances the board — a torn chunk from a dying relay
    raises here (the caller falls back) and is never re-served."""
    import socket

    s = socket.socket(fileno=os.dup(conn.fileno()))
    try:
        view = sink.view
        hdr = bytearray(4)
        got = 0
        while got < total:
            _recv_exact(s, memoryview(hdr), deadline)
            (n,) = struct.unpack("<I", hdr)
            if n == 0 or got + n > total:
                raise OSError(f"relay framing error: chunk {n} at {got}/{total}")
            _recv_exact(s, view[got : got + n], deadline)
            _recv_exact(s, memoryview(hdr), deadline)
            (want_crc,) = struct.unpack("<I", hdr)
            if _chunk_sum(view[got : got + n]) != want_crc:
                raise OSError(f"relay chunk crc mismatch at {got}/{total}")
            got += n
            sink.advance(n)
    finally:
        s.close()


def fetch_object(
    endpoint: Tuple[str, int],
    authkey: bytes,
    oid: str,
    start_pull: Callable[[str, int], object],
    timeout: Optional[float] = None,
) -> Optional[Tuple[int, str]]:
    """Pull one object from a remote endpoint into the local store.

    start_pull(oid, total) -> store.PullSink | None (None = a sibling pull
    already sealed it locally).  The sink's buffer is the receive target
    (zero staging), its board makes this pull relay-servable mid-flight,
    and commit() seals + publishes.  Returns (size, via) where via is
    "pull" (sealed source), "relay" (served from an in-flight transfer)
    or "local" (sealed under us — no bytes moved); None when the endpoint
    lacks a copy.  Raises OSError/EOFError on transport failure, crc
    mismatch, or deadline/stall overrun — caller tries the next endpoint.
    The single fetch-side count_copy site lives here: every landed
    transfer ticks exactly one `pull` or `relay` copy.
    """
    import time

    if timeout is None:
        timeout = _config.get("object_transfer_timeout_s")
    deadline = time.monotonic() + timeout
    if faults.ENABLED:
        # error -> OSError out of the fetch: pull_from_any tries the next
        # copy, or the consumer falls to lineage reconstruction.
        faults.point("object.fetch", key=oid)
    conn = _connect_with_deadline(endpoint, authkey, timeout)
    try:
        conn.send(("object_fetch", oid, 1))
        if not conn.poll(max(deadline - time.monotonic(), 0.0)):
            raise OSError("object transfer timed out awaiting header")
        hdr = conn.recv()
        if hdr[0] == "missing":
            return None
        if hdr[0] == "ok":
            via = "pull"
        elif hdr[0] == "relay":
            via = "relay"
        else:
            return None
        total = int(hdr[1])
        sink = start_pull(oid, total)
        if sink is None:
            return (total, "local")  # abandon the body; conn closes below
        try:
            if via == "relay":
                _recv_relay_body(conn, total, deadline, sink)
            else:
                _recv_body(conn, total, deadline, sink)
        except BaseException:
            sink.abort()
            raise
        sink.commit()
        from ray_tpu._private import telemetry as _telemetry

        _telemetry.count_copy(via, total)
        return (total, via)
    finally:
        try:
            conn.close()
        except OSError:
            pass


def pull_from_any(
    endpoints: List[Tuple[str, int]],
    authkey: bytes,
    oid: str,
    start_pull: Callable[[str, int], object],
    timeout: Optional[float] = None,
) -> Optional[Tuple[int, str]]:
    """Try each endpoint of the transfer plan in order until one yields
    the object: the plan's head is the assigned feed (possibly a relay),
    the tail the sealed-source fallbacks — a dead relay degrades to a
    direct source pull here, without a fresh owner round trip."""
    for ep in endpoints:
        try:
            r = fetch_object(tuple(ep), authkey, oid, start_pull, timeout=timeout)
        except (OSError, EOFError):
            continue  # node died / wedged / torn chunk: next copy
        if r is not None:
            return r
    return None
