"""Head io-shard fabric: multi-process accept/decode shards feeding the
single-writer GCS.

ray: src/ray/gcs/gcs_server/gcs_server.cc runs its gRPC services on a
thread pool — connection fan-in, HTTP/2 framing, and protobuf decode
happen on io threads while table mutations serialize onto the main
io_context.  PROFILE_r5.md measured the same boundary as this build's
scaling wall: the head's single Python io loop is only ~2% compute on one
core, so throughput scales exactly until the GIL saturates.  This module
moves the per-connection work OFF the head process:

  * the head keeps ONE listener + auth/handshake path (unchanged wire
    protocol — peers notice nothing); after the handshake it hands the
    live socket fd to an io-shard process chosen by conn-hash
    (SCM_RIGHTS over an AF_UNIX channel, netutil.send_conn_fd);
  * each shard runs its own epoll loop over its slice of the
    worker/daemon/driver conns and performs the expensive per-conn work
    there — protocol-v2 batch frame decode/encode, pickle, wire-stat
    counting — then forwards only DECODED control messages to the head
    as `("shard_fwd", conn_id, [msgs])` over one batched channel per
    shard, riding the same BatchingConn flush discipline as every other
    hot stream;
  * ALL state mutation stays in the head process: a shard never touches
    `state.*` (the gcs-mutation lint enforces forwarding-only — the
    journaled single-writer seam PR 4 centralized is exactly what makes
    this sharding safe); head replies/pubsub fan-out route back through
    the owning shard as `("shard_send", conn_id, msg)`.

Ordering invariant: a conn's frames are decoded by exactly one shard in
arrival order, appended to `shard_fwd` lists in that order, and the ctl
channel is one FIFO stream — so a conn's messages can never interleave
out of order across the shard boundary (tier-1 asserted in
tests/test_io_shard.py).

Failure model: a shard death closes its conns' fds, so every peer sees a
plain conn EOF and reconnects through the normal window — the fresh
handshake hashes onto a surviving (or head-respawned) shard.  The head
treats the shard's ctl EOF as an EOF of every conn it owned, which is
exactly what the sockets did.  `shard.accept` / `shard.forward` fault
points make both windows chaos-testable.

RAY_TPU_HEAD_IO_SHARDS=0 (default) keeps the classic in-process io loop:
single-core behavior is byte-for-byte unchanged.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import faults
from ray_tpu._private import lock_watchdog


def _kind(obj: Any) -> Optional[str]:
    if isinstance(obj, tuple) and obj and isinstance(obj[0], str):
        return obj[0]
    return None


# ---------------------------------------------------------------------------
# head-side: the stand-in the head's conn maps hold for a sharded conn


class ShardConnProxy:
    """What the head's conn maps (`_conn_to_worker`/`_conn_to_daemon`/
    `drivers`...) hold for a connection an io shard owns.  send() routes
    the frame out through the owning shard's batched ctl channel; the
    head's io loop never selects on a proxy (no fileno by design — a
    registration attempt fails loudly instead of busy-polling a pipe the
    shard owns).  A dead shard makes every proxy raise OSError at send,
    the same contract a broken BatchingConn has."""

    __slots__ = ("shard", "conn_id", "kind", "peer_id", "_closed")

    def __init__(self, shard: "IoShardHandle", conn_id: str, kind: str, peer_id: str):
        self.shard = shard
        self.conn_id = conn_id
        self.kind = kind
        self.peer_id = peer_id
        self._closed = False

    def send(self, obj: Any) -> None:
        if self._closed or not self.shard.alive:
            raise OSError(f"io shard {self.shard.idx} no longer owns conn "
                          f"{self.conn_id}")
        from ray_tpu._private import wire

        # Encode ONCE here (native codec or pickle): the shard writes the
        # body straight onto the peer socket without decoding — the v2
        # fabric pickled the message twice (once into shard_send, again
        # at the shard's re-send) and unpickled it once in between.
        self.shard.ctl_conn.send(
            ("shard_send", self.conn_id, wire.encode_body(obj))
        )

    def flush(self) -> None:
        """Push queued shard_send frames now (the ctl channel is a
        BatchingConn, so it also rides every wire.flush_dirty sweep)."""
        from ray_tpu._private import wire

        if self.shard.alive and self.shard.ctl_conn is not None:
            wire.flush_conn(self.shard.ctl_conn)

    def close(self) -> None:
        """Tell the owning shard to drop the real socket (best-effort:
        a dead shard already dropped it)."""
        if self._closed:
            return
        self._closed = True
        self.shard.conns.pop(self.conn_id, None)
        try:
            if self.shard.alive:
                self.shard.ctl_conn.send(("shard_close", self.conn_id))
        except OSError:
            pass

    # Defensive surface for code paths that probe conns generically: a
    # proxy never has locally-readable data (the shard reads the socket).
    def poll(self, timeout: float = 0.0) -> bool:
        return False

    def pending_frames(self) -> int:
        return 0

    @property
    def closed(self) -> bool:
        return self._closed or not self.shard.alive

    def __repr__(self) -> str:
        return (f"ShardConnProxy(shard={self.shard.idx}, "
                f"conn={self.conn_id}, kind={self.kind})")


class IoShardHandle:
    """Head-side record of one io-shard process: its Popen, the two
    channels (batched ctl for messages, raw fd channel for SCM_RIGHTS
    handoffs), and the proxies for every conn it currently owns."""

    def __init__(self, idx: int, proc):
        self.idx = idx
        self.proc = proc
        self.pid: Optional[int] = None
        self.ctl_conn = None   # wire.BatchingConn once the hello lands
        self.fd_conn = None    # raw AF_UNIX Connection (handoff channel)
        self.alive = False
        self.respawn_at = 0.0
        # conn_id -> ShardConnProxy for EOF fan-out on shard death.
        self.conns: Dict[str, ShardConnProxy] = {}
        # Serializes (meta, fd) pairs on the handoff channel: interleaved
        # writers would split a meta from its SCM_RIGHTS payload.
        self.fd_lock = lock_watchdog.make_lock("IoShardHandle.fd_lock")

    def adopt(self, conn_id: str, kind: str, peer_id: str, fd: int) -> None:
        """Ship one conn's fd to the shard (meta first, then the fd — the
        shard reads them as a pair).  Raises OSError if the shard died;
        the caller falls back through the shard-death path."""
        from ray_tpu._private import netutil

        with self.fd_lock:
            self.fd_conn.send(("handoff", conn_id, kind, peer_id))
            netutil.send_conn_fd(self.fd_conn, fd, self.pid)

    def __repr__(self) -> str:
        return (f"IoShardHandle(idx={self.idx}, pid={self.pid}, "
                f"alive={self.alive}, conns={len(self.conns)})")


def spawn_shard_process(idx: int, ctl_addr: str, authkey: bytes,
                        session: str) -> "IoShardHandle":
    """Launch one io-shard subprocess pointed at the head's AF_UNIX shard
    listener.  The handle starts not-alive; the head's shard accept loop
    flips it when the hello pair lands."""
    import subprocess

    env = os.environ.copy()
    env["RAY_TPU_IO_SHARD_CONFIG"] = json.dumps(
        {"index": idx, "ctl_addr": ctl_addr, "authkey": authkey.hex(),
         "session": session}
    )
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    paths = [pkg_root] + [p for p in sys.path if p]
    env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(paths))
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.io_shard"],
        env=env,
        close_fds=True,
    )
    return IoShardHandle(idx, proc)


# ---------------------------------------------------------------------------
# shard-side: the process entry + io loop

_DRAIN_CAP = 256  # physical reads per conn per round (decoded tails drain too)


class _ShardServer:
    """One io shard's event loop: epoll over the ctl/fd channels and every
    owned conn; decode inbound frames and forward them head-ward; apply
    head-routed sends; never touch any state table (forwarding only —
    lint-enforced)."""

    def __init__(self, idx: int, ctl_conn, fd_conn):
        import selectors

        from ray_tpu.util import metrics as _metrics

        self.idx = idx
        self.ctl_conn = ctl_conn    # BatchingConn to the head
        self.fd_conn = fd_conn      # raw handoff channel
        self._read_event = selectors.EVENT_READ
        self.sel = selectors.DefaultSelector()
        self.sel.register(ctl_conn, selectors.EVENT_READ)
        self.sel.register(fd_conn, selectors.EVENT_READ)
        self.owned: Dict[str, Any] = {}      # conn_id -> BatchingConn
        self.conn_ids: Dict[Any, str] = {}   # BatchingConn -> conn_id
        # Sends that raced ahead of their conn's fd handoff (ctl and fd
        # ride different channels, so cross-channel order is unguaranteed):
        # buffered until the handoff lands, dropped after a deadline.
        self.pending_sends: Dict[str, tuple] = {}  # conn_id -> (deadline, [msgs])
        self._last_push = time.monotonic()
        tag = {"shard": str(idx)}
        self.g_conns = _metrics.Gauge(
            "io_shard_conns",
            "connections this io shard currently owns",
            tag_keys=("shard",),
        ).set_default_tags(tag)
        self.c_forwarded = _metrics.Counter(
            "io_shard_forwarded_frames",
            "decoded control frames forwarded head-ward by this io shard",
            tag_keys=("shard",),
        ).set_default_tags(tag)
        self.c_fwd_batches = _metrics.Counter(
            "io_shard_forward_batches",
            "shard_fwd messages sent head-ward (frames/batches = per-conn "
            "coalescing on the forward channel)",
            tag_keys=("shard",),
        ).set_default_tags(tag)
        self.c_accepts = _metrics.Counter(
            "io_shard_accepts",
            "conn handoffs this io shard adopted from the head",
            tag_keys=("shard",),
        ).set_default_tags(tag)

    # -- lifecycle ---------------------------------------------------------

    def serve_forever(self) -> None:
        from ray_tpu._private import wire

        while True:
            try:
                events = self.sel.select(timeout=0.05)
            except OSError:
                continue
            for key, _ in events:
                obj = key.fileobj
                if obj is self.fd_conn:
                    self._accept_handoff()
                elif obj is self.ctl_conn:
                    self._drain_ctl()
                else:
                    self._drain_conn(obj)
            self._expire_pending()
            self._maybe_push_metrics()
            # Round end: every forwarded batch + routed send queued this
            # round goes out as one physical write per channel (the
            # flush-before-blocking-wait rule — select() is this loop's
            # blocking wait).
            wire.flush_dirty()

    def _head_gone(self) -> None:
        # The ctl channel died: the head bounced (or shut down).  Owned
        # conns are useless without it — exit and let every peer's conn
        # EOF drive its normal reconnect to the (restarted) head.
        raise SystemExit(0)

    # -- handoff path ------------------------------------------------------

    def _accept_handoff(self) -> None:
        from ray_tpu._private import netutil, wire

        try:
            meta = self.fd_conn.recv()
        except (EOFError, OSError):
            self._head_gone()
            return
        if meta[0] == "shutdown":
            raise SystemExit(0)
        _tag, conn_id, kind, _peer_id = meta
        try:
            raw = netutil.recv_conn_fd(self.fd_conn)
        except (EOFError, OSError):
            self._head_gone()
            return
        if faults.ENABLED:
            # crash = die with the fd adopted but unregistered (the
            # mid-handshake window: the peer sees a clean conn EOF and
            # must reconnect, never wedge); error/drop = refuse the
            # handoff (same peer-visible outcome, shard survives).
            try:
                if faults.point("shard.accept", key=kind) == "drop":
                    raw.close()
                    return
            except faults.InjectedFault:
                try:
                    raw.close()
                except OSError:
                    pass
                return
        conn = wire.batching(wire.wrap(raw))
        self.owned[conn_id] = conn
        self.conn_ids[conn] = conn_id
        self.sel.register(conn, self._read_event)
        self.c_accepts.inc()
        self.g_conns.set(float(len(self.owned)))
        queued = self.pending_sends.pop(conn_id, None)
        if queued is not None:
            for msg in queued[1]:
                self._deliver(conn_id, msg)

    # -- inbound: conn -> head --------------------------------------------

    def _drain_conn(self, conn) -> None:
        from ray_tpu._private import wire, wire_native

        conn_id = self.conn_ids.get(conn)
        if conn_id is None:
            return
        eof = False
        bodies: List[bytes] = []
        kinds: List[Any] = []
        # recv_bodies: raw sub-frame bodies, NO unpickle.  Native bodies
        # (the hot kinds) forward head-ward untouched — the head's marshal
        # decode is the only decode they ever get.  Pickled bodies (cold
        # kinds, pre-v3 shapes) still decode + schema-validate HERE, on
        # the shard pid, exactly like the v2 fabric — the expensive decode
        # never lands on the single-writer head.
        try:
            reads = 0
            while True:
                for body in conn.recv_bodies():
                    nk = wire_native.kind_of(body)
                    if nk is None:
                        try:
                            obj = wire.decode_body(body)
                        except wire.ProtocolError:
                            # Garbage-speaking peer: treat like a dead one
                            # (the decoded prefix still forwards).
                            eof = True
                            break
                        nk = _kind(obj)
                        body = wire.encode_body(obj)
                    if faults.ENABLED and faults.point(
                        "wire.recv", key=nk
                    ) == "drop":
                        # Per-sub-frame drop semantics, preserved across
                        # the raw-forward path (the head does not re-fire
                        # wire.recv for forwarded bodies).
                        continue
                    bodies.append(body)
                    kinds.append(nk)
                reads += 1
                if eof or reads >= _DRAIN_CAP or not conn.poll(0):
                    break
        except (EOFError, OSError):
            eof = True
        if bodies:
            self._forward(conn_id, bodies, kinds[0])
        if eof:
            self._close_conn(conn_id, report=True)

    def _forward(self, conn_id: str, bodies: List[bytes], first_kind) -> None:
        if faults.ENABLED:
            # drop = the forwarded batch is lost shard-side (peers'
            # retry/reconnect budgets must absorb it, like a wire drop);
            # crash = the soak's shard-kill: die with decoded frames in
            # hand — the conn fds die with us, peers reconnect.
            if faults.point("shard.forward", key=first_kind) == "drop":
                return
        try:
            self.ctl_conn.send(("shard_fwd", conn_id, bodies))
        except OSError:
            self._head_gone()
            return
        self.c_forwarded.inc(float(len(bodies)))
        self.c_fwd_batches.inc()

    # -- outbound: head -> conn -------------------------------------------

    def _drain_ctl(self) -> None:
        msgs: List[Any] = []
        try:
            msgs.append(self.ctl_conn.recv())
            while len(msgs) < _DRAIN_CAP and self.ctl_conn.poll(0):
                msgs.append(self.ctl_conn.recv())
            while self.ctl_conn.pending_frames():
                msgs.append(self.ctl_conn.recv())
        except (EOFError, OSError):
            self._head_gone()
            return
        for msg in msgs:
            if msg[0] == "shard_send":
                self._deliver(msg[1], msg[2])
            elif msg[0] == "shard_close":
                self._close_conn(msg[1], report=False)
            elif msg[0] == "shutdown":
                raise SystemExit(0)

    def _deliver(self, conn_id: str, body: bytes) -> None:
        """Write one head-encoded BODY to the owned conn — zero decode on
        the shard (the head already ran the codec; shard_send carries
        bytes)."""
        from ray_tpu._private import config as _config

        conn = self.owned.get(conn_id)
        if conn is None:
            deadline, queued = self.pending_sends.setdefault(
                conn_id,
                (time.monotonic() + _config.get("io_shard_pending_send_s"), []),
            )
            queued.append(body)
            return
        try:
            conn.send_body(body)
        except OSError:
            # Dead socket discovered at send: same as an EOF on read.
            self._close_conn(conn_id, report=True)

    def _close_conn(self, conn_id: str, report: bool) -> None:
        conn = self.owned.pop(conn_id, None)
        self.pending_sends.pop(conn_id, None)
        if conn is not None:
            self.conn_ids.pop(conn, None)
            try:
                self.sel.unregister(conn)
            except (KeyError, ValueError, OSError):
                pass
            try:
                conn.close()
            except OSError:
                pass
        self.g_conns.set(float(len(self.owned)))
        if report:
            try:
                self.ctl_conn.send(("shard_eof", conn_id))
            except OSError:
                self._head_gone()

    # -- housekeeping ------------------------------------------------------

    def _expire_pending(self) -> None:
        if not self.pending_sends:
            return
        now = time.monotonic()
        for conn_id in [
            c for c, (dl, _q) in self.pending_sends.items() if now > dl
        ]:
            self.pending_sends.pop(conn_id, None)

    def _maybe_push_metrics(self) -> None:
        from ray_tpu._private import config as _config
        from ray_tpu._private import telemetry as _telemetry

        period_ms = _config.get("metrics_push_ms")
        if period_ms <= 0:
            return
        now = time.monotonic()
        if now - self._last_push < period_ms / 1000.0:
            return
        self._last_push = now
        try:
            snap = _telemetry.snapshot_process(
                extra={
                    "io_shard_conns": float(len(self.owned)),
                    "io_shard_pending_handoff_sends": float(
                        len(self.pending_sends)
                    ),
                }
            )
            self.ctl_conn.send(("metrics_push", snap))
        except OSError:
            self._head_gone()
        except Exception:
            pass  # telemetry must never take the fabric down


def main() -> None:
    cfg = json.loads(os.environ["RAY_TPU_IO_SHARD_CONFIG"])
    idx = int(cfg["index"])
    tag = f"io_shard:{idx}"
    faults.set_process_tag(tag)

    from ray_tpu._private import telemetry as _telemetry
    from ray_tpu._private import wire

    _telemetry.install(tag)

    from multiprocessing.connection import Client

    authkey = bytes.fromhex(cfg["authkey"])
    # Hellos ride the raw channels (plain pickled tuples, pre-framing) so
    # the head's shard accept loop can tell ctl from fd channel apart with
    # one recv; wire framing starts with the first post-hello message on
    # the ctl channel, symmetric on both sides.
    raw_ctl = Client(cfg["ctl_addr"], authkey=authkey)
    raw_ctl.send(("io_shard", idx, os.getpid()))
    raw_fd = Client(cfg["ctl_addr"], authkey=authkey)
    raw_fd.send(("io_shard_fd", idx, os.getpid()))
    ctl_conn = wire.batching(wire.wrap(raw_ctl))
    server = _ShardServer(idx, ctl_conn, raw_fd)
    try:
        server.serve_forever()
    except (KeyboardInterrupt, SystemExit):
        pass


if __name__ == "__main__":
    main()
