"""Versioned, schema-validated control-plane framing.

ray: src/ray/protobuf/*.proto — the reference's control plane is typed
protobuf-over-gRPC with versioned services.  Rounds 1-3 here sent raw
pickled tuples: no version negotiation (a mixed-version cluster fails
with arbitrary unpickling errors mid-stream) and no message validation
(any tuple off an authenticated socket was dispatched on faith).

This module gives every control connection:

  * a 4-byte frame header (magic + u16 protocol version) on EVERY frame —
    a peer speaking a different protocol version fails at the first recv
    with a clean ProtocolError naming both versions, instead of a pickle
    traceback deep in a handler;
  * a per-message schema registry: str-kinded control tuples are checked
    for known kind, arity bounds, and leading field types at decode time —
    unknown or malformed control messages are rejected at the boundary;
  * pickle confined to the framed body (it still carries user payload
    blobs and complex specs — the authkey HMAC gates the bytes before any
    unpickling, as before), with raw passthrough (`send_bytes` /
    `recv_bytes` / `fileno`) for the object-transfer body path, which is
    not pickled at all.

TypedConn wraps a multiprocessing.connection.Connection and preserves its
surface (send/recv/poll/fileno/close), so `multiprocessing.connection
.wait` and the recv_into fast path keep working unchanged.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import faults
from ray_tpu._private import lock_watchdog


def _kind(obj: Any) -> Optional[str]:
    """Control-message kind for fault `match=` scoping (None for payload
    frames) — only computed when injection is enabled."""
    if isinstance(obj, tuple) and obj and isinstance(obj[0], str):
        return obj[0]
    return None

MAGIC = b"RT"
PROTOCOL_VERSION = 1
_HEADER = struct.pack("<2sH", MAGIC, PROTOCOL_VERSION)


class ProtocolError(ConnectionError):
    """Frame failed version or schema validation."""


# kind -> (min_extra_fields, max_extra_fields, leading_field_types)
# `None` in the types tuple = any.  Extra fields beyond the typed prefix
# are unconstrained (payload positions).  max_extra None = unbounded.
SCHEMAS: Dict[str, Tuple[int, Optional[int], tuple]] = {
    # worker/driver -> head
    "ready": (3, 4, (str, int)),
    "env_failed": (2, 2, (str, str)),
    "done": (3, 3, (str,)),
    "refop": (2, 2, (str, str)),
    "req": (3, 3, (int, str)),
    "object_copied": (2, 2, (str, int)),
    "actor_exit": (1, 1, (str,)),
    "fence_ack": (1, 1, (str,)),
    "direct_seal": (3, 3, (str, int)),
    "direct_lineage": (1, 1, ()),
    "promote": (3, 3, (str,)),
    "promote_error": (2, 2, (str,)),
    "seal_ow": (3, 3, (str, int)),
    "put_ow": (3, 3, (str,)),
    "task_events": (1, 1, (list,)),
    "spans": (1, 1, (list,)),
    # cross-process pubsub (pubsub.py remote delivery)
    "subscribe": (2, 3, (str,)),
    "unsubscribe": (2, 2, (str,)),
    "pub": (3, 3, (str,)),
    "lease_return": (1, 1, (str,)),
    "sync": (0, 1, ()),
    "kv_fetch": (1, 1, (str,)),
    "object_fetch": (1, 1, (str,)),
    "driver": (2, 2, (str,)),
    "driver_store": (2, 2, ()),
    # head -> worker
    "reply": (3, 3, (int,)),
    "task": (2, 2, ()),
    "create_actor": (2, 2, ()),
    "fence": (1, 1, (str,)),
    "kill": (0, 0, ()),
    "shutdown": (0, 1, ()),
    # zygote fork server (zygote.py)
    "zygote": (1, 1, (int,)),
    "fork": (4, 4, (str, dict, str, str)),
    "forked": (2, 2, (str, int)),
    # daemon <-> head
    "daemon": (3, 3, (str,)),
    "heartbeat": (0, 1, ()),
    "worker_exited": (1, 3, (str,)),
    "worker_oom_killed": (1, None, (str,)),
    "log_lines": (3, 3, (str, str, list)),
    "spawn_worker": (1, None, (str,)),
    "kill_worker": (1, 1, (str,)),
    "delete_object": (1, 1, (str,)),
    # peer transport
    "pcall": (1, 2, ()),
    "pcancel": (1, 1, (str,)),
    "pdone": (3, 3, (str,)),
    # transfer plane / handshake replies
    "ok": (1, 1, (int,)),
    "missing": (0, 0, ()),
    "driver_ack": (1, 1, (dict,)),
    "protocol_error": (1, 2, ()),
    # external-env policy serving (rllib/policy_client.py)
    "start_episode": (1, 1, ()),
    "get_action": (3, 3, (str,)),
    "log_returns": (2, 2, (str, float)),
    "end_episode": (2, 3, (str,)),
    "error": (1, 2, ()),
}


def _validate(obj: Any) -> None:
    """Schema-check str-kinded control tuples; other values (one-shot
    payload replies: kv bytes, ack dicts) pass through untyped."""
    if not (isinstance(obj, tuple) and obj and isinstance(obj[0], str)):
        return
    spec = SCHEMAS.get(obj[0])
    if spec is None:
        raise ProtocolError(f"unknown control message kind {obj[0]!r}")
    lo, hi, types = spec
    n = len(obj) - 1
    if n < lo or (hi is not None and n > hi):
        raise ProtocolError(
            f"control message {obj[0]!r} has {n} fields, expected "
            f"[{lo}, {hi if hi is not None else 'inf'}]"
        )
    for i, t in enumerate(types):
        if t is not None and not isinstance(obj[i + 1], t):
            raise ProtocolError(
                f"control message {obj[0]!r} field {i} is "
                f"{type(obj[i + 1]).__name__}, expected {t.__name__}"
            )


def encode(obj: Any) -> bytes:
    return _HEADER + pickle.dumps(obj, protocol=5)


def decode(buf) -> Any:
    if len(buf) < 4:
        raise ProtocolError("short control frame")
    magic, version = struct.unpack_from("<2sH", buf, 0)
    if magic != MAGIC:
        raise ProtocolError(
            "peer is not speaking the ray_tpu control protocol "
            f"(bad magic {magic!r})"
        )
    if version != PROTOCOL_VERSION:
        raise ProtocolError(
            f"protocol version mismatch: peer speaks v{version}, this "
            f"process speaks v{PROTOCOL_VERSION} — upgrade the older side"
        )
    obj = pickle.loads(memoryview(buf)[4:])
    _validate(obj)
    return obj


class TypedConn:
    """Connection wrapper applying the framing to send/recv while keeping
    the raw-byte surface for transfer bodies.  send() is atomic per conn:
    Connection.send_bytes is NOT safe under concurrent writers (header and
    body interleave), and several head threads (reply path, pub sender)
    legitimately share one driver/worker conn."""

    __slots__ = ("_c", "_send_lock")

    def __init__(self, conn):
        self._c = conn
        import threading

        self._send_lock = lock_watchdog.make_lock("TypedConn._send_lock")

    def send(self, obj: Any) -> None:
        if faults.ENABLED and faults.point("wire.send", key=_kind(obj)) == "drop":
            return  # frame lost on the wire; the sender believes it went out
        with self._send_lock:
            self._c.send_bytes(encode(obj))

    def recv(self) -> Any:
        while True:
            obj = decode(self._c.recv_bytes())
            if faults.ENABLED and faults.point("wire.recv", key=_kind(obj)) == "drop":
                continue  # frame lost before delivery; wait for the next
            return obj

    # raw passthrough (object-transfer body, recv_into via fileno)
    def send_bytes(self, b) -> None:
        self._c.send_bytes(b)

    def recv_bytes(self):
        return self._c.recv_bytes()

    def poll(self, timeout: float = 0.0) -> bool:
        return self._c.poll(timeout)

    def fileno(self) -> int:
        return self._c.fileno()

    def close(self) -> None:
        self._c.close()

    @property
    def closed(self) -> bool:
        return self._c.closed

    def __repr__(self) -> str:
        return f"TypedConn({self._c!r})"


def wrap(conn) -> TypedConn:
    return conn if isinstance(conn, TypedConn) else TypedConn(conn)


def connect(address, authkey: bytes) -> TypedConn:
    """Client-side connect + auth + wrap (the stdlib handshake runs on the
    raw connection; framing starts with the first application message)."""
    from multiprocessing.connection import Client

    return TypedConn(Client(tuple(address), authkey=authkey))
