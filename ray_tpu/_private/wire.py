"""Versioned, schema-validated control-plane framing + frame coalescing.

ray: src/ray/protobuf/*.proto — the reference's control plane is typed
protobuf-over-gRPC with versioned services.  Rounds 1-3 here sent raw
pickled tuples: no version negotiation (a mixed-version cluster fails
with arbitrary unpickling errors mid-stream) and no message validation
(any tuple off an authenticated socket was dispatched on faith).

This module gives every control connection:

  * a frame header (magic + u16 protocol version) on EVERY frame —
    a peer speaking a different protocol version fails at the first recv
    with a clean ProtocolError naming both versions, instead of a pickle
    traceback deep in a handler;
  * a per-message schema registry: str-kinded control tuples are checked
    for known kind, arity bounds, and leading field types at decode time —
    unknown or malformed control messages are rejected at the boundary;
  * serialization confined to the framed body — since v3 the hot control
    kinds ride NATIVE bodies (wire_native.py: struct-framed marshal data
    tuples, no pickle; the first body byte discriminates, 0x80 = pickle)
    and everything else stays pickled (the authkey HMAC gates the bytes
    before any decode, as before), with raw passthrough (`send_bytes` /
    `recv_bytes` / `fileno`) for the object-transfer body path, which is
    not serialized here at all.

Protocol v2 adds the BATCH frame: one physical write carrying N
schema-validated sub-frames.  PROFILE_r5.md showed the head's steady
state is raw syscall traffic — one posix.write and one epoll wakeup per
logical control message (the reference amortizes this for free through
gRPC stream buffering and its batched syncer/pubsub messages,
src/ray/ray_syncer/ + pubsub/publisher.h).  `BatchingConn` is the sender
side: messages queue into a pending buffer and flush on

  (a) size      — pending bytes reach RAY_TPU_WIRE_BATCH_BYTES (~64KB);
  (b) linger    — a short background sweep (RAY_TPU_WIRE_FLUSH_US,
                  ~200µs) bounds the delay of fire-and-forget frames;
  (c) explicit  — `flush()` / `flush_dirty()` BEFORE ANY BLOCKING WAIT,
                  so latency-sensitive request/reply paths never stall
                  behind the linger.  This is a RULE for new send paths:
                  queue freely, but flush before you park.

Per-sub-frame ordering, schema validation, and `wire.send`/`wire.recv`
fault-injection semantics are preserved: a `drop` clause drops an
individual sub-frame, never the whole batch; the new `wire.flush` point
covers the physical write (crash = batch lost mid-flight).  A malformed
sub-frame rejects the WHOLE batch at the boundary (no partial dispatch),
and a truncated batch body is a clean ProtocolError.

TypedConn wraps a multiprocessing.connection.Connection and preserves its
surface (send/recv/poll/fileno/close), so `multiprocessing.connection
.wait` and the recv_into fast path keep working unchanged; decoded batch
sub-frames queue receiver-side and `recv()` hands them out in order
(`poll()` reports them, `pending_frames()` exposes the count so drain
loops never strand a buffered tail behind an idle socket).
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import config as _config
from ray_tpu._private import faults
from ray_tpu._private import lock_watchdog
from ray_tpu._private import wire_native


def _kind(obj: Any) -> Optional[str]:
    """Control-message kind for fault `match=` scoping (None for payload
    frames) — only computed when injection is enabled."""
    if isinstance(obj, tuple) and obj and isinstance(obj[0], str):
        return obj[0]
    return None

MAGIC = b"RT"
# Batch frames carry their own magic so a v2 receiver can tell one
# physical write of N sub-frames from a plain single frame; a v1 receiver
# fails both shapes with the same clean bad-magic/version error.
MAGIC_BATCH = b"RB"
# v3: frame BODIES may be native (wire_native.py: struct-framed marshal,
# no pickle) for the hot control kinds.  The first body byte
# discriminates — pickle protocol-2+ streams always start with 0x80,
# native bodies with their kind id (1..0x7F) — so pickled and native
# bodies coexist per conn and per batch.  Negotiation IS the version
# fence: every frame header carries v3, an older peer rejects the first
# frame with the clean mismatch error naming both versions, and a v3
# peer by contract decodes both body forms.  Fallback is per-frame: any
# message whose kind has no native codec, or whose payload doesn't fit
# the packed schema (strategy objects, exceptions in replies), pickles
# exactly as in v2 (RAY_TPU_WIRE_NATIVE=0 forces the pickle path for
# every frame).
PROTOCOL_VERSION = 3
_HEADER = struct.pack("<2sH", MAGIC, PROTOCOL_VERSION)
_BATCH_HEADER = struct.Struct("<2sHI")  # magic, version, sub-frame count
_SUBLEN = struct.Struct("<I")


class ProtocolError(ConnectionError):
    """Frame failed version or schema validation."""


# kind -> (min_extra_fields, max_extra_fields, leading_field_types)
# `None` in the types tuple = any.  Extra fields beyond the typed prefix
# are unconstrained (payload positions).  max_extra None = unbounded.
SCHEMAS: Dict[str, Tuple[int, Optional[int], tuple]] = {
    # worker/driver -> head.  ready's optional 5th extra field is the
    # reconnect-time actor announcement (reconciliation handshake); the
    # optional 6th is the sender's time.time() at send — the head's
    # clock-offset estimate for merging this process's spans/task events
    # into one cluster timeline; the optional 7th is the executor's
    # relayed-work announcement (task ids still held) — the head
    # re-drives in-flight work missing from it, the conn-death recovery
    # the io-shard fabric leans on.
    "ready": (3, 7, (str, int)),
    "actor_announce": (1, 1, (list,)),
    "env_failed": (2, 2, (str, str)),
    # done's optional 4th extra field is the executor-side stage timing
    # ({"recv","start","end"} wall-clock stamps) the head folds into the
    # task's lifecycle record (clock-offset-corrected at ingest).
    "done": (3, 4, (str,)),
    "refop": (2, 2, (str, str)),
    "req": (3, 3, (int, str)),
    # object_copied's optional 3rd extra field is the transfer path the
    # puller used ("pull" sealed source / "relay" in-flight feed) — the
    # owner releases the right transfer-plan slot and labels the ledger
    # event with it.
    "object_copied": (2, 3, (str, int)),
    "actor_exit": (1, 1, (str,)),
    "fence_ack": (1, 1, (str,)),
    "direct_seal": (3, 3, (str, int)),
    "direct_lineage": (1, 1, ()),
    "promote": (3, 3, (str,)),
    "promote_error": (2, 2, (str,)),
    "seal_ow": (3, 3, (str, int)),
    "put_ow": (3, 3, (str,)),
    "task_events": (1, 1, (list,)),
    "spans": (1, 1, (list,)),
    "wire_stats": (1, 1, (dict,)),
    # Periodic per-process telemetry snapshot (util/metrics registry +
    # wire counters + internal gauges) — droppable oneway, aggregated
    # into the head's TelemetrySink (telemetry.py).
    "metrics_push": (1, 1, (dict,)),
    # Periodic per-process live-ref table (refs.py snapshot + transport
    # ownership) — the worker leg of the object ledger (`ray_tpu memory`),
    # droppable like metrics_push.
    "refs_push": (1, 1, (dict,)),
    # Periodic per-process collapsed-stack table (profiler.py snapshot,
    # cumulative since start) — the worker leg of `ray_tpu profile`.
    # Droppable like metrics_push: a lost push costs freshness only.
    "prof_push": (1, 1, (dict,)),
    # head io-shard fabric (io_shard.py): the internal channel between the
    # head process and its io-shard processes.  shard_fwd carries a conn's
    # raw sub-frame BODIES in arrival order (native bodies untouched —
    # the head's decode is the only decode; pickled bodies were decoded/
    # validated on the shard pid and re-encoded): the per-conn ordering
    # invariant across the shard boundary is the list order.  shard_send
    # is the reverse path — ONE head-encoded body the shard writes to the
    # conn without decoding; shard_eof reports a handed-off conn's death.
    "shard_fwd": (2, 2, (str, list)),
    "shard_eof": (1, 2, (str,)),
    "shard_send": (2, 2, (str, bytes)),
    "shard_close": (1, 1, (str,)),
    # cross-process pubsub (pubsub.py remote delivery)
    "subscribe": (2, 3, (str,)),
    "unsubscribe": (2, 2, (str,)),
    "pub": (3, 3, (str,)),
    "lease_return": (1, 1, (str,)),
    "sync": (0, 1, ()),
    "kv_fetch": (1, 1, (str,)),
    # object_fetch's optional 2nd extra field flags a relay-capable
    # receiver (it understands the crc-framed "relay" body).
    "object_fetch": (1, 2, (str,)),
    # driver hello's optional 3rd extra = sender clock (same offset
    # estimate the worker ready carries).
    "driver": (2, 3, (str,)),
    "driver_store": (2, 2, ()),
    # head -> worker
    "reply": (3, 3, (int,)),
    "task": (2, 2, ()),
    "create_actor": (2, 2, ()),
    "fence": (1, 1, (str,)),
    "kill": (0, 0, ()),
    "shutdown": (0, 1, ()),
    # zygote fork server (zygote.py)
    "zygote": (1, 1, (int,)),
    "fork": (4, 4, (str, dict, str, str)),
    "forked": (2, 2, (str, int)),
    # daemon -> zygote: the node arena's open fd follows this frame as an
    # SCM_RIGHTS ancillary message on the same AF_UNIX pipe (netutil
    # send_fd/recv_fd); forked workers inherit the descriptor and map the
    # store without touching the path.
    "arena_fd": (1, 1, (str,)),
    # daemon <-> head
    "daemon": (3, 3, (str,)),
    "heartbeat": (0, 1, ()),
    # worker_exited rides two channels: zygote -> daemon sends (wid, rc),
    # daemon -> head adds the oom flag (wid, rc, oom).
    "worker_exited": (2, 3, (str,)),
    "worker_oom_killed": (1, None, (str,)),
    "log_lines": (3, 3, (str, str, list)),
    "spawn_worker": (2, 2, (str,)),
    "kill_worker": (1, 1, (str,)),
    "delete_object": (1, 1, (str,)),
    # peer transport
    "pcall": (1, 2, ()),
    "pcancel": (1, 1, (str,)),
    "pdone": (3, 3, (str,)),
    # transfer plane / handshake replies
    "ok": (1, 1, (int,)),
    # relay reply header: (total_bytes, chunk_bytes) — body is crc-framed
    # chunks streamed as the serving board's watermark advances.
    "relay": (2, 2, (int, int)),
    "missing": (0, 0, ()),
    "driver_ack": (1, 1, (dict,)),
    "protocol_error": (1, 2, ()),
    # external-env policy serving (rllib/policy_client.py)
    "start_episode": (1, 1, ()),
    "get_action": (3, 3, (str,)),
    "log_returns": (2, 2, (str, float)),
    "end_episode": (2, 3, (str,)),
    "error": (1, 2, ()),
}


def _validate(obj: Any) -> None:
    """Schema-check str-kinded control tuples; other values (one-shot
    payload replies: kv bytes, ack dicts) pass through untyped."""
    if not (isinstance(obj, tuple) and obj and isinstance(obj[0], str)):
        return
    spec = SCHEMAS.get(obj[0])
    if spec is None:
        raise ProtocolError(f"unknown control message kind {obj[0]!r}")
    lo, hi, types = spec
    n = len(obj) - 1
    if n < lo or (hi is not None and n > hi):
        raise ProtocolError(
            f"control message {obj[0]!r} has {n} fields, expected "
            f"[{lo}, {hi if hi is not None else 'inf'}]"
        )
    for i, t in enumerate(types):
        if t is not None and not isinstance(obj[i + 1], t):
            raise ProtocolError(
                f"control message {obj[0]!r} field {i} is "
                f"{type(obj[i + 1]).__name__}, expected {t.__name__}"
            )


def _check_version(magic: bytes, version: int) -> None:
    if magic not in (MAGIC, MAGIC_BATCH):
        raise ProtocolError(
            "peer is not speaking the ray_tpu control protocol "
            f"(bad magic {magic!r})"
        )
    if version != PROTOCOL_VERSION:
        raise ProtocolError(
            f"protocol version mismatch: peer speaks v{version}, this "
            f"process speaks v{PROTOCOL_VERSION} — upgrade the older side"
        )


def encode_body(obj: Any) -> bytes:
    """Body bytes for one control message: native (struct-framed marshal,
    wire_native.py) for the hot kinds when the knob allows, else pickle.
    The first body byte self-describes which (0x80 = pickle)."""
    if _config.get("wire_native"):
        body = wire_native.encode(obj)
        if body is not None:
            _count_codec(native_encodes=1)
            return body
    _count_codec(pickle_encodes=1)
    return pickle.dumps(obj, protocol=5)


# Allocation guard for the pickle path (RAY_TPU_WIRE_GUARD, shared with
# the marshal-side guard in wire_native._scan_payload).  pickle.loads has
# the same pre-allocation hazard marshal does: counted opcodes
# (BINBYTES8, BYTEARRAY8 — the latter ZERO-FILLS) allocate the declared
# size before checking the buffer holds it, and LONG_BINPUT grows the
# memo table to the declared index — so a single byte flip in a pickled
# body can make the decoder commit gigabytes.  The scan walks the opcode
# stream, bounds every declared length/index against the bytes actually
# present, and admits only opcodes a protocol-2+ pickler emits (our
# encoder always writes protocol 5; a text-era opcode in a frame body is
# corruption, not data).  It bounds ALLOCATION only — pickle still
# executes reducers on scan-clean bodies; the trust model is unchanged.
_PK_BAD, _PK_C1, _PK_C4, _PK_C8, _PK_PUT4 = -1, -2, -3, -4, -5
_PK_ACTIONS = [_PK_BAD] * 256
for _op, _skip in {
    0x80: 1,          # PROTO
    0x95: 8,          # FRAME (length hint; loads tolerates mismatch)
    0x2E: 0,          # STOP
    0x28: 0, 0x30: 0, 0x31: 0, 0x32: 0,        # MARK POP POP_MARK DUP
    0x4E: 0, 0x88: 0, 0x89: 0,                 # NONE NEWTRUE NEWFALSE
    0x29: 0, 0x85: 0, 0x86: 0, 0x87: 0, 0x74: 0,  # tuples
    0x5D: 0, 0x61: 0, 0x65: 0,                 # EMPTY_LIST APPEND APPENDS
    0x7D: 0, 0x73: 0, 0x75: 0,                 # EMPTY_DICT SETITEM(S)
    0x8F: 0, 0x90: 0, 0x91: 0,                 # sets
    0x52: 0, 0x62: 0, 0x81: 0, 0x92: 0,        # REDUCE BUILD NEWOBJ(_EX)
    0x93: 0, 0x94: 0,                          # STACK_GLOBAL MEMOIZE
    0x4A: 4, 0x4B: 1, 0x4D: 2, 0x47: 8,        # BININT/1/2 BINFLOAT
    0x68: 1, 0x6A: 4, 0x71: 1,                 # BINGET LONG_BINGET BINPUT
    0x51: 0, 0x97: 0, 0x98: 0,  # BINPERSID NEXT_BUFFER READONLY_BUFFER
}.items():
    _PK_ACTIONS[_op] = _skip
_PK_ACTIONS[0x8C] = _PK_C1   # SHORT_BINUNICODE
_PK_ACTIONS[0x58] = _PK_C4   # BINUNICODE
_PK_ACTIONS[0x8D] = _PK_C8   # BINUNICODE8
_PK_ACTIONS[0x43] = _PK_C1   # SHORT_BINBYTES
_PK_ACTIONS[0x42] = _PK_C4   # BINBYTES
_PK_ACTIONS[0x8E] = _PK_C8   # BINBYTES8
_PK_ACTIONS[0x96] = _PK_C8   # BYTEARRAY8
_PK_ACTIONS[0x8A] = _PK_C1   # LONG1
_PK_ACTIONS[0x8B] = _PK_C4   # LONG4
_PK_ACTIONS[0x72] = _PK_PUT4  # LONG_BINPUT: memo grows to the index
del _op, _skip


def _scan_pickle(data) -> None:
    """Bounds-check a pickled body's opcode stream before pickle.loads.
    Raises ProtocolError when a declared length/index outruns the bytes
    present or an opcode outside the binary-protocol subset appears.
    Stops at STOP like loads does; a stream that ends without STOP is
    left for loads to reject (it can't over-allocate once every counted
    opcode is bounded)."""
    if type(data) is not bytes:
        data = bytes(data)
    n = len(data)
    pos = 0
    actions = _PK_ACTIONS
    while pos < n:
        op = data[pos]
        act = actions[op]
        pos += 1
        if act > 0:
            pos += act
            continue
        if act == 0:
            if op == 0x2E:  # STOP: loads ignores anything after it
                return
            continue
        if act == _PK_C1:
            if pos >= n:
                raise ProtocolError("truncated pickle opcode argument")
            ln = data[pos]
            pos += 1 + ln
            continue
        if act == _PK_C4 or act == _PK_C8:
            width = 4 if act == _PK_C4 else 8
            if pos + width > n:
                raise ProtocolError("truncated pickle opcode argument")
            ln = int.from_bytes(data[pos:pos + width], "little")
            pos += width
            if ln > n - pos:
                raise ProtocolError(
                    f"pickle opcode {op:#x} declares {ln} bytes, "
                    f"{n - pos} remain — allocation bomb"
                )
            pos += ln
            continue
        if act == _PK_PUT4:
            if pos + 4 > n:
                raise ProtocolError("truncated pickle opcode argument")
            idx = int.from_bytes(data[pos:pos + 4], "little")
            if idx > n:
                raise ProtocolError(
                    f"pickle memo index {idx} outruns the body — the memo "
                    "table would be grown to it"
                )
            pos += 4
            continue
        raise ProtocolError(
            f"pickle opcode {op:#x} outside the binary-protocol subset"
        )


def decode_body(body) -> Any:
    """Decode + schema-validate ONE sub-frame body (pickled or native)."""
    if body and body[0] != 0x80:
        try:
            obj = wire_native.decode(body)
        except wire_native.ProtocolError as e:
            raise ProtocolError(str(e)) from None
        _count_codec(native_decodes=1)
    else:
        # A corrupt pickled body raises UnpicklingError/EOFError/etc. —
        # wrap in ProtocolError so a torn frame is a boundary rejection
        # (conn death), never an unhandled exception in a recv loop.
        if wire_native._guard_enabled():
            _scan_pickle(body)
        try:
            obj = pickle.loads(body)
        except ProtocolError:
            raise
        except Exception as e:
            raise ProtocolError(f"malformed pickled frame body: {e!r}") from None
        _count_codec(pickle_decodes=1)
    _validate(obj)
    return obj


def encode(obj: Any) -> bytes:
    return _HEADER + pickle.dumps(obj, protocol=5)


def encode_native(obj: Any) -> bytes:
    """One full frame using the body codec (native when possible)."""
    return _HEADER + encode_body(obj)


def encode_batch(bodies: List[bytes]) -> bytes:
    """One physical frame carrying N already-pickled sub-frame bodies."""
    parts = [_BATCH_HEADER.pack(MAGIC_BATCH, PROTOCOL_VERSION, len(bodies))]
    for b in bodies:
        parts.append(_SUBLEN.pack(len(b)))
        parts.append(b)
    return b"".join(parts)


def decode(buf) -> Any:
    """Decode ONE single-kind frame (handshakes, tests).  Batch frames go
    through decode_frames — a batch here would be a framing bug."""
    objs = decode_frames(buf)
    if len(objs) != 1:
        raise ProtocolError(
            f"expected a single control frame, got a batch of {len(objs)}"
        )
    return objs[0]


def split_frame_bodies(buf) -> List[memoryview]:
    """Parse a physical frame into its raw sub-frame BODIES, in order,
    without decoding any of them.  Structural validation only: truncated
    batches reject whole (the shape a mid-batch sender crash leaves
    behind).  The io shards use this to forward native bodies raw —
    decode happens exactly once, head-side."""
    if len(buf) < 4:
        raise ProtocolError("short control frame")
    magic, version = struct.unpack_from("<2sH", buf, 0)
    _check_version(magic, version)
    view = memoryview(buf)
    if magic == MAGIC:
        return [view[4:]]
    if len(buf) < _BATCH_HEADER.size:
        raise ProtocolError("truncated batch frame (short header)")
    _m, _v, count = _BATCH_HEADER.unpack_from(buf, 0)
    bodies: List[memoryview] = []
    off = _BATCH_HEADER.size
    for _ in range(count):
        if off + _SUBLEN.size > len(buf):
            raise ProtocolError(
                f"truncated batch frame ({len(bodies)}/{count} sub-frames "
                "before the body ran out)"
            )
        (n,) = _SUBLEN.unpack_from(buf, off)
        off += _SUBLEN.size
        if off + n > len(buf):
            raise ProtocolError(
                f"truncated batch frame (sub-frame {len(bodies)} declares "
                f"{n} bytes, {len(buf) - off} remain)"
            )
        bodies.append(view[off:off + n])
        off += n
    if off != len(buf):
        raise ProtocolError(
            f"batch frame has {len(buf) - off} trailing bytes after "
            f"{count} sub-frames"
        )
    return bodies


def decode_frames(buf) -> List[Any]:
    """Decode a physical frame into its validated sub-frames, in order.

    A single frame yields [obj].  For a batch, EVERY sub-frame is
    decoded and schema-validated before any is returned: one malformed
    sub-frame rejects the whole batch at the boundary (no partial
    dispatch).  Bodies may be pickled or native (v3) — decode_body
    dispatches per body."""
    return [decode_body(b) for b in split_frame_bodies(buf)]


# ---------------------------------------------------------------------------
# per-process wire statistics
#
# Counting is always on (a few int adds under a lock already serializing
# the physical write path); EXPOSURE through the state API / dashboard /
# bench output is gated on RAY_TPU_WIRE_STATS=1.  logical_frames counts
# control messages handed to send layers; physical_writes counts actual
# send_bytes calls — their ratio is the coalescing factor the
# acceptance bar is measured by.

_stats_lock = threading.Lock()
_stats_pid = os.getpid()
_STAT_KEYS = (
    "logical_frames",
    "physical_writes",
    "bytes_written",
    "batched_frames",   # logical frames that rode a multi-frame batch
    "flush_size",
    "flush_linger",
    "flush_explicit",
    "flush_direct",     # unbatched TypedConn.send / single passthrough
    # codec split: how many control bodies this process pickled vs
    # native-encoded (and the decode twins).  pickle_* per task is the
    # deterministic acceptance metric of the native-codec work — host
    # noise can fake an ops/s win, a counter can't.
    "pickle_encodes",
    "pickle_decodes",
    "native_encodes",
    "native_decodes",
)
_stats: Dict[str, int] = {k: 0 for k in _STAT_KEYS}


def _count(n_logical: int, n_bytes: int, reason: str) -> None:
    with _stats_lock:
        _stats["logical_frames"] += n_logical
        _stats["physical_writes"] += 1
        _stats["bytes_written"] += n_bytes
        if n_logical > 1:
            _stats["batched_frames"] += n_logical
        key = f"flush_{reason}"
        if key in _stats:
            _stats[key] += 1


def _count_codec(
    pickle_encodes: int = 0, pickle_decodes: int = 0,
    native_encodes: int = 0, native_decodes: int = 0,
) -> None:
    with _stats_lock:
        _stats["pickle_encodes"] += pickle_encodes
        _stats["pickle_decodes"] += pickle_decodes
        _stats["native_encodes"] += native_encodes
        _stats["native_decodes"] += native_decodes


def stats() -> Dict[str, int]:
    """Snapshot of this process's wire counters."""
    _fork_check()
    with _stats_lock:
        return dict(_stats)


def stats_enabled() -> bool:
    return bool(_config.get("wire_stats"))


def _reset_stats_for_tests() -> None:
    with _stats_lock:
        for k in _STAT_KEYS:
            _stats[k] = 0


# ---------------------------------------------------------------------------
# background linger flusher
#
# One daemon thread per process sweeps dirty BatchingConns after a short
# linger (RAY_TPU_WIRE_FLUSH_US).  It is the BOUND on fire-and-forget
# latency, not the main flush path: bursts flush on size, and every
# blocking wait flushes explicitly first.  Forked children (zygote
# workers, fork-start daemons) inherit the module state but not the
# thread — _fork_check() detects the pid change and resets.

_dirty_lock = threading.Lock()
_dirty: "set[BatchingConn]" = set()
_dirty_event = threading.Event()
_flusher_started = False


def _linger_s() -> float:
    return max(_config.get("wire_flush_us"), 0) / 1e6


def _fork_check() -> None:
    global _stats_pid, _flusher_started
    if os.getpid() == _stats_pid:
        return
    with _dirty_lock, _stats_lock:
        if os.getpid() == _stats_pid:
            return
        _stats_pid = os.getpid()
        _flusher_started = False  # parent's thread did not survive the fork
        _dirty.clear()            # nor did its conns
        for k in _STAT_KEYS:
            _stats[k] = 0


def _note_dirty(bc: "BatchingConn") -> None:
    global _flusher_started
    _fork_check()
    with _dirty_lock:
        was_empty = not _dirty
        _dirty.add(bc)
        if not _flusher_started:
            _flusher_started = True
            threading.Thread(
                target=_flusher_loop, daemon=True, name="raytpu-wire-flush"
            ).start()
        if was_empty:
            # Arm the linger sweep only on the empty->dirty transition; an
            # explicit flush_dirty() that empties the set DISARMS it
            # (_take_dirty clears the event under the same lock), so the
            # common send-then-flush-before-park pattern never wakes the
            # flusher thread at all — per-op thread wakeups were a
            # measured ~2x latency hit on a 1-vCPU host.
            _dirty_event.set()


def _forget_dirty(bc: "BatchingConn") -> None:
    with _dirty_lock:
        _dirty.discard(bc)


def _take_dirty() -> List["BatchingConn"]:
    with _dirty_lock:
        out = list(_dirty)
        _dirty.clear()
        # Atomic with the emptying: a concurrent _note_dirty serializes on
        # _dirty_lock, so it either re-arms after this clear or found the
        # set non-empty (no arm needed — we are taking its conn).
        _dirty_event.clear()
    return out


def _flusher_loop() -> None:
    while True:
        _dirty_event.wait()
        linger = _linger_s()
        if linger > 0:
            time.sleep(linger)
        # _take_dirty disarms the event; usually an explicit flush already
        # did both and this sweep finds nothing (then goes back to sleep
        # without having cost the hot path anything).
        for bc in _take_dirty():
            try:
                bc.flush(_reason="linger")
            except (OSError, ValueError):
                pass  # conn died; its owner's recv side handles it


def flush_dirty() -> None:
    """Flush every pending batch in this process NOW.  Call this before
    any blocking wait (the rule latency-sensitive paths live by) — the
    io loop, request/reply muxes, and executor idle points all do."""
    for bc in _take_dirty():
        try:
            bc.flush(_reason="explicit")
        except (OSError, ValueError):
            pass


def flush_conn(conn) -> None:
    """Flush one conn if it batches (no-op for plain TypedConns/mocks);
    transport errors surface to the caller like a failed send."""
    f = getattr(conn, "flush", None)
    if f is not None:
        f()


class TypedConn:
    """Connection wrapper applying the framing to send/recv while keeping
    the raw-byte surface for transfer bodies.  send() is atomic per conn:
    Connection.send_bytes is NOT safe under concurrent writers (header and
    body interleave), and several head threads (reply path, pub sender)
    legitimately share one driver/worker conn.

    Received batch frames are decoded whole (validate-all-then-dispatch)
    into an internal queue; recv() returns sub-frames in order.  The
    queue is only touched by the conn's single reader thread — recv
    concurrency was never supported and still isn't."""

    __slots__ = ("_c", "_send_lock", "_rbuf")

    def __init__(self, conn):
        self._c = conn
        self._send_lock = lock_watchdog.make_lock("TypedConn._send_lock")
        self._rbuf: List[Any] = []  # decoded-but-undelivered sub-frames

    def send(self, obj: Any) -> None:
        if faults.ENABLED and faults.point("wire.send", key=_kind(obj)) == "drop":
            return  # frame lost on the wire; the sender believes it went out
        buf = _HEADER + encode_body(obj)
        with self._send_lock:
            self._c.send_bytes(buf)
            _count(1, len(buf), "direct")

    def _send_frame(self, buf: bytes, n_logical: int, reason: str) -> None:
        """Physical write of a pre-encoded frame (BatchingConn flush path)
        — shares the send lock so batched and direct writers never
        interleave on the wire."""
        with self._send_lock:
            self._c.send_bytes(buf)
            _count(n_logical, len(buf), reason)

    def recv(self) -> Any:
        while True:
            if self._rbuf:
                return self._rbuf.pop(0)
            objs = decode_frames(self._c.recv_bytes())
            if faults.ENABLED:
                # drop clauses fire per SUB-frame (key = message kind),
                # exactly as they did per physical frame pre-batching.
                objs = [
                    o for o in objs
                    if faults.point("wire.recv", key=_kind(o)) != "drop"
                ]
            if not objs:
                continue  # everything dropped; wait for the next frame
            self._rbuf = objs
            return self._rbuf.pop(0)

    def pending_frames(self) -> int:
        """Decoded sub-frames awaiting recv().  Drain loops must consume
        these before blocking on the fd — the socket shows no data for
        them, so an epoll/wait would strand a buffered tail."""
        return len(self._rbuf)

    def recv_bodies(self) -> List[bytes]:
        """One physical frame's raw sub-frame bodies, NO decode (io-shard
        forward path: native bodies ship head-ward untouched).  Must not
        be mixed with recv() on the same conn while decoded sub-frames
        are buffered — the interleaving would reorder the stream."""
        if self._rbuf:
            raise RuntimeError(
                "recv_bodies() with decoded sub-frames pending would "
                "reorder the stream"
            )
        return [bytes(b) for b in split_frame_bodies(self._c.recv_bytes())]

    # raw passthrough (object-transfer body, recv_into via fileno)
    def send_bytes(self, b) -> None:
        self._c.send_bytes(b)

    def recv_bytes(self):
        return self._c.recv_bytes()

    def poll(self, timeout: float = 0.0) -> bool:
        if self._rbuf:
            return True
        return self._c.poll(timeout)

    def fileno(self) -> int:
        return self._c.fileno()

    def close(self) -> None:
        self._c.close()

    @property
    def closed(self) -> bool:
        return self._c.closed

    def __repr__(self) -> str:
        return f"TypedConn({self._c!r})"


class BatchingConn:
    """Coalescing sender over a TypedConn (recv side passes through).

    send() encodes the message immediately (native codec or pickle —
    cheap, and the bytes are what the size threshold meters) and queues
    it; the pending run is flushed
    as ONE physical frame on size / linger / explicit flush.  A single
    pending message flushes as a plain frame — the batch envelope only
    appears when it pays for itself.

    Failure model: the first flush that hits a dead socket marks the conn
    broken; from then on send() raises OSError AT THE CALL, restoring the
    pre-batching contract that callers (oneway backlogs, reply paths)
    detect a dead conn at send time.  Messages stranded in the pending
    buffer by the breaking flush are recoverable via drain_pending() —
    the worker reconnect path replays them ahead of its oneway backlog.

    send_lock is the wire-serialization lock for the PENDING BUFFER; the
    physical write additionally serializes on the TypedConn's own send
    lock, so batched flushes and direct TypedConn sends on the same conn
    never interleave frames."""

    __slots__ = (
        "_c", "send_lock", "_pending", "_pending_bytes", "_batch_bytes",
        "_broken", "flush_reasons", "_pending_first_kind",
    )

    def __init__(self, conn, batch_bytes: Optional[int] = None):
        self._c = wrap(conn)
        self.send_lock = lock_watchdog.make_lock("BatchingConn.send_lock")
        self._pending: List[bytes] = []
        self._pending_bytes = 0
        self._batch_bytes = (
            _config.get("wire_batch_bytes") if batch_bytes is None else batch_bytes
        )
        self._broken = False
        # Per-conn flush-reason histogram (the per-process aggregate lives
        # in wire.stats()).
        self.flush_reasons: Dict[str, int] = {}
        # Kind of the batch's LEADING message: the wire.flush fault key,
        # so clauses scope by stream exactly like wire.send ones
        # (match=^done kills a task executor at its done-batch flush
        # without touching a replica's pdone batches).
        self._pending_first_kind: Optional[str] = None

    @property
    def conn(self):
        """The underlying TypedConn (tests, fd surgery)."""
        return self._c

    def send(self, obj: Any) -> None:
        if self._batch_bytes <= 0:
            # Coalescing disabled (RAY_TPU_WIRE_BATCH_BYTES=0): behave as
            # a plain TypedConn — the unbatched comparison baseline.
            self._c.send(obj)
            return
        if self._broken:
            raise OSError("connection previously failed a batch flush")
        if faults.ENABLED and faults.point("wire.send", key=_kind(obj)) == "drop":
            return  # frame lost on the wire; the sender believes it went out
        body = encode_body(obj)
        with self.send_lock:
            if not self._pending:
                self._pending_first_kind = _kind(obj)
            self._pending.append(body)
            self._pending_bytes += len(body) + _SUBLEN.size
            if self._pending_bytes >= self._batch_bytes:
                self._flush_locked("size")
                return
        _note_dirty(self)

    def flush(self, _reason: str = "explicit") -> None:
        with self.send_lock:
            self._flush_locked(_reason)

    def _flush_locked(self, reason: str) -> None:
        # caller holds self.send_lock
        if not self._pending:
            return
        if faults.ENABLED:
            # crash = die with the batch in flight (the receiver sees a
            # torn physical stream — EOF, or a truncated frame that
            # decode_frames rejects whole); delay stretches the flush
            # window; error/drop fail/lose the whole batch, which is one
            # physical message now.  Key = "<leading kind>:<reason>" so
            # clauses scope per stream (match=^done) or per trigger
            # (match=linger).
            key = f"{self._pending_first_kind or 'payload'}:{reason}"
            if faults.point("wire.flush", key=key) == "drop":
                self._pending = []
                self._pending_bytes = 0
                self._pending_first_kind = None
                return
        bodies = self._pending
        if len(bodies) == 1:
            buf = _HEADER + bodies[0]
        else:
            buf = encode_batch(bodies)
        try:
            self._c._send_frame(buf, len(bodies), reason)
        except (OSError, ValueError):
            # Leave the pending run in place for drain_pending(): the
            # conn is dead, but the messages may carry ownership state a
            # reconnect path can replay.
            self._broken = True
            raise
        self._pending = []
        self._pending_bytes = 0
        self._pending_first_kind = None
        self.flush_reasons[reason] = self.flush_reasons.get(reason, 0) + 1

    def drain_pending_bodies(self) -> List[bytes]:
        """Take back queued-but-unflushed PICKLED bodies (a broken conn's
        tail) for replay on a replacement conn via send_body().  Raw by
        design: unpickling can construct ObjectRefs, whose refcount hooks
        take the transport lock — poison while the caller holds a conn
        lock (the reconnect path does)."""
        with self.send_lock:
            bodies, self._pending = self._pending, []
            self._pending_bytes = 0
            self._pending_first_kind = None
        return bodies

    def drain_pending(self) -> List[Any]:
        """drain_pending_bodies, decoded (tests/diagnostics — do NOT call
        while holding a conn lock, see above)."""
        return [decode_body(b) for b in self.drain_pending_bodies()]

    def send_body(self, body: bytes) -> None:
        """Queue an already-pickled body (replay of a drained tail)."""
        if self._broken:
            raise OSError("connection previously failed a batch flush")
        if self._batch_bytes <= 0:
            with self.send_lock:
                self._c._send_frame(_HEADER + body, 1, "direct")
            return
        with self.send_lock:
            self._pending.append(body)
            self._pending_bytes += len(body) + _SUBLEN.size
            if self._pending_bytes >= self._batch_bytes:
                self._flush_locked("size")
                return
        _note_dirty(self)

    # -- recv + passthrough surface (the conn's reader side is unchanged)

    def recv(self) -> Any:
        return self._c.recv()

    def recv_bodies(self) -> List[bytes]:
        return self._c.recv_bodies()

    def pending_frames(self) -> int:
        return self._c.pending_frames()

    def send_bytes(self, b) -> None:
        self._c.send_bytes(b)

    def recv_bytes(self):
        return self._c.recv_bytes()

    def poll(self, timeout: float = 0.0) -> bool:
        return self._c.poll(timeout)

    def fileno(self) -> int:
        return self._c.fileno()

    def close(self) -> None:
        _forget_dirty(self)
        self._c.close()

    @property
    def closed(self) -> bool:
        return self._c.closed

    def __repr__(self) -> str:
        return f"BatchingConn({self._c!r}, pending={len(self._pending)})"


def wrap(conn) -> TypedConn:
    if isinstance(conn, (TypedConn, BatchingConn)):
        return conn
    return TypedConn(conn)


def batching(conn) -> BatchingConn:
    """Wrap a conn in the coalescing sender (idempotent)."""
    return conn if isinstance(conn, BatchingConn) else BatchingConn(conn)


def connect(address, authkey: bytes) -> TypedConn:
    """Client-side connect + auth + wrap (the stdlib handshake runs on the
    raw connection; framing starts with the first application message)."""
    from multiprocessing.connection import Client

    return TypedConn(Client(tuple(address), authkey=authkey))
