"""Zygote: pre-warmed fork server for fast worker spawn.

The reference prestarts idle workers per language/runtime-env so actor
creation binds to a live process instead of paying an interpreter boot
(ray: src/ray/raylet/worker_pool.h:156, PopWorker/StartWorkerProcess).
On this build a fresh CPython interpreter + worker-runtime imports cost
~150-300ms of CPU per worker — at 1000 actors that IS the creation
budget (round-4 bench: 3.8 actors/s, entirely spawn-bound).

The zygote goes further than prestart: ONE interpreter boots, imports
the worker runtime (never jax — forking a process with an initialized
XLA client is undefined), connects back to its owner (head runtime or
node daemon), and serves ("fork", wid, overrides, out, err) requests.
A fork costs ~2ms, so worker supply scales with the scheduler, not
with interpreter boots.

Invariants:
  * the zygote is SINGLE-THREADED until it forks (fork + threads is the
    classic deadlock) and never imports jax/torch (sitecustomize's axon
    hook is stripped from its env by the spawner; the original value is
    restored per-fork via overrides so children can still reach the TPU);
  * children are direct children of the zygote: PR_SET_PDEATHSIG chains
    owner -> zygote -> worker, preserving the die-with-owner invariant
    daemon workers rely on, and the zygote reaps exits, reporting them
    as ("worker_exited", wid, pid) so never-connected boot crashes are
    classified without waiting for a conn-EOF that will never come.
"""

from __future__ import annotations

import os
import signal
import sys


def _arm_pdeathsig() -> None:
    try:
        import ctypes

        ctypes.CDLL(None).prctl(1, signal.SIGTERM)  # PR_SET_PDEATHSIG
    except Exception:
        pass


def _child_entry(overrides: dict, out_path: str, err_path: str) -> None:
    """Runs in the forked child: restore the worker env, point stdio at
    the worker's log files, and enter the normal worker main."""
    # fork(2) clears PR_SET_PDEATHSIG: re-arm so the worker dies with the
    # ZYGOTE (its parent), completing the owner -> zygote -> worker chain.
    if os.environ.get("RAY_TPU_PDEATHSIG") or overrides.get("RAY_TPU_PDEATHSIG"):
        _arm_pdeathsig()
    os.environ.update({k: str(v) for k, v in overrides.items()})
    # The fork inherited the ZYGOTE's fault-plane state (its process tag
    # and visit counters): re-derive the worker identity and restart the
    # clause counters so proc=worker clauses scope correctly and each
    # worker's injection schedule starts from zero.
    from ray_tpu._private import faults

    faults.set_process_tag(
        "worker:" + os.environ.get("RAY_TPU_WORKER_ID", "?")
    )
    faults.refresh_from_env()
    try:
        out_fd = os.open(out_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        err_fd = os.open(err_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(out_fd, 1)
        os.dup2(err_fd, 2)
        os.close(out_fd)
        os.close(err_fd)
        # Re-bind the Python-level streams to the new fds (the inherited
        # file objects still wrap the zygote's /dev/null-ish stdio).
        sys.stdout = os.fdopen(1, "w", buffering=1)
        sys.stderr = os.fdopen(2, "w", buffering=1)
    except OSError:
        pass  # log redirection is best-effort; the worker still runs
    from ray_tpu._private.worker_proc import _subprocess_entry

    try:
        _subprocess_entry()
    except SystemExit:
        raise
    except BaseException:
        import traceback

        traceback.print_exc()
    finally:
        os._exit(0)


def main() -> None:
    _arm_pdeathsig()
    # Two attachment modes: an inherited pipe fd (daemon-owned zygotes —
    # RAY_TPU_ZYGOTE_FD) or a connect-back to the head's listener (head
    # runtime's zygote).
    inherited_fd = os.environ.get("RAY_TPU_ZYGOTE_FD")
    # Pre-import the worker runtime + serialization stack.  Everything
    # here must be thread-free and fork-safe; jax/torch are NOT on this
    # list by design.
    import cloudpickle  # noqa: F401
    import numpy  # noqa: F401

    import ray_tpu  # noqa: F401  (public API surface user tasks touch first)
    import ray_tpu._native  # noqa: F401  (ctypes arena binding: dlopen once)
    import ray_tpu._private.object_plane  # noqa: F401
    import ray_tpu._private.peer  # noqa: F401
    import ray_tpu._private.log_monitor  # noqa: F401
    import ray_tpu._private.runtime  # noqa: F401  (worker_main imports it for _worker_mode)
    import ray_tpu._private.runtime_env  # noqa: F401
    import ray_tpu._private.serialization  # noqa: F401
    import ray_tpu._private.store  # noqa: F401
    import ray_tpu._private.worker_proc  # noqa: F401
    import ray_tpu.exceptions  # noqa: F401
    from ray_tpu._private import faults
    from ray_tpu._private import wire

    faults.set_process_tag("zygote")

    if inherited_fd is not None:
        from multiprocessing.connection import Connection

        conn = wire.wrap(Connection(int(inherited_fd)))
    else:
        addr = (
            os.environ["RAY_TPU_DRIVER_HOST"],
            int(os.environ["RAY_TPU_DRIVER_PORT"]),
        )
        authkey = bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
        conn = wire.connect(addr, authkey)
    conn.send(("zygote", os.getpid()))
    children: dict = {}  # pid -> wid

    def reap() -> None:
        while children:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                children.clear()
                return
            if pid == 0:
                return
            wid = children.pop(pid, None)
            if wid is not None:
                try:
                    rc = os.waitstatus_to_exitcode(status)
                except ValueError:
                    rc = -1
                try:
                    conn.send(("worker_exited", wid, rc))
                except OSError:
                    os._exit(0)

    while True:
        try:
            ready = conn.poll(1.0)
        except OSError:
            os._exit(0)
        reap()
        if not ready:
            continue
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            os._exit(0)  # owner gone; children follow via their own pdeathsig
        if isinstance(msg, tuple) and msg and msg[0] == "arena_fd":
            # The daemon's node-arena fd follows as an SCM_RIGHTS
            # ancillary message on this AF_UNIX pipe: hold it open so
            # every forked worker inherits it and maps the store without
            # resolving the path (store.py prefers RAY_TPU_ARENA_FD).
            from ray_tpu._private import netutil

            try:
                afd = netutil.recv_fd(conn)
                os.environ["RAY_TPU_ARENA_FD"] = str(afd)
            except (OSError, EOFError, ValueError):
                pass  # workers fall back to opening the arena by path
            continue
        if not (isinstance(msg, tuple) and msg and msg[0] == "fork"):
            continue
        _, wid, overrides, out_path, err_path = msg
        pid = os.fork()
        if pid == 0:
            try:
                conn.close()
            except Exception:
                pass
            _child_entry(overrides, out_path, err_path)
            os._exit(0)  # unreachable; _child_entry never returns
        children[pid] = wid
        try:
            # drop -> the ("forked", ...) reply is lost while both zygote
            # and child live: the owner's pid-less handle must reap via
            # its grace window.  error (an OSError) -> zygote exit, the
            # conn-break twin of the same scenario.
            if not (
                faults.ENABLED
                and faults.point("zygote.forked", key=wid) == "drop"
            ):
                conn.send(("forked", wid, pid))
        except OSError:
            os._exit(0)


if __name__ == "__main__":
    main()
