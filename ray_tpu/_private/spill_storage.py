"""Pluggable spill targets for the object store.

ray: python/ray/_private/external_storage.py:185 — the reference spills
plasma objects to local disk OR external storage (S3/URI) behind one
interface.  Same shape here: the OwnerStore's reclaim path talks to a
SpillStorage; the default is a local directory, and any fsspec-style URI
(s3://, gs://, file://) selects the external backend via the
RAY_TPU_SPILL_STORAGE_URI knob.  file:// works with zero dependencies;
other schemes use `fsspec` when importable and fail with guidance when
not (this image ships no cloud SDKs).
"""

from __future__ import annotations

import os
import shutil
from typing import Optional


class SpillStorage:
    """put/get/delete of packed object images by locator string."""

    def put(self, object_id: str, data) -> str:  # data: bytes-like
        """Persist `data`; returns the locator later passed to get/delete."""
        raise NotImplementedError

    def get(self, locator: str) -> bytes:
        raise NotImplementedError

    def delete(self, locator: str) -> None:
        raise NotImplementedError

    def destroy(self) -> None:
        """Session teardown: drop everything this session spilled."""


class LocalSpillStorage(SpillStorage):
    """File-per-object under a session-scoped directory (the default)."""

    def __init__(self, directory: str):
        self.dir = directory

    def _path(self, object_id: str) -> str:
        return os.path.join(self.dir, object_id.replace(":", "_"))

    def put(self, object_id: str, data) -> str:  # data: bytes-like
        os.makedirs(self.dir, exist_ok=True)
        path = self._path(object_id)
        with open(path, "wb") as f:
            f.write(data)
        return path

    def get(self, locator: str) -> bytes:
        with open(locator, "rb") as f:
            return f.read()

    def delete(self, locator: str) -> None:
        try:
            os.unlink(locator)
        except OSError:
            pass

    def destroy(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)


class URISpillStorage(SpillStorage):
    """External storage by URI prefix (ray: external_storage.py's
    ExternalStorageSmartOpenImpl intent).  file:// is handled natively;
    other schemes ride fsspec when importable."""

    def __init__(self, base_uri: str, session: str):
        self.base = base_uri.rstrip("/") + f"/raytpu-spill-{session}"
        self.scheme = base_uri.split("://", 1)[0] if "://" in base_uri else "file"
        self._fs = None
        if self.scheme != "file":
            try:
                import fsspec

                self._fs = fsspec.filesystem(self.scheme)
            except Exception as e:  # noqa: BLE001 — actionable guidance
                raise ValueError(
                    f"spill URI scheme {self.scheme!r} needs the fsspec "
                    f"package (and its {self.scheme} backend) installed; "
                    "this environment has neither — use a file:// URI or "
                    "the default local spill directory"
                ) from e

    def _local_path(self, uri: str) -> str:
        return uri[len("file://"):] if uri.startswith("file://") else uri

    def put(self, object_id: str, data) -> str:  # data: bytes-like
        locator = f"{self.base}/{object_id.replace(':', '_')}"
        if self._fs is None:
            path = self._local_path(locator)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "wb") as f:
                f.write(data)
        else:
            with self._fs.open(locator, "wb") as f:
                f.write(data)
        return locator

    def get(self, locator: str) -> bytes:
        if self._fs is None:
            with open(self._local_path(locator), "rb") as f:
                return f.read()
        with self._fs.open(locator, "rb") as f:
            return f.read()

    def delete(self, locator: str) -> None:
        try:
            if self._fs is None:
                os.unlink(self._local_path(locator))
            else:
                self._fs.rm(locator)
        except Exception:
            pass

    def destroy(self) -> None:
        try:
            if self._fs is None:
                shutil.rmtree(self._local_path(self.base), ignore_errors=True)
            else:
                self._fs.rm(self.base, recursive=True)
        except Exception:
            pass


def make_spill_storage(
    spill_dir: Optional[str], session: str
) -> Optional[SpillStorage]:
    """Backend per the spill_storage_uri knob; None disables spilling."""
    from ray_tpu._private import config as _config

    uri = _config.get("spill_storage_uri")
    if uri:
        return URISpillStorage(uri, session)
    if spill_dir is None:
        return None
    return LocalSpillStorage(spill_dir)
