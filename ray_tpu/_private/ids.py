"""Identifiers for tasks, objects, actors, nodes, and workers.

TPU-native analogue of the reference's ID scheme (ray: src/ray/common/id.h).
We keep the same conceptual structure -- an ObjectID embeds the ID of the task
that produces it plus a return index, so ownership and lineage can be derived
from the ID itself -- but use compact hex strings instead of 28-byte binary
blobs since our control plane is in-process / DCN-gRPC, not a C++ hot path.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_counter = 0


def _fresh(prefix: str) -> str:
    global _counter
    with _lock:
        _counter += 1
        n = _counter
    return f"{prefix}-{os.getpid():x}-{n:x}-{os.urandom(4).hex()}"


def task_id() -> str:
    return _fresh("t")


def actor_id() -> str:
    return _fresh("a")


def object_id(producing_task: str | None = None, index: int = 0) -> str:
    if producing_task is not None:
        return f"o:{producing_task}:{index}"
    return _fresh("o")


def node_id() -> str:
    return _fresh("n")


def worker_id() -> str:
    return _fresh("w")


def placement_group_id() -> str:
    return _fresh("pg")
