"""Identifiers for tasks, objects, actors, nodes, and workers.

TPU-native analogue of the reference's ID scheme (ray: src/ray/common/id.h).
We keep the same conceptual structure -- an ObjectID embeds the ID of the task
that produces it plus a return index, so ownership and lineage can be derived
from the ID itself -- but use compact hex strings instead of 28-byte binary
blobs since our control plane is in-process / DCN-gRPC, not a C++ hot path.

Uniqueness comes from (pid, per-process counter, per-process random tag).
The tag is drawn from os.urandom ONCE per process (re-drawn after fork):
ids sit on the submit hot path, and os.urandom is a GIL-releasing syscall
per call — on a contended host every one is a preemption point (profiled
at ~0.2ms p50 wall per call on the multi-client bench; the reference
draws task ids from a process-seeded generator for the same reason).
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_counter = 0
_pid = -1
_tag = ""


def _fresh(prefix: str) -> str:
    global _counter, _pid, _tag
    with _lock:
        pid = os.getpid()
        if pid != _pid:
            # First id in this process (or first after a fork — children
            # inherit the parent's tag and counter, which would collide).
            _pid = pid
            _tag = os.urandom(4).hex()
            _counter = 0
        _counter += 1
        n = _counter
        tag = _tag
    return f"{prefix}-{pid:x}-{n:x}-{tag}"


def task_id() -> str:
    return _fresh("t")


def actor_id() -> str:
    return _fresh("a")


def object_id(producing_task: str | None = None, index: int = 0) -> str:
    if producing_task is not None:
        return f"o:{producing_task}:{index}"
    return _fresh("o")


def node_id() -> str:
    return _fresh("n")


def worker_id() -> str:
    return _fresh("w")


def placement_group_id() -> str:
    return _fresh("pg")
