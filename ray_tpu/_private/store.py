"""Object store: owner-side memory store + shared-memory (plasma-lite) store.

TPU-native re-design of the reference's two-tier object plane:
  * small objects live in the owner's in-process memory store
    (ray: src/ray/core_worker/store_provider/memory_store/memory_store.h:43);
  * large objects live as files under /dev/shm which any worker process on the
    host can mmap zero-copy (ray: src/ray/object_manager/plasma/store.h:55).

Unlike plasma we do not run a separate store process with fd-passing: on TPU
hosts the store's clients are a handful of per-host worker processes, so a
file-per-object segment in tmpfs gives the same zero-copy mmap semantics with
radically less machinery. Eviction/spilling policies layer on top (see
spill_to below, mirroring ray: src/ray/raylet/local_object_manager.h:110).
"""

from __future__ import annotations

import mmap
import os
import pickle
import shutil
import struct
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import faults
from ray_tpu._private import lock_watchdog
from ray_tpu._private import serialization as ser

from ray_tpu._private import config as _config

# same knob as ray: max_direct_call_object_size
# (env RAY_TPU_MAX_DIRECT_CALL_OBJECT_SIZE / _system_config) — a FUNCTION,
# not an import-time constant: init()'s imports run before
# set_system_config, so a frozen module constant would ignore overrides.
def inline_threshold() -> int:
    return _config.get("max_direct_call_object_size")


def _default_shm_root() -> str:
    if os.path.isdir("/dev/shm") and os.access("/dev/shm", os.W_OK):
        return "/dev/shm"
    return tempfile.gettempdir()


def _default_capacity(shm_dir: str) -> int:
    """30% of the filesystem's free space at init (ray: plasma defaults to
    30% of system memory, object_manager default_object_store_memory)."""
    try:
        st = os.statvfs(shm_dir)
        return int(st.f_bavail * st.f_frsize * 0.3)
    except OSError:
        return 2 * 1024**3


class _WaitToken:
    """One blocked wait() call.  Each token carries its OWN event so a
    completion wakes only the waiters it satisfied — a shared condition
    with notify_all turns N waiting client threads into N wakeups per
    task completion, a measured 4x throughput collapse at 4 clients."""

    __slots__ = ("need", "event")

    def __init__(self, need: int):
        self.need = need
        self.event = threading.Event()


class SealedObject:
    """A stored, immutable object (serialized form + keepalive handles)."""

    __slots__ = ("payload", "buffers", "_keepalive", "size")

    def __init__(self, payload, buffers, keepalive=None):
        self.payload = payload
        self.buffers = buffers
        self._keepalive = keepalive
        self.size = len(payload) + sum(len(b) for b in buffers)

    def deserialize(self, ref_factory=None) -> Any:
        return ser.deserialize(self.payload, self.buffers, ref_factory)


# ---------------------------------------------------------------------------
# transfer boards: shared-memory progress ledger for in-flight pulls
#
# A node that is PULLING an object can simultaneously RE-SERVE the chunks it
# has already landed (pipelined tree/chain broadcast, ray: push_manager.h:29
# chunked push pipelining).  The puller (a worker process) and the server
# (the node daemon / the head's handshake thread) are different processes
# sharing the node store, so progress is published through a tiny mmap'd
# board file next to the object: backend + total + arena offset + a
# monotonically advancing watermark of verified bytes.  The data itself is
# the pull's real receive buffer (the arena pending slot or the .tmp file)
# — the relay path adds ZERO extra copies.

_BOARD_MAGIC = b"RTPB"
_BOARD_VER = 1
_BOARD_FMT = "<4sHHQQQII"  # magic, ver, backend, total, arena_off, wm, state, pid
_BOARD_SIZE = struct.calcsize(_BOARD_FMT)  # 40
_BOARD_WM_OFF = 24  # byte offset of the watermark field (8-aligned)
_BOARD_STATE_OFF = 32
BOARD_FILE_BACKEND = 0
BOARD_ARENA_BACKEND = 1


class _PullBoard:
    """Writer side of a transfer board (lives in the pulling process)."""

    __slots__ = ("path", "_mm", "_wm")

    def __init__(self, path: str, backend: int, total: int, arena_off: int):
        self.path = path
        with open(path, "wb+") as f:
            f.write(
                struct.pack(
                    _BOARD_FMT, _BOARD_MAGIC, _BOARD_VER, backend, total,
                    arena_off, 0, 0, os.getpid(),
                )
            )
            f.flush()
            self._mm = mmap.mmap(f.fileno(), _BOARD_SIZE)
        self._wm = 0

    def advance(self, n: int) -> None:
        """Publish n more verified bytes.  The data write happens-before
        this store on the same host (one page-cache), so a reader that
        observes the new watermark observes the bytes under it."""
        self._wm += n
        struct.pack_into("<Q", self._mm, _BOARD_WM_OFF, self._wm)

    def fail(self) -> None:
        try:
            struct.pack_into("<I", self._mm, _BOARD_STATE_OFF, 1)
        except ValueError:
            pass  # already closed

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class BoardReader:
    """Server side of a transfer board: maps the in-flight pull's receive
    buffer read-only and tracks the writer's watermark.  Constructed by
    ShmStore.read_board in the SERVING process (daemon / head)."""

    __slots__ = ("path", "total", "_mm", "_data", "_keepalive")

    def __init__(self, path: str, total: int, data: memoryview, mm, keepalive):
        self.path = path
        self.total = total
        self._mm = mm
        self._data = data
        self._keepalive = keepalive

    def watermark(self) -> int:
        try:
            wm = struct.unpack_from("<Q", self._mm, _BOARD_WM_OFF)[0]
        except ValueError:
            return 0
        return min(wm, self.total)

    def failed(self) -> bool:
        try:
            return struct.unpack_from("<I", self._mm, _BOARD_STATE_OFF)[0] != 0
        except ValueError:
            return True

    def gone(self) -> bool:
        """The writer finished (sealed + unlinked the board) or died and
        was cleaned up.  The reader's own mappings stay valid (the inode
        lives while mapped), so a board at watermark==total can still be
        drained after it is gone."""
        return not os.path.exists(self.path)

    def data(self, off: int, n: int) -> memoryview:
        return self._data[off : off + n]

    def close(self) -> None:
        self._data = memoryview(b"")
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass


class PullSink:
    """One in-flight pull's receive state: the writable buffer (arena
    pending slot or .tmp mmap), the optional transfer board, and the
    commit/abort lifecycle.  Produced by ShmStore.start_pull; driven by
    object_plane.fetch_object."""

    __slots__ = ("store", "oid", "view", "total", "_board", "_backend",
                 "_tmp_path", "_done", "on_commit")

    def __init__(self, store, oid, view, total, board, backend, tmp_path):
        self.store = store
        self.oid = oid
        self.view = view
        self.total = total
        self._board = board
        self._backend = backend
        self._tmp_path = tmp_path
        self._done = False
        self.on_commit = None  # OwnerStore accounting hook

    def advance(self, n: int) -> None:
        if self._board is not None:
            self._board.advance(n)

    def commit(self) -> None:
        """Seal the landed bytes.  After commit the sink's buffer is gone:
        writes through the sink raise (sealed-buffer immutability)."""
        if self._done:
            return
        self._done = True
        self.view = None  # release the writable buffer before sealing
        if self._backend == BOARD_ARENA_BACKEND:
            self.store.arena.seal(self.oid)
        else:
            os.rename(self._tmp_path, self.store._path(self.oid))
        # Seal-then-unlink: a relay reader that loses the board re-checks
        # the sealed copy and finds it (never a window with neither).
        if self._board is not None:
            self._board.close()
        if self.on_commit is not None:
            self.on_commit()

    def abort(self) -> None:
        """Reclaim the pending allocation; downstream relay readers see
        the failed state (or the missing board) and fall back."""
        if self._done:
            return
        self._done = True
        self.view = None
        if self._board is not None:
            self._board.fail()
        if self._backend == BOARD_ARENA_BACKEND:
            try:
                self.store.arena.delete(self.oid)
            except Exception:
                pass
        else:
            try:
                os.unlink(self._tmp_path)
            except OSError:
                pass
        if self._board is not None:
            self._board.close()


class ShmStore:
    """Host-shared object segments, mmap'ed zero-copy on read.

    Two backends behind one surface:
    - native ARENA (default when the C++ component builds,
      ray_tpu/_native/shm_arena.cpp): one mmap per process for the whole
      session; C++ owns allocation + the object table, Python slices data
      out of the single mapping — no per-object open/mmap syscalls;
    - file-per-object tmpfs segments (fallback + overflow): atomic
      rename-seal, still zero-copy via per-object mmap.

    The driver decides (capacity= given + native available + env
    RAY_TPU_NATIVE_STORE != 0) and creates the arena file; workers join
    whatever exists on disk, so every process of a session agrees.
    """

    # Arena ids are fixed-width slots in C++ (ID_MAX); longer ids overflow
    # to the file backend transparently.
    _ARENA_ID_MAX = 47

    def __init__(
        self,
        session_name: str,
        root: Optional[str] = None,
        capacity: Optional[int] = None,
        dir_path: Optional[str] = None,
    ):
        """dir_path overrides the derived location — each NODE owns a
        distinct store directory (daemons pass their node-scoped dir to
        their workers via RAY_TPU_STORE_DIR), so nothing resolves an object
        through a path shared across nodes; cross-node reads go through the
        object transfer plane (object_plane.py)."""
        self.dir = dir_path or os.path.join(
            root or _default_shm_root(), f"raytpu-{session_name}"
        )
        os.makedirs(self.dir, exist_ok=True)
        self.arena = None
        arena_path = os.path.join(self.dir, "arena")
        if _config.get("native_store"):
            try:
                from ray_tpu._native.arena import Arena

                if capacity is not None:
                    # ~5.3MB of table metadata + the data heap
                    self.arena = Arena(arena_path, capacity=capacity + 8 * 1024 * 1024)
                else:
                    # Joining processes prefer the fd their node daemon
                    # passed over the AF_UNIX spawn channels (SCM_RIGHTS,
                    # netutil.send_fd): the map works even when the store
                    # path is not resolvable from this process's view.
                    # Any failure here falls back to the classic path
                    # open, and failing THAT leaves arena=None — the
                    # file-per-object copy path.
                    fd_env = os.environ.get("RAY_TPU_ARENA_FD")
                    arena = None
                    if fd_env and os.environ.get("RAY_TPU_STORE_DIR") == self.dir:
                        try:
                            if faults.ENABLED:
                                # error -> fd map fails -> path fallback
                                faults.point("arena.map", key=self.dir)
                            arena = Arena(arena_path, fd=int(fd_env))
                        except Exception:
                            arena = None
                    if arena is None and os.path.exists(arena_path):
                        arena = Arena(arena_path)
                    self.arena = arena
            except Exception:
                self.arena = None  # toolchain/platform unavailable: files

    def _use_arena(self, object_id: str) -> bool:
        return self.arena is not None and len(object_id) <= self._ARENA_ID_MAX

    def _path(self, object_id: str) -> str:
        return os.path.join(self.dir, object_id.replace(":", "_"))

    def create(self, object_id: str, payload: bytes, buffers: List[pickle.PickleBuffer]) -> int:
        size = ser.packed_size(payload, buffers)
        if self._use_arena(object_id):
            try:
                try:
                    view = self.arena.allocate(object_id, size)
                except FileExistsError:
                    if self.arena.is_pending(object_id):
                        # The previous creator died between allocate and
                        # seal: the stale PENDING slot would otherwise make
                        # this id permanently unwritable AND unreadable.
                        self.arena.delete(object_id)
                        view = self.arena.allocate(object_id, size)
                    else:
                        return size  # sealed by the same producer re-run
                try:
                    ser.pack_into(view, payload, buffers)
                finally:
                    del view  # release the buffer before any later close()
                self.arena.seal(object_id)
                return size
            except MemoryError:
                pass  # fragmentation overflow: fall through to a file
            except RuntimeError:
                pass  # poisoned arena: file fallback
        path = self._path(object_id)
        tmp = path + ".tmp"
        with open(tmp, "wb+") as f:
            f.truncate(size)
            with mmap.mmap(f.fileno(), size) as m:
                ser.pack_into(memoryview(m), payload, buffers)
        os.rename(tmp, path)  # atomic "seal"
        return size

    def contains(self, object_id: str) -> bool:
        if self._use_arena(object_id) and self.arena.contains(object_id):
            return True
        return os.path.exists(self._path(object_id))

    def get(self, object_id: str) -> Optional[SealedObject]:
        if self._use_arena(object_id):
            pinned = self.arena.get(object_id)
            if pinned is not None:
                # The PinnedView pins the arena bytes for the SealedObject's
                # lifetime: delete/spill under live readers defers the free.
                # path=arena_map with ZERO bytes: the read maps the sealed
                # buffer in place — the counter records the event so the
                # zero-copy claim is counted, not asserted.
                from ray_tpu._private import telemetry as _telemetry

                _telemetry.count_copy("arena_map", 0)
                payload, buffers = ser.unpack(pinned.view)
                return SealedObject(payload, buffers, keepalive=pinned)
        path = self._path(object_id)
        try:
            f = open(path, "rb")
        except FileNotFoundError:
            return None
        try:
            size = os.fstat(f.fileno()).st_size
            m = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        finally:
            f.close()
        payload, buffers = ser.unpack(memoryview(m))
        return SealedObject(payload, buffers, keepalive=m)

    def _allocate_for_pull(self, object_id: str, total: int):
        """(view, offset) of an arena slot for an incoming pull, or
        (None, 0) when the object is (or becomes) sealed.  A PENDING slot
        usually means ANOTHER LIVE PULLER (workers of one node can race on
        the same arg ref — each process only serializes its own pulls):
        deleting it would yank memory out from under its writer, so wait
        for its seal and only reclaim a slot that stays pending past the
        transfer deadline (dead puller)."""
        import time

        try:
            return self.arena.allocate_at(object_id, total)
        except FileExistsError:
            pass
        deadline = time.monotonic() + _config.get("object_transfer_timeout_s")
        while time.monotonic() < deadline:
            if self.arena.contains(object_id):
                return None, 0  # concurrent puller sealed it
            if not self.arena.is_pending(object_id):
                # slot vanished (freed): take it
                try:
                    return self.arena.allocate_at(object_id, total)
                except FileExistsError:
                    continue
            time.sleep(0.05)
        # stale PENDING past the transfer deadline: the writer is dead
        self.arena.delete(object_id)
        return self.arena.allocate_at(object_id, total)

    def get_raw(self, object_id: str) -> Optional[Tuple[Any, Any]]:
        """(buffer, keepalive) of the PACKED segment bytes, or None.

        The transfer plane ships segments verbatim — the receiver seals the
        identical packed image, so no serialize/deserialize on either side.
        """
        if self._use_arena(object_id):
            pinned = self.arena.get(object_id)
            if pinned is not None:
                return pinned.view, pinned
        path = self._path(object_id)
        try:
            f = open(path, "rb")
        except FileNotFoundError:
            return None
        try:
            size = os.fstat(f.fileno()).st_size
            m = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        finally:
            f.close()
        return memoryview(m), m

    def create_from_stream(self, object_id: str, total: int, fill) -> None:
        """Allocate, then let `fill(buffer)` land the packed bytes straight
        in shared memory — the pull path passes a recv_into filler, so the
        KERNEL's copy into the arena mmap is the only receive-side copy
        (create_from_chunks still stages through a bounce buffer; at 1-core
        loopback ceilings that staging copy is ~40% of broadcast time).
        fill(None) means the object is already sealed locally (skip).
        On a fill failure the allocation is reclaimed, not left pending."""
        view = None
        if self._use_arena(object_id):
            try:
                view, _off = self._allocate_for_pull(object_id, total)
                if view is None and self.arena.contains(object_id):
                    fill(None)
                    return
            except (MemoryError, RuntimeError):
                view = None  # fragmentation/poison: file fallback
        if view is not None:
            try:
                fill(view)
            except BaseException:
                del view
                self.arena.delete(object_id)  # reclaim the pending slot
                raise
            del view
            self.arena.seal(object_id)
            return
        path = self._path(object_id)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb+") as f:
                f.truncate(total)
                with mmap.mmap(f.fileno(), total) as m:
                    fill(memoryview(m))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.rename(tmp, path)

    def create_from_chunks(self, object_id: str, total: int, chunks) -> None:
        """Allocate-then-fill from an iterator of byte chunks (the pull
        receive path): the arena view (or tmpfs mmap) is the receive buffer
        — chunks land directly in shared memory, one copy total."""
        view = None
        if self._use_arena(object_id):
            try:
                view, _off = self._allocate_for_pull(object_id, total)
                if view is None and self.arena.contains(object_id):
                    for _ in chunks:
                        pass  # already sealed locally: drain politely
                    return
            except (MemoryError, RuntimeError):
                view = None  # fragmentation/poison: file fallback
        if view is not None:
            try:
                off = 0
                for b in chunks:
                    view[off : off + len(b)] = b
                    off += len(b)
            finally:
                del view
            self.arena.seal(object_id)
            return
        path = self._path(object_id)
        tmp = path + ".tmp"
        with open(tmp, "wb+") as f:
            f.truncate(total)
            with mmap.mmap(f.fileno(), total) as m:
                off = 0
                for b in chunks:
                    m[off : off + len(b)] = b
                    off += len(b)
        os.rename(tmp, path)

    # -- transfer boards (pipelined relay broadcast) ----------------------

    def _board_path(self, object_id: str) -> str:
        return self._path(object_id) + ".prog"

    def start_pull(self, object_id: str, total: int, board: bool = True):
        """Open a PullSink for an incoming transfer: the receive buffer IS
        the final resting place (arena pending slot or the .tmp file), and
        the optional transfer board publishes landed-byte progress so this
        node's server can relay the prefix mid-transfer.  Returns None
        when the object is already sealed locally (a sibling pull landed
        it — the caller abandons the body)."""
        view = None
        off = 0
        backend = BOARD_FILE_BACKEND
        tmp_path = None
        if self._use_arena(object_id):
            try:
                view, off = self._allocate_for_pull(object_id, total)
                if view is None and self.arena.contains(object_id):
                    return None
                backend = BOARD_ARENA_BACKEND
            except (MemoryError, RuntimeError):
                view = None  # fragmentation/poison: file fallback
        if view is None:
            backend = BOARD_FILE_BACKEND
            tmp_path = self._path(object_id) + ".tmp"
            with open(tmp_path, "wb+") as f:
                f.truncate(total)
                view = memoryview(mmap.mmap(f.fileno(), total)) if total else memoryview(bytearray())
        pb = None
        if board and total:
            try:
                pb = _PullBoard(self._board_path(object_id), backend, total, off)
            except OSError:
                pb = None  # board is an optimization; the pull proceeds
        return PullSink(self, object_id, view, total, pb, backend, tmp_path)

    def read_board(self, object_id: str) -> Optional[BoardReader]:
        """Open the serving side of an in-flight pull's transfer board, or
        None when no live board exists.  The returned reader maps the
        pull's receive buffer read-only; its mappings survive the writer's
        seal/unlink (inodes live while mapped), so a fully-watermarked
        board drains even after the writer finishes."""
        path = self._board_path(object_id)
        try:
            f = open(path, "rb")
        except OSError:
            return None
        try:
            hdr = f.read(_BOARD_SIZE)
            if len(hdr) < _BOARD_SIZE:
                return None
            magic, ver, backend, total, arena_off, _wm, state, _pid = struct.unpack(
                _BOARD_FMT, hdr
            )
            if magic != _BOARD_MAGIC or ver != _BOARD_VER or state != 0 or not total:
                return None
            mm = mmap.mmap(f.fileno(), _BOARD_SIZE, prot=mmap.PROT_READ)
        finally:
            f.close()
        if backend == BOARD_ARENA_BACKEND:
            if self.arena is None:
                mm.close()
                return None
            data = self.arena.peek(arena_off, total)
            keepalive = None
        else:
            tmp = self._path(object_id) + ".tmp"
            try:
                df = open(tmp, "rb")
            except OSError:
                mm.close()
                return None
            try:
                size = os.fstat(df.fileno()).st_size
                if size < total:
                    mm.close()
                    return None
                dmm = mmap.mmap(df.fileno(), total, prot=mmap.PROT_READ)
            finally:
                df.close()
            data = memoryview(dmm)
            keepalive = dmm
        return BoardReader(path, total, data, mm, keepalive)

    def delete(self, object_id: str) -> None:
        if self._use_arena(object_id) and self.arena.delete(object_id):
            return
        try:
            os.unlink(self._path(object_id))
        except FileNotFoundError:
            pass

    def destroy(self) -> None:
        if self.arena is not None:
            self.arena.close()
        shutil.rmtree(self.dir, ignore_errors=True)


class OwnerStore:
    """The owner's view of every object it created.

    Combines the in-process memory store (small objects), the shm directory
    (large objects) and the owner-side reference count
    (ray: src/ray/core_worker/reference_count.h:61 -- we implement the owner
    bookkeeping; borrower chains collapse to owner-mediated counts because all
    submissions flow through the owner in this runtime).
    """

    def __init__(
        self,
        session_name: str,
        spill_dir: Optional[str] = None,
        capacity_bytes: Optional[int] = None,
    ):
        if capacity_bytes is None:
            capacity_bytes = _config.get("object_store_memory") or _default_capacity(
                _default_shm_root()
            )
        self.shm = ShmStore(session_name, capacity=capacity_bytes)
        self._mem: Dict[str, SealedObject] = {}
        self._in_shm: Dict[str, int] = {}  # id -> size
        self._spilled: Dict[str, str] = {}  # id -> file path
        self._refcount: Dict[str, int] = {}
        # Releases that arrived before their object was registered (the
        # control plane has per-connection FIFO only — see remove_ref),
        # consumed by the matching add_ref.
        self._early_dels: Dict[str, int] = {}
        self._available = threading.Condition()
        self._ready: Dict[str, bool] = {}
        # wait() bookkeeping: per-oid waiter tokens so a completion is O(its
        # waiters), and each woken waiter checks one counter instead of
        # rescanning its whole oid list (wakeup-storm O(n^2) otherwise).
        self._oid_waiters: Dict[str, List["_WaitToken"]] = {}
        self._errors: Dict[str, Any] = {}  # id -> exception to raise on get
        # Pluggable spill backend (ray: external_storage.py:185): local
        # directory by default, URI-selected external storage via the
        # spill_storage_uri knob; locators are stored in _spilled.
        from ray_tpu._private.spill_storage import make_spill_storage

        self._spill_storage = make_spill_storage(spill_dir, session_name)
        # Locators to delete OFF the lock (an external backend's rm may be
        # a network call; running it under self._lock would stall every
        # store operation) — drained by the reclaim thread.
        self._spill_deletes: List[str] = []
        self._lock = lock_watchdog.make_lock("OwnerStore._lock", rlock=True)
        # Capacity + LRU clock (ray: plasma_allocator.h:44 footprint cap,
        # eviction_policy.h:105 LRUCache).  Overridable via env for tests/ops.
        self.capacity = capacity_bytes
        self._clock = 0
        self._last_access: Dict[str, int] = {}
        self._shm_bytes = 0  # running total of _in_shm values
        self._reserved = 0  # bytes admitted by _make_room but not yet sealed
        # Background reclaimer: the worker-sealed path (mark_shm_sealed) runs
        # on the runtime io thread under the global runtime lock — spill disk
        # I/O there would stall all scheduling, so it only signals this
        # thread (ray: local_object_manager spills async for the same
        # reason).  Strict puts still reclaim inline: admission control must
        # be synchronous.
        self._reclaim_event = threading.Event()
        self._reclaim_thread = threading.Thread(
            target=self._reclaim_loop, daemon=True, name="raytpu-spill"
        )
        self._destroyed = False
        # Object lifecycle observer (runtime._on_store_lifecycle): called
        # as hook(oid, event, nbytes) on spill/restore/free so the ledger's
        # event ring and the chrome timeline see store transitions.  MUST
        # stay lock-light — _free fires it under self._lock.
        self.on_lifecycle = None
        self._reclaim_thread.start()

    def _lifecycle(self, object_id: str, event: str, nbytes) -> None:
        hook = self.on_lifecycle
        if hook is not None:
            try:
                hook(object_id, event, nbytes)
            except Exception:
                pass

    # -- refcounting ---------------------------------------------------------

    def add_ref(self, object_id: str, n: int = 1) -> None:
        with self._lock:
            early = self._early_dels.pop(object_id, 0)
            if early:
                # Consume buffered releases that raced ahead of this add
                # (see remove_ref): each buffered del corresponds to
                # exactly one add still in flight.
                consumed = min(early, n)
                if early - consumed:
                    self._early_dels[object_id] = early - consumed
                n -= consumed
                if n <= 0:
                    # The adds and their buffered releases cancelled out:
                    # if nothing else holds the object, free any bytes that
                    # were registered between the buffered del and this add
                    # (otherwise they'd sit at refcount 0 forever — the
                    # balancing remove_ref already fired).
                    if object_id not in self._refcount:
                        self._free(object_id)
                    return
            self._refcount[object_id] = self._refcount.get(object_id, 0) + n

    def remove_ref(self, object_id: str, n: int = 1) -> bool:
        """Returns True when the count hit zero and the object was freed.

        A release for an object this store has never seen is BUFFERED, not
        applied: the control plane is per-connection FIFO but has no
        cross-connection ordering, so a caller's balancing del (its conn)
        can overtake the callee's registering direct_seal/promote/guard-add
        (the callee's conn).  Applying it eagerly would let the later add
        resurrect the count to a permanently-leaked 1.  The buffered del is
        consumed by the matching add when it lands (add_ref)."""
        with self._lock:
            known = (
                object_id in self._refcount
                or object_id in self._ready
                or object_id in self._errors
            )
            if not known:
                self._early_dels[object_id] = self._early_dels.get(object_id, 0) + n
                return False
            c = self._refcount.get(object_id, 0) - n
            if c > 0:
                self._refcount[object_id] = c
                return False
            self._refcount.pop(object_id, None)
            self._free(object_id)
            return True

    def refcount(self, object_id: str) -> int:
        return self._refcount.get(object_id, 0)

    def _free(self, object_id: str) -> None:
        had = object_id in self._mem or object_id in self._spilled
        self._mem.pop(object_id, None)
        size = self._in_shm.pop(object_id, None)
        if size is not None:
            self._shm_bytes -= size
            self.shm.delete(object_id)
        p = self._spilled.pop(object_id, None)
        if p and self._spill_storage is not None:
            self._spill_deletes.append(p)  # deleted off-lock by the reclaimer
            self._reclaim_event.set()
        self._ready.pop(object_id, None)
        self._errors.pop(object_id, None)
        self._last_access.pop(object_id, None)
        if had or size is not None:
            self._lifecycle(object_id, "free", size)

    # -- put / seal ----------------------------------------------------------

    def _touch(self, object_id: str) -> None:
        self._clock += 1
        self._last_access[object_id] = self._clock

    def _account_shm(self, object_id: str, size: int) -> None:
        """Record id->size under the lock, displacing any prior entry.
        Re-puts happen (lineage re-execution re-seals surviving return ids);
        blindly adding would double-count _shm_bytes forever."""
        prev = self._in_shm.get(object_id)
        if prev is not None:
            self._shm_bytes -= prev
        self._in_shm[object_id] = size
        self._shm_bytes += size

    def _usage(self) -> int:
        return self._shm_bytes + self._reserved

    def _make_room(self, incoming: int, strict: bool, reserve: bool = False) -> None:
        """Reclaim shm (by SPILLING LRU objects to disk) until incoming fits
        under capacity.

        Spill-only, never delete: every sealed object stays retrievable via
        transparent restore.  (Deleting refcount-0 objects would race the
        seal→first-addref window — a just-created object has rc 0 until its
        ObjectRef lands; unreferenced garbage is already freed eagerly by
        remove_ref → _free, so there is nothing safe left to delete here.)

        strict: raise ObjectStoreFullError when room cannot be made (caller
        has not written yet — admission control).  Non-strict (bytes already
        on tmpfs, e.g. a worker-sealed segment or a restore): tolerate the
        overage.  reserve: on success, account `incoming` as reserved until
        the caller seals or aborts — closes the check→write TOCTOU between
        concurrent strict puts.

        Victim SELECTION runs under the lock; the spill I/O itself runs
        OUTSIDE it (the pluggable backend may be an fsspec network store —
        a blocking put under self._lock would stall every store operation;
        the concurrency lint's blocking-under-lock pass flags the old
        shape).  Reclaim stays synchronous for strict admission; only the
        lock is released around each victim's write, and the fit check +
        reservation re-run atomically afterwards.
        """
        from ray_tpu.exceptions import ObjectStoreFullError

        with self._lock:
            if strict and incoming > self.capacity:
                raise ObjectStoreFullError(
                    f"object of {incoming} bytes exceeds store capacity "
                    f"{self.capacity} bytes"
                )
        spilled: set = set()
        while True:
            with self._lock:
                if self._usage() + incoming <= self.capacity:
                    if reserve:
                        self._reserved += incoming
                    return
                by_lru = sorted(
                    self._in_shm, key=lambda o: self._last_access.get(o, 0)
                )
                victim = next((o for o in by_lru if o not in spilled), None)
            if victim is None:
                break  # nothing left to evict
            spilled.add(victim)  # never re-pick: a failed spill would spin
            self.spill(victim)  # disk/network I/O — off the store lock
        with self._lock:
            if strict and self._usage() + incoming > self.capacity:
                raise ObjectStoreFullError(
                    f"store full: {self._usage()} bytes used of "
                    f"{self.capacity}, cannot fit {incoming} "
                    f"(no spill dir or spill failed)"
                )
            if reserve:
                self._reserved += incoming

    def _reclaim_loop(self) -> None:
        while not self._destroyed:
            self._reclaim_event.wait(timeout=1.0)
            if self._destroyed:
                return
            if not self._reclaim_event.is_set():
                continue
            self._reclaim_event.clear()
            with self._lock:
                doomed, self._spill_deletes = self._spill_deletes, []
            for loc in doomed:  # off-lock: external rm may be a network call
                try:
                    self._spill_storage.delete(loc)
                except Exception:
                    pass
            try:
                self._make_room(0, strict=False)
            except Exception:
                pass  # reclaim is best-effort; next seal re-signals

    def put_serialized(
        self, object_id: str, payload: bytes, buffers: List[pickle.PickleBuffer]
    ) -> None:
        raw_size = len(payload) + sum(len(b.raw()) for b in buffers)
        if raw_size >= inline_threshold():
            # Account what the segment actually occupies (header + per-buffer
            # framing + alignment), the same figure ShmStore.create allocates
            # and _restore later records — raw bytes would undercount.
            size = ser.packed_size(payload, buffers)
            self._make_room(size, strict=True, reserve=True)
            try:
                self.shm.create(object_id, payload, buffers)
            except BaseException:
                with self._lock:
                    self._reserved -= size
                raise
            with self._lock:
                self._reserved -= size
                self._account_shm(object_id, size)
                self._touch(object_id)
            from ray_tpu._private import telemetry as _telemetry

            _telemetry.count_copy("put", size)
        else:
            obj = SealedObject(payload, [b.raw() for b in buffers])
            with self._lock:
                self._mem[object_id] = obj
        self._mark_ready(object_id)

    def put(self, object_id: str, value: Any) -> List[str]:
        payload, buffers, contained = ser.serialize(value)
        self.put_serialized(object_id, payload, buffers)
        return contained

    def put_error(self, object_id: str, err: Exception) -> None:
        with self._lock:
            self._errors[object_id] = err
        self._mark_ready(object_id)

    def mark_shm_sealed(self, object_id: str, size: int) -> None:
        """A worker already wrote the segment directly; record and publish.
        The bytes are on tmpfs already, so reclaim is best-effort and runs
        on the background spill thread — this method is called on the
        runtime io thread under the global runtime lock, where synchronous
        disk I/O would stall all scheduling."""
        with self._lock:
            self._account_shm(object_id, size)
            self._touch(object_id)
            over = self._usage() > self.capacity
        if over:
            self._reclaim_event.set()
        self._mark_ready(object_id)

    def mark_remote_sealed(self, object_id: str) -> None:
        """A worker on ANOTHER node sealed this object: publish readiness
        (gets/waits unblock) without local byte accounting — the bytes live
        in that node's store and arrive here only via the transfer plane."""
        self._mark_ready(object_id)

    def _mark_ready(self, object_id: str) -> None:
        with self._available:
            self._ready[object_id] = True
            for token in self._oid_waiters.pop(object_id, ()):
                token.need -= 1
                if token.need <= 0:
                    token.event.set()

    # -- get / wait ----------------------------------------------------------

    def is_ready(self, object_id: str) -> bool:
        return self._ready.get(object_id, False)

    def error_for(self, object_id: str):
        return self._errors.get(object_id)

    def wait(self, object_ids: List[str], num_returns: int, timeout: Optional[float]):
        """Block until num_returns of object_ids are ready. Returns the
        ready subset (may exceed num_returns).  Duplicate ids are counted
        per occurrence both at registration and in the result — consistent,
        though callers normally pass unique refs."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._available:
            pending = [o for o in object_ids if not self._ready.get(o, False)]
            satisfied = len(object_ids) - len(pending)
            if satisfied >= num_returns or not pending:
                return [o for o in object_ids if self._ready.get(o, False)]
            token = _WaitToken(num_returns - satisfied)
            for o in pending:
                self._oid_waiters.setdefault(o, []).append(token)
        # Block OUTSIDE the registration lock on the token's own event:
        # completions touching other waiters' objects never wake us.
        try:
            if deadline is None:
                token.event.wait()
            else:
                remaining = deadline - time.monotonic()
                if remaining > 0:
                    token.event.wait(remaining)
        finally:
            with self._available:
                for o in pending:
                    lst = self._oid_waiters.get(o)
                    if lst is not None:
                        try:
                            lst.remove(token)
                        except ValueError:
                            pass
                        if not lst:
                            self._oid_waiters.pop(o, None)
        return [o for o in object_ids if self._ready.get(o, False)]

    def get_sealed(self, object_id: str) -> Optional[SealedObject]:
        with self._lock:
            obj = self._mem.get(object_id)
            if obj is not None:
                return obj
            if object_id in self._in_shm:
                self._touch(object_id)
                return self.shm.get(object_id)
            p = self._spilled.get(object_id)
        if p:
            self._restore(object_id, p)
            return self.shm.get(object_id)
        return None

    # -- transfer plane hooks (object_plane.py) ------------------------------

    def get_raw_packed(self, object_id: str) -> Optional[Tuple[Any, Any]]:
        """(buffer, keepalive) of the packed bytes for serving a remote
        pull; restores from spill transparently.  None when this store has
        no copy (the object may live only on other nodes)."""
        with self._lock:
            obj = self._mem.get(object_id)
            if obj is not None:
                data = bytes(
                    ser.pack(
                        bytes(obj.payload),
                        [pickle.PickleBuffer(b) for b in obj.buffers],
                    )
                )
                return memoryview(data), data
            if object_id in self._in_shm:
                self._touch(object_id)
                return self.shm.get_raw(object_id)
            p = self._spilled.get(object_id)
        if p:
            self._restore(object_id, p)
            return self.shm.get_raw(object_id)
        return None

    def ingest_packed(self, object_id: str, total: int, chunks) -> None:
        """Land a pulled object in this store (packed image, chunked) and
        account it like any other sealed segment.  Non-strict admission:
        the object exists in the cluster and the driver asked for it — LRU
        spill makes room rather than refusing."""
        self._make_room(total, strict=False)
        self.shm.create_from_chunks(object_id, total, chunks)
        with self._lock:
            self._account_shm(object_id, total)
            self._touch(object_id)
        from ray_tpu._private import telemetry as _telemetry

        _telemetry.count_copy("pull", total)
        self._mark_ready(object_id)

    def ingest_stream(self, object_id: str, total: int, fill) -> None:
        """Streaming twin of ingest_packed (zero-staging receive)."""
        self._make_room(total, strict=False)
        self.shm.create_from_stream(object_id, total, fill)
        with self._lock:
            self._account_shm(object_id, total)
            self._touch(object_id)
        from ray_tpu._private import telemetry as _telemetry

        _telemetry.count_copy("pull", total)
        self._mark_ready(object_id)

    def start_pull(self, object_id: str, total: int):
        """OwnerStore twin of ShmStore.start_pull: same sink, plus head
        capacity admission up front and owner accounting + readiness
        publication on commit (the copy counter ticks at the single
        fetch-side site in object_plane).  Non-strict admission: the
        object exists in the cluster and the driver asked for it."""
        self._make_room(total, strict=False)
        sink = self.shm.start_pull(object_id, total)
        if sink is None:
            return None

        def _on_commit():
            with self._lock:
                self._account_shm(object_id, total)
                self._touch(object_id)
            self._mark_ready(object_id)

        sink.on_commit = _on_commit
        return sink

    def read_board(self, object_id: str):
        """Serving-side board lookup for the head's object server."""
        return self.shm.read_board(object_id)

    def has_local(self, object_id: str) -> bool:
        """Any byte-bearing copy here (mem / shm / spill)?"""
        with self._lock:
            return (
                object_id in self._mem
                or object_id in self._in_shm
                or object_id in self._spilled
            )

    # -- spilling (ray: local_object_manager.h:110 SpillObjects) -------------

    def spill(self, object_id: str) -> Optional[str]:
        if self._spill_storage is None:
            return None
        obj = self.shm.get(object_id)
        if obj is None:
            return None
        locator = self._spill_storage.put(
            object_id,
            ser.pack(  # bytearray written as-is: no extra copy under pressure
                bytes(obj.payload),
                [pickle.PickleBuffer(b) for b in obj.buffers],
            ),
        )
        with self._lock:
            size = self._in_shm.pop(object_id, None)
            if size is None:
                # Freed (remove_ref -> _free) between the unlocked read above
                # and here: recording _spilled would resurrect a dead object
                # and leak the stored image.  Queue the delete for the
                # reclaim thread — on a URI/fsspec backend it is a blocking
                # network call, and running it here would stall every store
                # operation behind this lock (the hazard _free's own
                # _spill_deletes queue exists to avoid).
                self._spill_deletes.append(locator)
                self._reclaim_event.set()
                return None
            self._spilled[object_id] = locator
            self._shm_bytes -= size
            self.shm.delete(object_id)
        from ray_tpu._private import telemetry as _telemetry

        _telemetry.count_copy("spill", size)
        self._lifecycle(object_id, "spill", size)
        return locator

    def _restore(self, object_id: str, path: str) -> None:
        data = self._spill_storage.get(path)
        # Non-strict: the object exists and must come back even when it is
        # individually larger than capacity (it got in via a worker-sealed
        # overage) — raising here would make it permanently unreadable.
        self._make_room(len(data), strict=False)
        payload, buffers = ser.unpack(memoryview(data))
        self.shm.create(object_id, bytes(payload), [pickle.PickleBuffer(b) for b in buffers])
        with self._lock:
            self._account_shm(object_id, len(data))
            self._spilled.pop(object_id, None)
            self._touch(object_id)
        self._spill_storage.delete(path)
        from ray_tpu._private import telemetry as _telemetry

        _telemetry.count_copy("restore", len(data))
        self._lifecycle(object_id, "restore", len(data))

    def shm_usage(self) -> int:
        with self._lock:
            return self._shm_bytes

    def snapshot_table(self):
        """One consistent read of the owner tables for the object ledger:
        ({oid: (location, size|None)}, {oid: refcount}, {oid: ready}).
        Spilled sizes are None here — the runtime's object_sizes map
        retains the packed size across the spill."""
        with self._lock:
            table: Dict[str, Tuple[str, Optional[int]]] = {}
            for oid, obj in self._mem.items():
                table[oid] = ("memory", obj.size)
            for oid, size in self._in_shm.items():
                table[oid] = ("shm", size)
            for oid in self._spilled:
                table[oid] = ("spilled", None)
            for oid in self._errors:
                table.setdefault(oid, ("error", None))
            return table, dict(self._refcount), dict(self._ready)

    def destroy(self) -> None:
        self._destroyed = True
        self._reclaim_event.set()
        self.shm.destroy()
        if self._spill_storage is not None:
            self._spill_storage.destroy()
