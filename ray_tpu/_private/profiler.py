"""Cluster-wide sampling profiler: collapsed-stack flamegraphs on demand.

ray: the dashboard's py-spy integration (`ray stack` / the "CPU flame
graph" button — dashboard/modules/reporter attaches py-spy to a live pid
and renders speedscope output).  Spawning an external tracer per process
doesn't fit a many-process control plane under test, so this build
samples IN-PROCESS instead: a daemon thread wakes at RAY_TPU_PROF_HZ and
walks `sys._current_frames()`, folding every thread's stack into the
classic collapsed form (`thread;mod:fn;mod:fn... count`).  Per-process
tables ship to the head as DROPPABLE `prof_push` oneways riding the v2
batch frames (the metrics_push discipline: a dead conn loses a tick,
never wedges the ownership backlog), where ProfileSink merges them into
per-node and cluster-wide flamegraphs (`ray_tpu profile`, /api/profile).

Cost model (the faults.ENABLED discipline):

  * OFF (default) — `ENABLED` is a module bool nothing checks on any hot
    path; there is no thread, no timer, no allocation.  Steady-state cost
    is exactly zero.
  * ON — one thread per process; each tick costs one _current_frames()
    walk (microseconds at typical stack depths).  Started either by the
    RAY_TPU_PROF_HZ env knob (autostart at process entry — the chaos
    soak's always-hot mode) or cluster-wide at runtime by a pubsub
    broadcast on the "profiler" channel (`ray_tpu profile` / the
    profile_start head op), so a steady-state cluster pays nothing until
    an operator asks a question.

Tables are CUMULATIVE since start(): pushes are idempotent latest-wins
snapshots, so a dropped push (head bounce, shard death) costs freshness,
never correctness.  The flight recorder folds the top stacks into every
crash dump (telemetry.flight_dump) — a chaos-killed process leaves
behind not just what it did, but where it was spending time.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# Module-bool fast path (the faults.ENABLED idiom): False means no
# sampler thread exists and nothing else in this module runs.
ENABLED: bool = False

# Sampling rate used when a start request doesn't name one (the
# "default HZ" of the acceptance bar; RAY_TPU_PROF_HZ overrides at
# autostart, the profile verb's --hz overrides per run).  Continuous
# CLUSTER-WIDE profiling pays the rate in EVERY process — on a 1-vCPU CI
# host that is ~20 samplers sharing one core — so the default follows
# the continuous-profiler convention (~10Hz, the Cloud Profiler /
# conservative py-spy regime) rather than py-spy's single-process 100Hz;
# `ray_tpu profile --hz` raises it for short interactive windows.
DEFAULT_HZ = 10.0

_lock = threading.Lock()
_thread: Optional[threading.Thread] = None
_stop = threading.Event()
_pid = os.getpid()
_hz = 0.0
_t0 = 0.0
_n_samples = 0
_n_dropped_stacks = 0
_samples: Dict[str, int] = {}

_MAX_DEPTH = 48          # frames kept per stack (deepest dropped first)
_MAX_STACKS = 4096       # distinct stacks kept in-process before pruning
_PUSH_STACKS = 512       # top-N stacks per prof_push payload


# Per-code-object label cache: the live-stack set of a program is small
# and stable, so the f_globals lookup + string build happen once per code
# object, not once per frame per sample (the sampler's hot-path budget).
# Keyed by the code object itself — module-level code is alive anyway;
# bounded clear on pathological churn (exec-heavy workloads).
_label_cache: Dict[Any, str] = {}


def _frame_label(frame) -> str:
    """`module:function` for one frame — cached per code object."""
    code = frame.f_code
    label = _label_cache.get(code)
    if label is None:
        mod = frame.f_globals.get("__name__")
        if not mod:
            mod = os.path.splitext(os.path.basename(code.co_filename))[0]
        if len(_label_cache) > 8192:
            _label_cache.clear()
        label = _label_cache[code] = f"{mod}:{code.co_name}"
    return label


def collapse_frame(frame, thread_name: str = "") -> str:
    """Fold one thread's live frame chain into a collapsed stack string,
    root-first (the flamegraph.pl / py-spy `--format collapsed` shape),
    prefixed with the thread name so per-thread time stays attributable
    after the cluster merge."""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < _MAX_DEPTH:
        parts.append(_frame_label(f))
        f = f.f_back
    parts.reverse()
    if thread_name:
        parts.insert(0, thread_name)
    return ";".join(parts)


def _prune_locked() -> int:
    """Keep the top half of stacks by count when the table overflows
    (rare: a stable program has a bounded live-stack set).  Returns how
    many stacks were dropped; their sample counts are gone from the
    table but remain in _n_samples, so `other` time stays visible as the
    gap between total and per-stack sums."""
    global _samples
    ranked = sorted(_samples.items(), key=lambda kv: -kv[1])
    keep = ranked[: _MAX_STACKS // 2]
    dropped = len(ranked) - len(keep)
    _samples = dict(keep)
    return dropped


# Thread-name map, refreshed lazily (threading.enumerate() walks a lock
# + list per call — too hot to pay per sample; names change rarely).
_thread_names: Dict[int, str] = {}
_names_refresh_due = 0


def _sample_once(own_ident: int) -> None:
    global _n_samples, _n_dropped_stacks, _names_refresh_due
    try:
        frames = sys._current_frames()
    except Exception:
        return
    names = _thread_names
    if _n_samples >= _names_refresh_due or any(
        i not in names for i in frames
    ):
        names = {t.ident: t.name for t in threading.enumerate()}
        _thread_names.clear()
        _thread_names.update(names)
        _names_refresh_due = _n_samples + 64
    with _lock:
        _n_samples += 1
        for ident, frame in frames.items():
            if ident == own_ident:
                continue  # never profile the profiler
            key = collapse_frame(frame, names.get(ident, f"t{ident}"))
            _samples[key] = _samples.get(key, 0) + 1
        if len(_samples) > _MAX_STACKS:
            _n_dropped_stacks += _prune_locked()


def _loop(period: float, stop: threading.Event) -> None:
    own = threading.get_ident()
    next_t = time.monotonic() + period
    while not stop.is_set():
        delay = next_t - time.monotonic()
        if delay > 0:
            if stop.wait(delay):
                return
        next_t = max(next_t + period, time.monotonic())
        _sample_once(own)


def running() -> bool:
    return ENABLED and _thread is not None and _thread.is_alive()


def status() -> Dict[str, Any]:
    """Current sampler state.  The head answers this for the
    late-subscriber sync: a worker spawned AFTER a cluster-wide
    profile_start never saw the broadcast (pubsub is live-only), so it
    asks once right after subscribing and catches up."""
    return {"running": running(), "hz": _hz}


def start(hz: Optional[float] = None) -> float:
    """Start (or retune) the sampler in THIS process.  Resets the table —
    a profile run measures from its own start.  Returns the effective
    rate.  Fork-safe: a child inherits module state but not the thread;
    the pid check re-arms cleanly."""
    global ENABLED, _thread, _stop, _pid, _hz, _t0, _n_samples
    global _n_dropped_stacks, _samples
    hz = float(hz) if hz else DEFAULT_HZ
    hz = min(max(hz, 1.0), 1000.0)
    with _lock:
        if running() and _pid == os.getpid() and abs(hz - _hz) < 1e-9:
            return _hz  # idempotent re-start at the same rate
        _stop.set()
        _stop = threading.Event()
        _samples = {}
        _n_samples = 0
        _n_dropped_stacks = 0
        _pid = os.getpid()
        _hz = hz
        _t0 = time.time()
        ENABLED = True
        _thread = threading.Thread(
            target=_loop, args=(1.0 / hz, _stop), daemon=True,
            name="raytpu-prof",
        )
        _thread.start()
    try:
        from ray_tpu._private import telemetry

        telemetry.note("prof_start", hz=hz)
    except Exception:
        pass
    return hz


def stop() -> None:
    """Stop sampling; the table is kept for a final snapshot/push."""
    global ENABLED, _thread
    with _lock:
        ENABLED = False
        _stop.set()
        t = _thread
        _thread = None
    if t is not None and t.is_alive():
        t.join(timeout=0.5)


def maybe_autostart() -> None:
    """Start sampling when RAY_TPU_PROF_HZ > 0 (called from
    telemetry.install at every process entry — head, workers, daemons,
    io shards all sample under the soak's always-hot mode).  The default
    0 keeps this a single config read."""
    if running():
        return
    try:
        from ray_tpu._private import config as _config

        hz = float(_config.get("prof_hz"))
    except Exception:
        return
    if hz > 0:
        start(hz)


def snapshot_payload(top: int = _PUSH_STACKS) -> Dict[str, Any]:
    """The prof_push body: this process's cumulative table (top-N stacks
    by count), with enough metadata for the head to merge and attribute.
    Cheap enough to build on the telemetry tick."""
    with _lock:
        ranked = sorted(_samples.items(), key=lambda kv: -kv[1])
        dropped = _n_dropped_stacks + sum(n for _s, n in ranked[top:])
        payload = {
            "pid": os.getpid(),
            "t": time.time(),
            "t0": _t0,
            "hz": _hz,
            "n": _n_samples,
            "running": running(),
            "dropped_stacks": dropped,
            "samples": dict(ranked[:top]),
        }
    try:
        from ray_tpu._private import telemetry

        payload["proc"] = telemetry._proc_tag
    except Exception:
        payload["proc"] = "?"
    return payload


def flight_snapshot(top: int = 20) -> Optional[List[Tuple[str, int]]]:
    """Top stacks for a crash dump, or None when nothing was sampled —
    telemetry.flight_dump folds this into every dump so a chaos-killed
    process records where its time went."""
    with _lock:
        if not _samples:
            return None
        return sorted(_samples.items(), key=lambda kv: -kv[1])[:top]


def _reset_for_tests() -> None:
    global _samples, _n_samples, _n_dropped_stacks
    stop()
    with _lock:
        _samples = {}
        _n_samples = 0
        _n_dropped_stacks = 0


# ---------------------------------------------------------------------------
# merge + rendering (pure: unit-testable without a cluster)

def merge_samples(tables: List[Dict[str, int]]) -> Dict[str, int]:
    """Sum collapsed-stack tables (per-process cumulative counts) into
    one — the cluster/node flamegraph body."""
    out: Dict[str, int] = {}
    for t in tables:
        for stack, n in (t or {}).items():
            out[stack] = out.get(stack, 0) + int(n)
    return out


def folded_text(samples: Dict[str, int]) -> str:
    """`stack count` lines, descending — the flamegraph.pl / speedscope
    collapsed input format (`--flame out.txt`)."""
    lines = [
        f"{stack} {n}"
        for stack, n in sorted(samples.items(), key=lambda kv: (-kv[1], kv[0]))
    ]
    return "\n".join(lines) + ("\n" if lines else "")


class _Node:
    __slots__ = ("name", "count", "children")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.children: Dict[str, "_Node"] = {}


def _build_tree(samples: Dict[str, int]) -> _Node:
    root = _Node("all")
    for stack, n in samples.items():
        root.count += n
        node = root
        for part in stack.split(";"):
            child = node.children.get(part)
            if child is None:
                child = node.children[part] = _Node(part)
            child.count += n
            node = child
    return root


def _svg_escape(s: str) -> str:
    return (
        s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
        .replace('"', "&quot;")
    )


def flamegraph_svg(samples: Dict[str, int], title: str = "ray_tpu profile",
                   width: int = 1200) -> str:
    """Self-contained flamegraph SVG (`--flame out.svg`): one rect per
    call-tree node, width proportional to samples, hover titles with
    counts — no JS, opens anywhere."""
    root = _build_tree(samples)
    row_h = 16
    rects: List[str] = []
    max_depth = [0]

    def layout(node: _Node, x: float, w: float, depth: int) -> None:
        if w < 0.5:
            return
        max_depth[0] = max(max_depth[0], depth)
        hue = (hash(node.name) % 55) + 5  # warm palette, stable per name
        label = _svg_escape(node.name)
        pct = 100.0 * node.count / max(root.count, 1)
        rects.append(
            f'<g><title>{label} ({node.count} samples, {pct:.1f}%)</title>'
            f'<rect x="{x:.1f}" y="{depth * row_h}" width="{w:.1f}" '
            f'height="{row_h - 1}" fill="hsl({hue},70%,62%)"/>'
            + (
                f'<text x="{x + 2:.1f}" y="{depth * row_h + 11}" '
                f'font-size="10" font-family="monospace">'
                f'{label[: max(int(w / 6.5), 0)]}</text>'
                if w > 20
                else ""
            )
            + "</g>"
        )
        cx = x
        for child in sorted(node.children.values(), key=lambda c: -c.count):
            cw = w * child.count / max(node.count, 1)
            layout(child, cx, cw, depth + 1)
            cx += cw

    layout(root, 0.0, float(width), 0)
    height = (max_depth[0] + 2) * row_h
    return (
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{height}" font-family="monospace">'
        f'<text x="4" y="{height - 4}" font-size="11">{_svg_escape(title)}'
        f" — {root.count} samples</text>" + "".join(rects) + "</svg>"
    )


class ProfileSink:
    """Head-side merge of pushed per-process tables (the TelemetrySink
    idiom: latest snapshot per sender, forgotten on process death).
    Payloads are cumulative-since-start, so latest-wins ingest plus a
    sum across senders is exact regardless of dropped pushes."""

    def __init__(self):
        self._lock = threading.Lock()
        self.tables: Dict[str, Dict[str, Any]] = {}
        self.nodes: Dict[str, Optional[str]] = {}

    def ingest(self, key: str, payload: Dict, node: Optional[str] = None) -> None:
        if not isinstance(payload, dict):
            return
        with self._lock:
            while len(self.tables) >= 4096:
                self.tables.pop(next(iter(self.tables)))
            self.tables[key] = payload
            if node is not None:
                self.nodes[key] = node

    def forget(self, key: str) -> None:
        with self._lock:
            self.tables.pop(key, None)
            self.nodes.pop(key, None)

    def merged(
        self, node: Optional[str] = None, pid: Optional[int] = None
    ) -> Dict[str, Any]:
        """Cluster (or node-/pid-filtered) flamegraph: summed samples +
        per-process attribution rows."""
        with self._lock:
            items = [
                (key, snap, self.nodes.get(key)) for key, snap in self.tables.items()
            ]
        procs: List[Dict[str, Any]] = []
        tables: List[Dict[str, int]] = []
        now = time.time()
        for key, snap, snap_node in items:
            if node is not None and snap_node != node:
                continue
            if pid is not None and snap.get("pid") != pid:
                continue
            procs.append(
                {
                    "key": key,
                    "proc": snap.get("proc"),
                    "pid": snap.get("pid"),
                    "node": snap_node,
                    "hz": snap.get("hz"),
                    "n_samples": snap.get("n", 0),
                    "running": bool(snap.get("running")),
                    "age_s": round(now - snap.get("t", now), 3),
                }
            )
        tables = [
            snap.get("samples") or {}
            for key, snap, snap_node in items
            if (node is None or snap_node == node)
            and (pid is None or snap.get("pid") == pid)
        ]
        merged = merge_samples(tables)
        return {
            "samples": merged,
            "total_samples": sum(p["n_samples"] for p in procs),
            "processes": sorted(procs, key=lambda p: -p["n_samples"]),
            "pids": sorted({p["pid"] for p in procs if p.get("pid")}),
        }
