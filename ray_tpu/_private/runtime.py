"""Driver-side runtime: ownership, scheduling, worker pool, actor FSM.

This process plays three reference roles at once (they split into separate
processes when the multi-host DCN transport lands):
  * CoreWorker of the driver -- task submission, object ownership/refcounts
    (ray: src/ray/core_worker/core_worker.h:284, task_manager.h:90,
     reference_count.h:61);
  * raylet/NodeManager -- worker leases, dependency management, dispatch
    (ray: src/ray/raylet/node_manager.h:115, local_task_manager.h:58,
     worker_pool.h:156, dependency_manager.h:51);
  * GCS -- global tables + actor lifecycle FSM
    (ray: src/ray/gcs/gcs_server/gcs_actor_manager.h:258-280).

Design notes (TPU-first): hosts are few and fat (a TPU host drives 4-8 chips),
so a single asio-style control loop per host with direct connections to every
worker replaces the reference's raylet<->GCS<->worker RPC triangle. Tasks are
pushed directly to leased workers (the analogue of
ray: transport/direct_task_transport.h:75), and the object plane is the
host-shared tmpfs store (store.py).
"""

from __future__ import annotations

import atexit
import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Set, Tuple

import cloudpickle

from ray_tpu._private import faults
from ray_tpu._private import ids, lock_watchdog, serialization as ser
from ray_tpu._private import wire as _wire
from ray_tpu._private.gcs import (
    ALIVE,
    DEAD,
    PENDING_CREATION,
    RESTARTING,
    ActorInfo,
    GlobalState,
    NodeInfo,
    PlacementGroupInfo,
    pg_record as _pg_record,
)
from ray_tpu._private.refs import ObjectRef, set_ref_hooks
from ray_tpu._private.scheduler import Scheduler
from ray_tpu._private.store import OwnerStore
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)

_worker_mode = False  # set True inside worker processes (worker_proc.py)

# Lock-discipline checking (SURVEY §5.2): the reference leans on clang
# thread-safety annotations (GUARDED_BY) + TSAN in CI; the Python analogue
# is runtime ownership assertions on every "caller holds self.lock"
# internal.  Enabled via RAY_TPU_DEBUG_LOCKS=1 — the test suite runs with
# it on (tests/conftest.py), production pays only one module-bool check.
_DEBUG_LOCKS = os.environ.get("RAY_TPU_DEBUG_LOCKS") == "1"


def _locked(method):
    """Decorator asserting the runtime lock is held on entry (debug mode)."""
    if not _DEBUG_LOCKS:
        return method
    import functools

    @functools.wraps(method)
    def wrapper(self, *a, **kw):
        if not self.lock._is_owned():
            raise AssertionError(
                f"{method.__name__} requires self.lock held (lock-discipline "
                "violation — see RAY_TPU_DEBUG_LOCKS)"
            )
        return method(self, *a, **kw)

    return wrapper


# How long a lineage re-execution waits on a pending function-export
# fence before its parked gets fail loudly (see _reconstruct).
_FN_FENCE_TIMEOUT_S = 30.0


def _runtime_env_key(renv) -> object:
    """Worker-pool identity of a runtime env: workers are only shared
    between tasks whose env_vars AND code packages match."""
    if not renv:
        return None
    env_vars = renv.get("env_vars") or None
    return (
        tuple(sorted(env_vars.items())) if env_vars else None,
        renv.get("working_dir"),
        tuple(renv.get("py_modules") or ()) or None,
        tuple(renv.get("pip") or ()) or None,
    )


def _detect_tpu_chips() -> int:
    """Local TPU chip count: RAY_TPU_CHIPS env override, else the TPU-VM
    accelerator device files.  Never imports jax (backend init costs
    seconds and this runs in every ray_tpu.init)."""
    env = os.environ.get("RAY_TPU_CHIPS")
    if env:
        try:
            return int(env)
        except ValueError:
            pass  # malformed override: fall through to device detection
    import glob as _glob

    return len(_glob.glob("/dev/accel*"))


class _PopenHandle:
    """subprocess.Popen adapter exposing the mp.Process surface the runtime
    uses (terminate/join/is_alive/pid)."""

    __slots__ = ("_p",)

    def __init__(self, p):
        self._p = p

    def terminate(self):
        self._p.terminate()

    def kill(self):
        self._p.kill()

    def join(self, timeout=None):
        import subprocess

        try:
            self._p.wait(timeout)
        except subprocess.TimeoutExpired:
            pass

    def is_alive(self):
        return self._p.poll() is None

    @property
    def pid(self):
        return self._p.pid


class _ZygoteProcHandle:
    """Handle for a worker forked by the zygote (not our child: no
    waitpid — liveness via kill(pid, 0), termination via signals).  The
    pid lands asynchronously with the zygote's ("forked", ...) reply; a
    handle whose pid never arrives (zygote died mid-request) reads as
    dead after a grace window so the reaper reschedules its lease."""

    __slots__ = ("_pid", "_created", "_zygote")

    def __init__(self, zygote_proc=None):
        self._pid = None
        self._created = time.monotonic()
        self._zygote = zygote_proc

    def set_pid(self, pid: int) -> None:
        self._pid = pid

    def _signal(self, sig) -> None:
        if self._pid is not None:
            try:
                os.kill(self._pid, sig)
            except (OSError, ProcessLookupError):
                pass

    def terminate(self):
        import signal

        self._signal(signal.SIGTERM)

    def kill(self):
        import signal

        self._signal(signal.SIGKILL)

    def join(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.is_alive():
            if deadline is not None and time.monotonic() >= deadline:
                return
            time.sleep(0.05)

    def is_alive(self):
        if self._pid is None:
            # Fork request in flight: the grace applies even while the
            # zygote itself lives — a lost ("forked", ...) reply (zygote
            # conn broke so _zygote_loop exited, or the frame was dropped)
            # leaves no worker process behind this handle, and an
            # unconditional True would wedge its lease as "starting"
            # forever.  The window is generous vs the ~2ms fork + serial
            # attribution so slow-boot storms are not mis-declared dead
            # (the old cascade this guard once caused).
            from ray_tpu._private import config as _config

            return (
                time.monotonic() - self._created
                < _config.get("zygote_fork_grace_s")
            )
        try:
            os.kill(self._pid, 0)
            return True
        except (OSError, ProcessLookupError):
            return False

    @property
    def pid(self):
        return self._pid


class _RemoteProcHandle:
    """Process facade for a worker owned by a node daemon: liveness comes
    from the worker's connection state; terminate routes through the daemon."""

    __slots__ = ("_rt", "_node_id", "_wid", "dead")

    def __init__(self, rt, node_id, wid):
        self._rt = rt
        self._node_id = node_id
        self._wid = wid
        self.dead = False

    def terminate(self):
        self._rt._daemon_send(self._node_id, ("kill_worker", self._wid))

    def kill(self):
        self.terminate()

    def join(self, timeout=None):
        pass  # the daemon reaps its own children

    def is_alive(self):
        # Until the worker's conn EOFs (io loop marks it crashed) we assume
        # it is alive; pre-connect spawn failures surface via the daemon's
        # own death or the lease timeout paths.
        return not self.dead

    @property
    def pid(self):
        return None


class _AdoptedHandle:
    """Process facade for a worker adopted after a head restart: the new
    head never spawned it, so liveness is purely connection state and
    terminate can only ask the worker itself to exit."""

    __slots__ = ("_rt", "_wid", "dead")

    def __init__(self, rt, wid):
        self._rt = rt
        self._wid = wid
        self.dead = False

    def terminate(self):
        h = self._rt.workers.get(self._wid)
        if h is not None and h.conn is not None:
            try:
                h.conn.send(("kill",))
            except OSError:
                pass

    def kill(self):
        self.terminate()

    def join(self, timeout=None):
        pass

    def is_alive(self):
        return not self.dead


class WorkerHandle:
    __slots__ = (
        "worker_id",
        "node_id",
        "env_key",
        "env_vars",
        "proc",
        "conn",
        "state",  # starting | idle | busy | actor | dead
        "pending_sends",
        "current_task",
        "actor_id",
        "known_fns",
        "pid",
        "spawn_ts",
        "idle_since",
    )

    def __init__(self, worker_id, node_id, env_key, env_vars, proc):
        self.worker_id = worker_id
        self.node_id = node_id
        self.env_key = env_key
        self.env_vars = env_vars
        self.proc = proc
        self.conn = None
        self.state = "starting"
        self.pending_sends: List[tuple] = []
        self.current_task: Optional[str] = None
        self.actor_id: Optional[str] = None
        self.known_fns: Set[str] = set()
        self.pid = None
        self.spawn_ts = time.monotonic()
        self.idle_since = 0.0


class _ReadySpill:
    """Disk overflow segment for the ready queue: beyond the
    ready_queue_spill_after backlog, dependency-free plain specs live as
    length-framed pickles in ONE append-only file and reload in FIFO
    chunks as the in-memory backlog drains.  This is what bounds head RSS
    under a 1M-task backlog (a TaskRecord+spec is ~1KB resident; the
    reference absorbs the same backlog across its distributed raylet
    queues — a single-node head needs disk).

    Same-session only: the file dies with the head (spilled overflow
    tasks are NOT in the snapshot's in-flight cap — a client retrying
    across a head bounce re-submits them, the same at-least-once contract
    lease-dispatched direct tasks already carry)."""

    __slots__ = ("path", "_w", "_roff", "count", "appended", "loaded")

    def __init__(self, path: str):
        self.path = path
        self._w = None       # lazily-opened append handle (buffered)
        self._roff = 0       # read offset: everything before it was loaded
        self.count = 0       # frames on disk not yet loaded
        self.appended = 0    # lifetime counters (bench/telemetry surface)
        self.loaded = 0

    def append(self, spec) -> None:
        import pickle as _pickle
        import struct as _struct

        if self._w is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._w = open(self.path, "ab")
        blob = _pickle.dumps(spec, protocol=5)
        self._w.write(_struct.pack("<I", len(blob)) + blob)
        self.count += 1
        self.appended += 1

    def load(self, n: int) -> List[Any]:
        """Next n specs in FIFO order; resets the file once drained so a
        long-lived head doesn't grow an unbounded tombstone prefix."""
        import pickle as _pickle
        import struct as _struct

        if self.count <= 0 or self._w is None:
            return []
        self._w.flush()
        out: List[Any] = []
        with open(self.path, "rb") as r:
            r.seek(self._roff)
            while len(out) < n and self.count > 0:
                hdr = r.read(4)
                if len(hdr) < 4:
                    break
                (ln,) = _struct.unpack("<I", hdr)
                blob = r.read(ln)
                if len(blob) < ln:
                    break
                out.append(_pickle.loads(blob))
                self.count -= 1
            self._roff = r.tell()
        self.loaded += len(out)
        if self.count <= 0:
            # Fully drained: truncate in place (the append handle's
            # position resets with it).
            self._w.close()
            self._w = open(self.path, "wb")
            self._w.close()
            self._w = open(self.path, "ab")
            self._roff = 0
            self.count = 0
        return out

    def close(self) -> None:
        if self._w is not None:
            try:
                self._w.close()
            except OSError:
                pass
            self._w = None
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _ReadyQueue:
    """Ready tasks bucketed by scheduling shape (ray: ClusterTaskManager
    keys its queues by scheduling class).  Dispatch probes one head task
    per bucket, so a blocked shape costs O(1) per event instead of
    rotating every queued sibling through the deque."""

    __slots__ = ("_rt", "buckets")

    def __init__(self, rt):
        self._rt = rt
        self.buckets: Dict[Any, deque] = {}

    def _shape_of(self, spec) -> tuple:
        if Scheduler.is_pg_task(spec):
            pg_id, want_idx = self._rt.scheduler._pg_for_spec(spec)
            # Bundle index is part of the shape: a full bundle 0 must not
            # block a sibling task targeting free bundle 1.
            return ("pg", pg_id, want_idx, tuple(sorted(spec.resources.items())))
        # Plain-task shape doubles as the lease SchedulingKey (ray:
        # scheduling_key.h = scheduling class + function descriptor):
        # fn_id keeps the leaseholder's fn-blob cache hot, env_key keeps
        # runtime-env workers distinct.  Head-of-line semantics are
        # unchanged — finer buckets, one head probe each.
        return (
            tuple(sorted(spec.resources.items())),
            Runtime._strategy_shape_key(spec.scheduling_strategy),
            spec.fn_id,
            None if not spec.runtime_env else _runtime_env_key(spec.runtime_env),
        )

    def append(self, tid: str, shape=None) -> None:
        if shape is None:
            shape = self._shape_of(self._rt.tasks[tid].spec)
        self.buckets.setdefault(shape, deque()).append(tid)

    def __iter__(self):
        for q in self.buckets.values():
            yield from q

    def __len__(self) -> int:
        return sum(len(q) for q in self.buckets.values())


class TaskLease:
    """One head-side worker lease: a worker bound to a SchedulingKey with
    its resources HELD across tasks (ray: direct_task_transport.h:75 —
    the same pooling the caller-side peer leases do, applied to the
    head's own dispatch loop).  idle_since is None while a task runs on
    the leaseholder; a monotonic stamp while it waits for the next
    same-key task."""

    __slots__ = (
        "lease_id", "key", "worker_id", "node_id", "resources",
        "granted_t", "idle_since", "dispatched", "last_extend_journal",
    )

    def __init__(self, lease_id, key, worker_id, node_id, resources):
        self.lease_id = lease_id
        self.key = key
        self.worker_id = worker_id
        self.node_id = node_id
        self.resources = resources
        self.granted_t = time.monotonic()
        self.idle_since: Optional[float] = None  # a task is running now
        self.dispatched = 1
        self.last_extend_journal = self.granted_t


class TaskRecord:
    __slots__ = (
        "spec", "state", "node_id", "worker_id", "unmet_deps", "cancelled",
        "pg", "start_time", "allow_pending", "stages", "lease",
    )

    def __init__(self, spec):
        self.spec = spec
        self.state = "PENDING"
        self.node_id = None
        self.worker_id = None
        self.unmet_deps = 0
        self.cancelled = False
        self.pg = None  # (pg_id, bundle_index) when resources come from a PG
        self.start_time = None  # wall time when dispatched (timeline)
        # The TaskLease this record dispatched on, when any: the LEASE
        # owns the node resources (release happens at revoke, not per
        # task) — _release_for must not double-release them.
        self.lease = None
        # Re-driven tasks (head-restart recovery) PARK when infeasible —
        # the cluster's daemon nodes rejoin seconds after restore, and
        # failing fast there would defeat the re-drive.
        self.allow_pending = False
        # Lifecycle stage stamps (telemetry.STAGE_ORDER): wall-clock time
        # each stage was entered, on the head clock (executor stamps land
        # via the done message, offset-corrected).  A retried attempt
        # re-stamps, so the record attributes the attempt that finished.
        self.stages: Dict[str, float] = {"submit": time.time()}

    def stamp(self, stage: str) -> None:
        self.stages[stage] = time.time()


class ActorRuntime:
    __slots__ = (
        "info",
        "worker_id",
        "queued",
        "in_flight",
        "expected_death",
        "no_restart",
        "placement",  # ("node", node_id) | ("pg", pg_id, bundle_idx)
        "_creation_crash_retries",
    )

    def __init__(self, info):
        self.info = info
        self.worker_id: Optional[str] = None
        self.queued: deque = deque()  # TaskSpecs waiting for ALIVE
        # task_ids sent to the worker, as an insertion-ordered dict-set:
        # requeue-on-death iterates this to rebuild per-caller call order
        # across a restart, so push order must be recoverable (a plain set
        # iterates in hash order — the direct path's ActorRoute buffer
        # keeps order, and this relayed twin must match; ray:
        # sequential_actor_submit_queue.h orders by sequence number).
        self.in_flight: Dict[str, None] = {}
        self.expected_death = False
        self.no_restart = False
        self.placement = None
        self._creation_crash_retries = 0


class Runtime:
    """Singleton per driver process."""

    def __init__(
        self,
        num_cpus: Optional[int] = None,
        resources: Optional[Dict[str, float]] = None,
        namespace: str = "default",
        session_name: Optional[str] = None,
        snapshot_path: Optional[str] = None,
        listen_port: int = 0,
        authkey: Optional[bytes] = None,
    ):
        # _system_config overrides exported their env form by now: pick up
        # a fault plan configured via ray_tpu.init(_system_config=...).
        faults.refresh_from_env()
        self.session_name = session_name or f"{os.getpid()}-{os.urandom(3).hex()}"
        self.namespace = namespace
        self.state = GlobalState()
        self.store = OwnerStore(self.session_name, spill_dir=f"/tmp/raytpu-spill-{self.session_name}")
        self.store.on_lifecycle = self._on_store_lifecycle
        self.lock = lock_watchdog.make_lock("Runtime.lock", rlock=True)
        self.head_node_id = ids.node_id()
        if num_cpus is None:
            num_cpus = max(os.cpu_count() or 1, 4)
        res = {"CPU": float(num_cpus), **(resources or {})}
        chips = _detect_tpu_chips()
        if chips > 0:
            # TPU is a first-class schedulable resource (the reference's
            # accelerators are GPU-only — accelerators.py:1-7): tasks/actors
            # reserve chips via num_tpus / ScalingConfig.chips_per_worker.
            res.setdefault("TPU", float(chips))
        self.state.register_node(
            NodeInfo(self.head_node_id, dict(res), dict(res), is_head=True)
        )
        self.scheduler = Scheduler(self.state, self.head_node_id)
        self.scheduler.locality_fn = self._deps_locality

        self.workers: Dict[str, WorkerHandle] = {}
        self.idle_pool: Dict[Tuple[str, Any], List[str]] = {}  # (node, env_key) -> worker_ids
        self.starting_pool: Dict[Tuple[str, Any], List[str]] = {}  # spawned, not yet connected
        self.tasks: Dict[str, TaskRecord] = {}
        self.actors: Dict[str, ActorRuntime] = {}
        self.ready_queue = _ReadyQueue(self)
        # ONE pubsub plane for every push mechanism (parked gets, wait
        # tokens, dep resolution here; GCS events and serve long-poll run
        # their own Publisher instances of the same abstraction) —
        # ray: src/ray/pubsub/publisher.h:298.
        from ray_tpu._private.pubsub import Publisher

        # Cross-process pubsub (ray: subscriber.h:70): (channel, key) ->
        # {worker/driver id: once} for ids that asked for pushes; "*" key
        # = wildcard (log streaming).  Fan-out rides the control conns.
        self.remote_subs: Dict[Tuple[str, Any], Dict[str, bool]] = {}
        # Drivers whose conn reset on a live head: death deferred briefly
        # so their reconnect can win the race (did -> deadline).
        self._driver_death_grace: Dict[str, float] = {}
        # Trace-span sink (util/tracing.py; ray: spans land in the GCS task
        # events the same batched way).
        self.trace_spans: deque = deque(maxlen=10000)
        # Per-sender clock-offset estimates (seconds to ADD to a sender's
        # timestamps to land them on this process's clock), sampled from
        # the time.time() the ready/driver/daemon hellos carry.  The spans
        # and task-event batches a sender ships are corrected at ingest so
        # the merged timeline (`ray_tpu timeline`) is one coherent clock.
        self.clock_offsets: Dict[str, float] = {}
        # Telemetry sink: latest pushed metric snapshot per process plus
        # bounded ring-buffer time series (telemetry.py; ray: the GCS-side
        # metrics aggregation the dashboard agent performs).
        from ray_tpu._private import config as _tcfg
        from ray_tpu._private import telemetry as _telemetry

        self.telemetry = _telemetry.TelemetrySink(
            ring_samples=_tcfg.get("telemetry_ring_samples")
        )
        # Object ledger (memory introspection plane): latest pushed live-
        # ref table per process (refs_push oneways), joined with the owner
        # tables below by memory_summary (ray: reference_count.h:61 tables
        # feeding `ray memory`).
        self.ledger = _telemetry.ObjectLedger()
        # Profile sink: latest pushed collapsed-stack table per process
        # (prof_push oneways), merged into the cluster flamegraph by
        # `ray_tpu profile` / /api/profile (profiler.py).
        from ray_tpu._private import profiler as _profiler

        self.profiles = _profiler.ProfileSink()
        # Conn-tracked outstanding ref borrows per WORKER (the driver twin
        # is driver_refs): every refop add/del updates this, so a worker
        # crash mid-hold leaves exactly the refs it still held — flagged
        # as dead-holder leak suspects, then reclaimed after
        # leak_reclaim_grace_s by reclaim_dead_refs.
        self.worker_refs: Dict[str, Dict[str, int]] = {}
        self._dead_refs: Dict[str, Dict[str, Any]] = {}
        # Object metadata the store doesn't keep: creation time + creator
        # process label per live object (ledger age/owner attribution).
        self.object_meta: Dict[str, tuple] = {}
        # Object lifecycle event ring (create/seal/transfer/spill/restore/
        # free), merged into the chrome timeline by dashboard.timeline().
        self.object_events: deque = deque(
            maxlen=max(_tcfg.get("object_events_max"), 16)
        )
        self.pubsub = Publisher()
        import queue as _queue

        # Cross-process delivery queue + sender thread: created BEFORE the
        # hook is installed (snapshot restore publishes during __init__).
        self._pub_queue: "_queue.Queue" = _queue.Queue(maxsize=10000)
        threading.Thread(
            target=self._pub_sender_loop, daemon=True, name="raytpu-pubsend"
        ).start()
        self.pubsub.remote_hook = self._remote_publish
        self.contained_map: Dict[str, List[str]] = {}  # oid -> contained oids
        # Object directory (ray: ownership_based_object_directory.h): which
        # NON-head nodes hold a sealed copy of each object.  Head-node
        # presence is the OwnerStore's own bookkeeping.  Single-controller
        # means every seal/copy/free flows through this process, so the
        # directory needs no pubsub.
        self.object_locations: Dict[str, Set[str]] = {}
        # Packed size per object with a sealed copy anywhere — feeds the
        # BYTES-weighted locality scoring (ray: the hybrid policy's
        # locality/load tradeoff weighs by object size, not count —
        # hybrid_scheduling_policy.h:50 + locality-aware leasing).
        self.object_sizes: Dict[str, int] = {}
        self.node_object_endpoints: Dict[str, Tuple[str, int]] = {}
        # Head-side outbound-transfer admission (the daemon ObjectServer
        # enforces the same bound for its node).
        from ray_tpu._private import config as _cfg

        self._transfer_sem = threading.BoundedSemaphore(
            _cfg.get("object_transfer_max_concurrency")
        )
        self.pending_pgs: List[str] = []
        # Lineage: producer TaskSpec per task-returned object, enabling
        # re-execution when an object's bytes are lost (evicted / spill file
        # gone) — ray: task_manager.h:97 lineage + object_recovery_manager.h:41.
        # Bounded FIFO (the reference bounds by footprint bytes); actor tasks
        # are excluded (actor state is not replayable).
        from collections import OrderedDict

        self.lineage: "OrderedDict[str, Any]" = OrderedDict()
        from ray_tpu._private import config as _config

        self.lineage_max = _config.get("lineage_max_entries")
        # Resolved once (dispatch hot path): lease idle window.
        self._lease_idle_s = _config.get("task_lease_idle_s")
        # Ready-queue disk overflow (bounded head RSS under a 1M-task
        # backlog): lazily created at the first spill.
        self._ready_spill: Optional[_ReadySpill] = None
        self._spill_after = _config.get("ready_queue_spill_after")
        # Lineage re-executions parked on a missing fn blob:
        # fn_id -> (since_mono, [oids]).  Released by the export hook,
        # failed loudly by the io-loop tick after the fence timeout.
        self._fn_fences: Dict[str, tuple] = {}
        self.state.on_function_export = self._on_function_export
        # (histogram, {stage: resolved series key}) — lazy, see
        # _observe_stage_durations.
        self._stage_key_cache = None
        # Footprint bound (bytes of retained args_blob) in addition to the
        # entry-count cap — ray: task_manager.h:97-104 lineage accounting.
        self.lineage_max_bytes = _config.get("lineage_max_bytes")
        self.lineage_bytes = 0
        # With an autoscaler attached, infeasible tasks PARK (the fleet may
        # grow to fit them — ray's default behavior); without one they error
        # fast (a fixed cluster can never run them).
        self.allow_pending_infeasible = False
        # Task-event sink (ray: gcs_task_manager.h:61 ring-buffer storage):
        # bounded history of finished tasks powering the state API + metrics.
        self.task_events: deque = deque(maxlen=_config.get("task_events_max"))
        self.metrics: Dict[str, float] = {
            "tasks_submitted": 0,
            "tasks_finished": 0,
            "tasks_failed": 0,
            "tasks_retried": 0,
            "actors_created": 0,
            "actor_restarts": 0,
            "objects_put": 0,
            "workers_spawned": 0,
            "worker_crashes": 0,
            "pull_parks": 0,
            "journal_appends": 0,
            "journal_fsyncs": 0,
            "journal_entries": 0,
            "task_leases_granted": 0,
            "task_leases_revoked": 0,
            "lease_dispatches": 0,
        }
        # Staggered broadcast admission (see _admit_pull): oid -> grant
        # timestamps of in-flight pulls; round-robin rotation counter.
        # (legacy mode, relay_pipeline=0)
        self._pull_grants: Dict[str, list] = {}
        self._pull_rr = 0
        # Pipelined-broadcast transfer plans (relay_pipeline=1): oid ->
        # {"feeds": {endpoint: {load, sealed, node}}, "pulling":
        # {node_id: (endpoint, granted_at)}}.  Feeds are sealed sources
        # AND in-flight pullers (their boards re-serve mid-transfer);
        # each feed carries at most relay_fanout downstreams, so
        # admission capacity grows with the tree, not with completed
        # rounds.  Loads are soft bounds: releases ride object_copied /
        # re-asks / timestamp decay, never block correctness.
        self._xfer_plans: Dict[str, dict] = {}
        # Per-op counts of synchronous worker requests — the direct
        # transport's "zero head hops on the hot path" claim is asserted
        # against these (tests/test_direct_transport.py).
        from collections import defaultdict

        self.req_counts: Dict[str, int] = defaultdict(int)
        # Per-process wire counters reported by workers/drivers (their
        # physical-write coalescing is invisible to the head's own
        # counters) when RAY_TPU_WIRE_STATS=1.
        self.worker_wire_stats: Dict[str, Dict[str, int]] = {}
        # Direct transport directory: worker_id -> peer (host, port) from
        # the ready handshake (ray: worker addresses in the GCS worker
        # table, resolved once per caller and cached).
        self.worker_peer_endpoints: Dict[str, Tuple[str, int]] = {}
        # Transport-switch fences: fence_id -> (caller, req_id, wid, ep).
        self._pending_fences: Dict[str, tuple] = {}
        self._fence_counter = 0
        # Peer-leased workers (ray: direct_task_transport.h lease pooling):
        # lease_id -> (worker_id, node_id, resources, caller_id).  A leased
        # worker executes tasks pushed straight by the caller; the head
        # only holds the resource reservation.
        self.peer_leases: Dict[str, tuple] = {}
        self._lease_counter = 0
        # Lease grants awaiting a spawning worker's ready handshake:
        # worker_id -> [(caller, req_id, lease_id)].
        self._parked_peer_leases: Dict[str, list] = {}
        # HEAD-side lease reuse (ray: direct_task_transport.h:40-55 —
        # "subsequent same-shape tasks skip the lease round trip"): a
        # worker dispatched a lease-eligible task stays BOUND to that
        # task's SchedulingKey (fn + resource shape + strategy + env),
        # resources held, and same-key tasks dispatch straight onto it —
        # no per-task placement, no pool churn.  Revoked on worker death,
        # idle timeout (RAY_TPU_LEASE_IDLE_S), or on demand when another
        # shape can't place (the idle lease's resources are the slack).
        self.task_leases: Dict[Any, List[TaskLease]] = {}
        self.lease_by_worker: Dict[str, "TaskLease"] = {}
        self._task_lease_seq = 0
        # Adaptive prestart (ray: worker_pool.h:156): pool-miss bursts
        # raise the target; 5 quiet seconds halve it.  Topped up from the
        # io-loop tick.
        self._prestart_target = 0
        self._prestart_miss_t = 0.0
        self._prestart_decay_t = 0.0
        # Zygote fork server (zygote.py): spawned lazily on first local
        # worker spawn; until its handshake lands, spawns exec fresh
        # interpreters.
        self._zygote_conn = None
        self._zygote_proc = None
        self._zygote_spawning = False
        self._zygote_axon_hook: Optional[str] = None
        self._zygote_env: Optional[Dict[str, str]] = None
        # Lease-dispatched tasks currently running (caller-reported via
        # batched task_events with state RUNNING): task table visibility
        # for work the head never dispatched (ray: GcsTaskManager fed by
        # TaskEventBuffer, gcs_task_manager.h:61).
        self.direct_running: Dict[str, dict] = {}
        self._direct_done_recent: set = set()
        self._direct_done_order: deque = deque()

        from multiprocessing.connection import Listener

        # listen_port/authkey are fixed (not ephemeral/random) in head-split
        # mode so a restarted head comes back at the SAME address and its
        # daemons/workers can reconnect (ray: the GCS address is stable
        # across gcs_server restarts).
        self._authkey = authkey or os.urandom(16)
        # backlog: many workers connect at once on startup; the default
        # backlog of 1 silently drops simultaneous handshakes (the dropped
        # worker then blocks forever in its auth recv).
        # Loopback by default; RAY_TPU_BIND_HOST=0.0.0.0 exposes the driver
        # to daemons on OTHER machines (required for cloud node providers).
        # No authkey HERE: accept() must not run the challenge inline (it
        # would serialize every connect behind the accept thread) — the
        # per-conn handshake thread runs it (_auth_and_handshake).
        bind_host = _config.get("bind_host")
        self.listener = Listener((bind_host, listen_port), backlog=128)
        self.address = self.listener.address
        self._shutdown = False
        self._conn_to_worker: Dict[Any, str] = {}
        self._conns_version = 0
        # Multi-host plane: per-node daemon processes owning remote worker
        # pools (ray: raylet main.cc) — node_id -> daemon conn, plus the
        # reverse map for EOF (= node death) detection in the io loop.
        self.node_daemons: Dict[str, Any] = {}
        self._conn_to_daemon: Dict[Any, str] = {}
        self._daemon_procs: Dict[str, Any] = {}  # node_id -> Popen (local launch)
        # wid -> (rss, used, limit): daemons report OOM kills BEFORE the
        # SIGKILL so the ensuing crash is classified as retriable OOM.
        self._oom_kills: Dict[str, tuple] = {}
        # wid -> deadline: a daemon-owned worker's conn EOF waits briefly
        # for its daemon's authoritative worker_exited (which says WHY —
        # the two arrive on different sockets and can reorder).
        self._deferred_crashes: Dict[str, float] = {}
        # nid -> last heartbeat time: timeout-based node death detection
        # on top of conn EOF (ray: gcs_health_check_manager.h:39).
        self._daemon_heartbeats: Dict[str, float] = {}
        # wid -> error text: runtime-env setup failures (non-retriable).
        self._env_failures: Dict[str, str] = {}
        # planned node removals: their daemon EOF is routine, not failure
        self._expected_node_removals: "Set[str]" = set()
        # workers on nodes being removed: their EOFs are routine stops
        self._expected_worker_stops: "Set[str]" = set()
        # Elastic capacity (autoscaler plane): journaled node lifecycle —
        # node_id -> {"node_id", "state", ...riders}.  States walk
        # REQUESTED -> STARTING -> ACTIVE -> DRAINING -> DEPARTED; every
        # transition goes through _set_node_lifecycle (journal kind
        # "node_lifecycle") so a restarted head replays them — a node that
        # died mid-DRAINING resumes draining when its daemon re-registers.
        # Only persistable fields live in the record; head-local timing
        # stays in the autoscaler (the PR-11 monotonic-field rule).
        self.node_lifecycle: Dict[str, dict] = {}
        # node_id -> daemon OS pid (from the registration hello): lets the
        # state API name the process a chaos harness must crash-kill to
        # simulate a node death mid-drain.
        self.node_daemon_pids: Dict[str, int] = {}
        # Attached by _private/autoscaler.attach_autoscaler when the
        # autoscale_enabled knob is on (or a test attaches one directly).
        self._autoscaler = None
        # Attached driver clients (head-split mode, head.py): did -> conn,
        # plus the pseudo-node each non-co-located driver reads objects as,
        # and per-driver ref borrows dropped on driver death
        # (ray: gcs_job_manager OnJobFinished cleanup).
        self.drivers: Dict[str, Any] = {}
        self.driver_nodes: Dict[str, str] = {}
        self._conn_to_driver: Dict[Any, str] = {}
        self.driver_refs: Dict[str, Dict[str, int]] = {}
        # Control-plane persistence (ray: gcs storage,
        # gcs/store_client/redis_store_client.h — ours is a snapshot file):
        # named/detached actors, KV, functions, PGs, object directory.
        self.snapshot_path = snapshot_path
        self._journal = None
        self._snapshot_kick = threading.Event()
        if snapshot_path:
            from ray_tpu._private.gcs_storage import (
                make_mutation_journal,
                make_snapshot_storage,
            )

            self._snapshot_storage = make_snapshot_storage(snapshot_path)
            if _config.get("gcs_journal"):
                self._journal = make_mutation_journal(
                    snapshot_path, self.session_name
                )
            self._journal_compact_bytes = _config.get("gcs_journal_compact_bytes")
        else:
            self._snapshot_storage = None
        self._restored_actors: Set[str] = set()
        # Inline-result lineage (oids whose bytes lived ONLY in this
        # process): journaled + snapshotted so a post-restart get() can
        # re-execute the producer instead of erroring/parking forever.
        self._inline_lineage: Set[str] = set()
        # Log pipeline (ray: log_monitor.py + driver print subscriber):
        # head workers' stdout/stderr redirect into per-worker files under
        # log_dir; a LogMonitor tails them (daemons tail their own nodes
        # and forward over their conns); every line lands in a per-worker
        # ring buffer (CLI/dashboard) and echoes to this process's stdout.
        self.log_dir = f"/tmp/raytpu-logs-{self.session_name}"
        # Structured cluster events (SURVEY §2.1 event framework —
        # ray: src/ray/util/event.h:102): severity/source records of
        # control-plane transitions, durable JSONL + in-memory ring.
        from ray_tpu._private.events import EventLog

        self.events = EventLog(os.path.join(self.log_dir, "events.jsonl"))
        self.events.emit(
            "INFO", "runtime", "session started", session=self.session_name
        )
        # Planning failures that need operator eyes (inconsistent
        # mesh_coord labels) surface through the same event log.
        self.scheduler.events = self.events
        # RESHAPING pg_ids already announced through the mesh.member_death
        # fault point (the sweep fires it once per episode, off the lock).
        self._remesh_announced: "Set[str]" = set()
        self.worker_logs: Dict[str, deque] = {}
        self.log_to_driver = _config.get("log_to_driver") != 0
        from ray_tpu._private.log_monitor import LogMonitor

        self._log_monitor = LogMonitor(self.log_dir, self._on_log_lines)
        if snapshot_path:
            self._restore_snapshot()
            if self._journal is not None:
                # Fold the just-replayed journal into a fresh snapshot NOW:
                # the reset inside _write_snapshot would otherwise race a
                # crash-before-first-tick (old snapshot on disk, replayed
                # entries gone).
                try:
                    self._write_snapshot()
                except Exception:
                    pass
                # Mutations from here on are journaled (the hook is
                # installed before the accept/io threads below can deliver
                # any request).
                self.state.journal_hook = self._journal_append
            threading.Thread(
                target=self._snapshot_loop, daemon=True, name="raytpu-snapshot"
            ).start()
        # Head io-shard fabric (io_shard.py; ray: the gcs_server gRPC
        # thread pools): N processes each owning a slice of the
        # worker/daemon/driver conns, decoding protocol-v2 frames there
        # and forwarding only decoded control messages here.  State
        # mutation stays in THIS process (the journaled single-writer
        # path); 0 shards = the classic in-process loop, unchanged.
        self._io_shards: Dict[int, Any] = {}
        self._conn_to_shard: Dict[Any, int] = {}
        self._shard_conn_seq = 0
        self._shard_listener = None
        self._shard_listener_path = None
        n_shards = _config.get("head_io_shards")
        if n_shards > 0:
            self._start_io_shards(n_shards)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="raytpu-accept"
        )
        self._io_thread = threading.Thread(target=self._io_loop, daemon=True, name="raytpu-io")
        self._accept_thread.start()
        self._io_thread.start()
        # Telemetry plane, head side: arm the flight recorder in this
        # process (workers/daemons/drivers arm their own at entry) and
        # start the aggregation tick — the head "pushes to itself" by
        # ingesting its own registry + internal queue-depth gauges, then
        # folds the cluster aggregate into the time-series rings.
        _telemetry.install(faults._PROC_TAG)
        if _config.get("metrics_push_ms") > 0:
            threading.Thread(
                target=self._telemetry_loop, daemon=True,
                name="raytpu-telemetry",
            ).start()

        # Head-node OOM protection: the head process doubles as this node's
        # daemon for locally-spawned workers, so it runs the same memory
        # monitor a node daemon does (ray: memory_monitor.h:52 — the raylet
        # embeds the monitor; our daemon nodes run their own copy).
        self._mem_monitor = None
        refresh_ms = _config.get("memory_monitor_refresh_ms")
        if refresh_ms > 0:
            from ray_tpu._private.memory_monitor import MemoryMonitor

            def _local_workers():
                with self.lock:
                    return {
                        wid: (h.pid, h.spawn_ts)
                        for wid, h in self.workers.items()
                        if isinstance(h.proc, _PopenHandle)
                        and h.pid
                        and h.state != "dead"
                    }

            def _oom_kill(wid, rss, used, limit):
                with self.lock:
                    h = self.workers.get(wid)
                    if h is None or h.state == "dead":
                        return
                    self._oom_kills[wid] = (rss, used, limit)
                    try:
                        h.proc.kill()
                    except OSError:
                        pass
                # reaper/conn-EOF classifies the death as OOM via the flag

            self._mem_monitor = MemoryMonitor(
                _local_workers,
                _oom_kill,
                limit_bytes=_config.get("memory_limit_bytes"),
                threshold=_config.get("memory_usage_threshold"),
                interval_s=refresh_ms / 1000.0,
                policy=_config.get("oom_worker_killing_policy"),
            )
            self._mem_monitor.start()

        set_ref_hooks(self._addref_local, self._decref_local)
        atexit.register(self.shutdown)

        # Prestart a warm worker pool (ray: src/ray/raylet/worker_pool.h:156
        # prestarts workers per language): exec'ed workers pay a fresh
        # interpreter start, so overlap that cost with driver setup.
        with self.lock:
            for _ in range(
                min(
                    int(self.state.nodes[self.head_node_id].resources.get("CPU", 0)),
                    _config.get("worker_prestart_count"),
                )
            ):
                self._spawn_worker(self.head_node_id, None, None, prestart=True)

        # Elastic capacity: the demand-driven reconcile loop (its own
        # thread, off the runtime lock) when the knob asks for it.
        if _config.get("autoscale_enabled"):
            from ray_tpu._private.autoscaler import attach_autoscaler

            attach_autoscaler(self)

    # ------------------------------------------------------------------
    # log pipeline (ray: log_monitor.py + worker print redirection)

    def _on_log_lines(self, wid: str, stream: str, lines: List[str]) -> None:
        from ray_tpu._private import config as _config

        buf = self.worker_logs.get(wid)
        if buf is None:
            buf = self.worker_logs.setdefault(
                wid, deque(maxlen=_config.get("worker_log_ring_lines"))
            )
        buf.extend(lines)
        # Log channel on the shared pubsub plane (ray: the reference's log
        # channel is a publisher channel too) — dashboards/CLIs can follow
        # a worker's output push-style instead of polling get_logs.
        self.pubsub.publish("logs", wid, stream, lines)
        if self.log_to_driver:
            from ray_tpu._private.log_monitor import format_log_lines

            out = format_log_lines(wid, stream, lines)
            try:
                import sys

                sys.stdout.write(out)
                sys.stdout.flush()
            except (OSError, ValueError):
                pass  # driver stdout closed (interpreter teardown)

    def get_logs(self, wid: str, n: Optional[int] = None) -> List[str]:
        buf = self.worker_logs.get(wid)
        if buf is None:
            return []
        lines = list(buf)
        return lines[-n:] if n else lines

    # ------------------------------------------------------------------
    # control-plane persistence (ray: gcs storage + gcs_actor_manager
    # recovery; ours snapshots the metadata tables to one file)

    def _snapshot_loop(self) -> None:
        while not self._shutdown:
            # The kick short-circuits the tick when the journal crosses its
            # compaction threshold (the snapshot folds the journal in).
            self._snapshot_kick.wait(0.5)
            self._snapshot_kick.clear()
            if self._shutdown:
                return
            try:
                self._write_snapshot()
            except Exception:
                pass  # next tick retries; persistence is best-effort

    def head_telemetry_snapshot(self) -> dict:
        """This process's telemetry snapshot plus the head-internal gauges
        remote processes can't see (scheduler/lease queue depths, journal
        counters, store occupancy).  Used by the telemetry tick AND the
        read-time fresh ingest (state API / prometheus endpoint) so both
        carry the same fields."""
        from ray_tpu._private import telemetry as _telemetry

        with self.lock:
            internal = {
                "head_ready_queue_depth": float(len(self.ready_queue)),
                "head_live_tasks": float(len(self.tasks)),
                "head_peer_leases": float(len(self.peer_leases)),
                "head_pending_fences": float(len(self._pending_fences)),
                "head_live_workers": float(
                    sum(1 for h in self.workers.values() if h.state != "dead")
                ),
                "head_io_shards_live": float(
                    sum(1 for s in self._io_shards.values() if s.alive)
                ),
                "head_sharded_conns": float(
                    sum(len(s.conns) for s in self._io_shards.values())
                ),
                "journal_entries": float(
                    self._journal.entries if self._journal else 0
                ),
                "journal_appends": float(
                    self._journal.writes if self._journal
                    else self.metrics["journal_appends"]
                ),
                "journal_fsyncs": float(
                    self._journal.fsyncs if self._journal
                    else self.metrics["journal_fsyncs"]
                ),
                "head_task_leases": float(
                    sum(len(p) for p in self.task_leases.values())
                ),
                "task_leases_granted": float(
                    self.metrics["task_leases_granted"]
                ),
                "task_leases_revoked": float(
                    self.metrics["task_leases_revoked"]
                ),
                "lease_dispatches": float(self.metrics["lease_dispatches"]),
                "head_ready_spilled": float(
                    self._ready_spill.count if self._ready_spill else 0
                ),
                "tasks_finished": float(self.metrics["tasks_finished"]),
                "tasks_failed": float(self.metrics["tasks_failed"]),
                # Elastic-capacity demand gauges (O(shapes): bucket heads
                # are the oldest entries, counts come from deque lengths).
                "autoscale_demand_tasks": float(len(self.ready_queue)),
                "autoscale_demand_buckets": float(
                    len(self.ready_queue.buckets)
                ),
                "autoscale_pending_bundles": float(
                    sum(
                        len(pg.bundles)
                        for pg in self.state.placement_groups.values()
                        if pg.state in ("PENDING", "RESHAPING")
                    )
                ),
                "autoscale_nodes_active": float(
                    sum(
                        1 for r in self.node_lifecycle.values()
                        if r.get("state") == "ACTIVE"
                    )
                ),
                "autoscale_nodes_draining": float(
                    sum(
                        1 for r in self.node_lifecycle.values()
                        if r.get("state") == "DRAINING"
                    )
                ),
            }
        internal["object_store_bytes_used"] = float(self.store.shm_usage())
        internal["objects_spilled"] = float(len(self.store._spilled))
        return _telemetry.snapshot_process(extra=internal)

    def _telemetry_loop(self) -> None:
        """Head-side telemetry tick (telemetry.py): snapshot this
        process's registry + internal queue-depth gauges into the sink,
        then fold the cluster aggregate into the time-series rings.  One
        sample per metrics_push_ms — same period the remote pushers use."""
        from ray_tpu._private import config as _config

        period = max(_config.get("metrics_push_ms"), 250) / 1000.0
        while not self._shutdown:
            time.sleep(period)
            if self._shutdown:
                return
            try:
                self.telemetry.ingest("head", self.head_telemetry_snapshot())
                self._ledger_tick()
                self.telemetry.sample()
            except Exception:
                pass  # telemetry must never take the control plane down

    def _journal_append(self, entry: tuple) -> None:
        """GlobalState journal hook + inline-lineage writer: mirror one
        control-plane mutation into the append-only journal (group-
        committed — see MutationJournal).  Best-effort by contract — a
        failed append degrades this mutation back to snapshot-tick
        durability, and the reconciliation handshake covers the actor
        records regardless."""
        j = self._journal
        if j is None:
            return
        try:
            j.append(entry)
        except Exception:
            return
        # Mirror the journal's own counters (the flusher thread advances
        # writes/fsyncs asynchronously; entries advance here).  NOTE the
        # post-group-commit meaning: journal_appends = PHYSICAL writes,
        # journal_entries = logical mutations — their ratio is the
        # group-commit factor, same shape as wire writes_per_op.
        self.metrics["journal_entries"] = j.entries
        self.metrics["journal_appends"] = j.writes
        self.metrics["journal_fsyncs"] = j.fsyncs
        if j.size_bytes() >= self._journal_compact_bytes:
            self._snapshot_kick.set()

    def _set_node_lifecycle(self, node_id: str, state: str, **kw) -> None:
        """Journaled node-lifecycle transition (REQUESTED -> STARTING ->
        ACTIVE -> DRAINING -> DEPARTED).  Caller holds self.lock.  The
        record carries only persistable riders (reason, provider tag);
        head-local monotonic timing lives with the autoscaler, never in
        the journal — a replayed DRAINING node re-arms fresh windows."""
        rec = self.node_lifecycle.setdefault(node_id, {"node_id": node_id})
        if rec.get("state") == "DEPARTED":
            # Terminal: a node that died mid-drain must keep its death
            # record even if the in-flight drain step finishes its (now
            # empty) evacuation and tries to close the drain as planned.
            return
        if rec.get("state") == state and all(
            rec.get(k) == v for k, v in kw.items()
        ):
            return  # no-op re-assertion: don't re-journal it
        rec["state"] = state
        rec.update(kw)
        self._journal_append(("node_lifecycle", node_id, state, dict(kw)))
        self.events.emit(
            "INFO", "autoscale", "node lifecycle", node_id=node_id,
            state=state, **kw,
        )

    def demand_summary(self) -> dict:
        """The head's published resource-demand view — what the autoscaler
        reconciles against and `ray_tpu status` renders: unplaceable/queued
        SchedulingKey buckets with wait-age, pending + RESHAPING placement
        -group bundles, and serve deployments' replica targets (published
        into the KV plane by the serve controller's reconcile loop)."""
        now_wall = time.time()
        with self.lock:
            buckets = []
            for shape, q in self.ready_queue.buckets.items():
                # Buckets are FIFO: the head task is the oldest, so the
                # scan stays O(shapes), never O(queued tasks).
                head = None
                for tid in q:
                    rec = self.tasks.get(tid)
                    if rec is not None and not rec.cancelled:
                        head = rec
                        break
                if head is None:
                    continue
                t = head.stages.get("queued") or head.stages.get("submit")
                buckets.append(
                    {
                        "key": repr(shape),
                        "resources": dict(head.spec.resources),
                        "count": len(q),
                        "wait_s": round(max(now_wall - t, 0.0), 3)
                        if t is not None
                        else 0.0,
                    }
                )
            spilled = self._ready_spill.count if self._ready_spill else 0
            pending_bundles = []
            for pg in self.state.placement_groups.values():
                if pg.state in ("PENDING", "RESHAPING"):
                    pending_bundles.append(
                        {
                            "pg_id": pg.pg_id,
                            "state": pg.state,
                            "bundles": [dict(b) for b in pg.bundles],
                        }
                    )
        import json as _json

        serve_targets = {}
        raw = self.state.kv_get("replica_targets", "serve")
        if raw:
            try:
                serve_targets = _json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                serve_targets = {}
        return {
            "task_buckets": buckets,
            "queued_tasks": sum(b["count"] for b in buckets) + spilled,
            "max_wait_s": max((b["wait_s"] for b in buckets), default=0.0),
            "pending_bundles": pending_bundles,
            "serve_targets": serve_targets,
        }

    def _write_snapshot(self) -> None:
        from ray_tpu._private.gcs import actor_record

        # Lock order everywhere else is self.lock -> state.lock (handshake
        # and io threads take self.lock then call into GlobalState); taking
        # them in the opposite order here would be an ABBA deadlock.
        with self.lock, self.state.lock:
            # EVERY live actor record is persisted — anonymous ones too
            # (ray: gcs_actor_manager keeps all records in the GCS tables;
            # only terminal DEAD rows are dropped, restore skips them
            # anyway).  Anonymous records are what let a replica that died
            # during a head outage be re-resolved and restarted.
            actors = [
                actor_record(info)
                for info in self.state.actors.values()
                if info.state != DEAD
            ]
            # In-flight PLAIN task specs: a head crash mid-flight re-drives
            # them on restart so their results still materialize for
            # reconnected drivers (ray: lineage-based resubmission after
            # GCS failover).  Actor work re-drives via the actor records;
            # oversized arg blobs are skipped — their argument objects
            # would not survive the head's store anyway.
            from ray_tpu._private import config as _cfg

            max_blob = _cfg.get("snapshot_inflight_max_blob_bytes")
            max_tasks = _cfg.get("snapshot_inflight_max_tasks")
            inflight = []
            for rec in self.tasks.values():
                spec = rec.spec
                if (
                    spec.actor_id is None
                    and not spec.is_actor_creation
                    and not rec.cancelled
                    and len(spec.args_blob or b"") <= max_blob
                ):
                    inflight.append(spec)
                    if len(inflight) >= max_tasks:
                        break
            snap = {
                "session": self.session_name,
                "kv": {ns: dict(d) for ns, d in self.state.kv.items()},
                "functions": dict(self.state.functions),
                "actors": actors,
                "placement_groups": {
                    pid: _pg_record(pg)
                    for pid, pg in self.state.placement_groups.items()
                    if pg.state != "REMOVED"
                },
                "object_locations": {
                    k: set(v) for k, v in self.object_locations.items()
                },
                "object_sizes": dict(self.object_sizes),
                "inflight_tasks": inflight,
                "jobs": {jid: dict(rec) for jid, rec in self.state.jobs.items()},
                # Autoscaler node-lifecycle table (journal kind
                # "node_lifecycle" folds in on top at restore).
                "node_lifecycle": {
                    nid: dict(rec) for nid, rec in self.node_lifecycle.items()
                },
                # Completed inline results' producer specs (bounded: a
                # subset of the lineage table, which lineage_max_bytes /
                # lineage_max_entries already cap) — these bytes live only
                # in this process, so lineage is their ONLY recovery.
                "lineage": [
                    (oid, self.lineage[oid])
                    for oid in self.lineage
                    if oid in self._inline_lineage
                ],
            }
        self._snapshot_storage.save(self.session_name, snap)
        if self._journal is not None:
            # Compaction: the snapshot now contains every journaled
            # mutation.  Skipped when the save above raised (the journal
            # then still replays over the PREVIOUS snapshot).
            self._journal.reset()

    def _restore_snapshot(self) -> None:
        """Replay persisted control state on head restart: KV, exported
        functions, the object directory, PGs (re-reserved as nodes return),
        inline-result lineage, the job table, and ALL actor records —
        named, detached, AND anonymous (recreated from their creation
        specs; live-worker adoption / re-announcement upgrades this when
        the worker reconnects).  The rebuilt actor table is snapshot +
        journal replay; the reconciliation handshake layers worker
        re-announcements on top."""
        snap = self._snapshot_storage.load(self.session_name)
        journal_entries = self._journal.replay() if self._journal is not None else []
        if snap is None and not journal_entries:
            return
        snap = snap or {}
        from ray_tpu._private import config as _config
        for ns, d in snap.get("kv", {}).items():
            self.state.kv.setdefault(ns, {}).update(d)
        self.state.import_functions(snap.get("functions", {}))
        for oid, locs in snap.get("object_locations", {}).items():
            self.object_locations.setdefault(oid, set()).update(locs)
            # Surviving node copies must satisfy gets on the restarted
            # head: without the readiness mark, a get would park forever
            # next to bytes the directory knows about.
            self.store.mark_remote_sealed(oid)
        self.object_sizes.update(snap.get("object_sizes", {}))
        # PG table: snapshot rows merged with journal replay below (the
        # dict form is pg_record; pre-remesh snapshots held 4-tuples).
        pgs_by_id: Dict[str, dict] = {}
        for pid, rec in snap.get("placement_groups", {}).items():
            if isinstance(rec, dict):
                pgs_by_id[pid] = dict(rec)
            else:
                bundles, strategy, name, pstate = rec
                pgs_by_id[pid] = {
                    "pg_id": pid, "bundles": bundles, "strategy": strategy,
                    "name": name, "state": pstate,
                }
        # ---- merge the actor/job tables: snapshot + journal replay.  The
        # journal holds every mutation since the snapshot's tick (torn
        # tail already truncated by replay()), so applying the entries in
        # order rebuilds the tables as of the crash.
        actors_by_id = {a["actor_id"]: dict(a) for a in snap.get("actors", [])}
        jobs: Dict[str, dict] = {
            jid: dict(rec) for jid, rec in snap.get("jobs", {}).items()
        }
        node_lc: Dict[str, dict] = {
            nid: dict(rec)
            for nid, rec in snap.get("node_lifecycle", {}).items()
        }
        restored_lineage = list(snap.get("lineage", []))
        for entry in journal_entries:
            try:
                kind = entry[0]
                if kind == "actor_register":
                    rec = dict(entry[1])
                    actors_by_id[rec["actor_id"]] = rec
                elif kind == "actor_state":
                    _, aid, astate, kw = entry
                    rec = actors_by_id.get(aid)
                    if rec is not None:
                        rec["state"] = astate
                        for k, v in kw.items():
                            rec[k] = v
                elif kind == "job_state":
                    _, jid, jstate, kw = entry
                    jobs.setdefault(jid, {"job_id": jid}).update(
                        {"state": jstate, **kw}
                    )
                elif kind == "pg_register":
                    rec = dict(entry[1])
                    pgs_by_id[rec["pg_id"]] = rec
                elif kind == "pg_state":
                    _, pid, pstate, kw = entry
                    rec = pgs_by_id.get(pid)
                    if rec is not None:
                        rec["state"] = pstate
                        rec.update(kw)
                elif kind == "node_lifecycle":
                    _, nid, nstate, kw = entry
                    rec = node_lc.setdefault(nid, {"node_id": nid})
                    rec["state"] = nstate
                    rec.update(kw)
                elif kind == "lineage":
                    restored_lineage.append((entry[1], entry[2]))
                elif kind == "function":
                    # Function exports journaled since the last snapshot:
                    # without these, a lineage re-execution of a task whose
                    # fn was exported within the final 0.5s tick fails
                    # "unknown function" (the PR-4 residual).
                    self.state.import_functions({entry[1]: entry[2]})
            except (IndexError, KeyError, TypeError, ValueError):
                continue  # malformed journal entry: skip, don't block boot
        for jid, rec in jobs.items():
            kw = {k: v for k, v in rec.items() if k not in ("job_id", "state")}
            self.state.set_job_state(jid, rec.get("state", "RUNNING"), **kw)
        # Node-lifecycle restore decisions (the journal-coverage lint's
        # KNOWN_KINDS entry documents these):
        #   DEPARTED  — stays departed (terminal; a retried drain across the
        #               bounce answers instead of re-draining a ghost);
        #   DRAINING  — resumes draining: the daemon's re-registration
        #               re-marks NodeInfo.draining and the reconciler picks
        #               the drain back up with FRESH timing windows (the
        #               PR-11 rule: never skip ahead on stale wall-clock);
        #   REQUESTED/STARTING — kept as-is; the reconciler re-checks them
        #               against the provider and re-arms the launch timeout;
        #   ACTIVE    — re-confirmed by the daemon's reconnect (the death
        #               path flips it to DEPARTED if it never comes back).
        self.node_lifecycle.update(node_lc)
        for pid, rec in pgs_by_id.items():
            if pid in self.state.placement_groups:
                continue
            try:
                pg = PlacementGroupInfo(
                    pid, rec["bundles"], rec["strategy"], name=rec.get("name"),
                    orig_bundles=[
                        dict(b)
                        for b in (rec.get("orig_bundles") or rec["bundles"])
                    ],
                    generation=int(rec.get("generation", 0)),
                    lost_node=rec.get("lost_node"),
                )
            except (KeyError, TypeError):
                continue  # malformed record: skip, don't block boot
            pstate = rec.get("state", "PENDING")
            if pstate == "REMOVED":
                # Kept (not re-queued) so a retried pg_remove/pg_state
                # across the bounce answers instead of "unknown pg".
                pg.state = "REMOVED"
                self.state.restore_pg(pg)
            elif pstate == "RESHAPING":
                # Died mid-reshape: resume the episode.  The wait deadline
                # is head-local and NOT persisted — the sweep re-arms a
                # fresh remesh_wait_s window on first sight (a bounce
                # extends the replacement wait; it never skips straight to
                # shrink on stale wall-clock).
                pg.state = "RESHAPING"
                self.state.restore_pg(pg)
            else:
                # PENDING and CREATED both re-reserve: bundle reservations
                # are volatile, the rebuilt node table re-acquires them.
                self.state.restore_pg(pg)
                self.pending_pgs.append(pid)
        # Inline-result lineage: the bytes died with the old head, but the
        # producer specs survive — a get() on one of these re-executes from
        # lineage instead of parking forever (ray: task_manager.h:97 +
        # object_recovery_manager.h:41 across GCS failover).
        with self.lock:
            for oid, spec in restored_lineage:
                try:
                    self._lineage_record(oid, spec)
                    self._inline_lineage.add(oid)
                except Exception:
                    continue
        for a in actors_by_id.values():
            if a["state"] == DEAD or a["actor_id"] in self.state.actors:
                continue
            spec = a["creation_spec"]
            if spec is None:
                continue
            if (
                a.get("owner_did")
                and not a["detached"]
                and jobs.get(a["owner_did"], {}).get("state") == "FINISHED"
            ):
                continue  # non-detached actor whose owner job already ended
            info = ActorInfo(
                actor_id=a["actor_id"],
                name=a["name"],
                namespace=a["namespace"],
                max_restarts=a["max_restarts"],
                num_restarts=a.get("num_restarts", 0),
                creation_spec=spec,
                detached=a["detached"],
                owner_did=a.get("owner_did"),
                state=RESTARTING,
                worker_id=a.get("worker_id"),
                node_id=a.get("node_id"),
            )
            try:
                self.state.register_actor(info)
            except ValueError:
                continue
            self.actors[spec.actor_id] = ActorRuntime(info)
            self._restored_actors.add(spec.actor_id)
        if self._restored_actors:
            # Give live workers the adoption grace to reconnect and re-bind
            # (actor memory state PRESERVED); whatever stays unbound is then
            # respawned from its creation spec (state reset; anonymous
            # actors charge their restart budget for the outage death) —
            # ray: gcs_actor_manager reconstruction after GCS restart.
            t = threading.Timer(
                _config.get("actor_adopt_grace_s"), self._respawn_unbound_actors
            )
            t.daemon = True
            t.start()
            # Restored NON-detached actors whose owner driver never
            # re-attaches die with their job, exactly as they would have
            # on a live head (ray: OnJobFinished) — after a window long
            # enough for the owner's own reconnect loop to win.
            orphan_grace = max(
                _config.get("reconnect_window_s"),
                _config.get("actor_adopt_grace_s"),
            ) + 2.0
            orphans = [
                aid
                for aid in self._restored_actors
                if (ar := self.actors.get(aid)) is not None
                and ar.info.owner_did
                and not ar.info.detached
            ]
            if orphans:
                t2 = threading.Timer(
                    orphan_grace, self._reap_ownerless_actors, args=(orphans,)
                )
                t2.daemon = True
                t2.start()
        # Re-drive tasks that were in flight at the crash: their results
        # never sealed (or survive on a node — then the resubmit is
        # skipped), so reconnected drivers' gets park until the re-run
        # completes (ray: owner-side resubmission after failover).  Chains
        # re-drive together (a dep produced by another re-driven task
        # resolves when it runs); a dep with NO surviving copy and NO
        # re-driven producer is unrecoverable — its task fails with
        # ObjectLostError now instead of parking forever.  Infeasible
        # shapes PARK (allow_pending) until the daemons rejoin.
        inflight = snap.get("inflight_tasks", [])
        will_produce = {o for s in inflight for o in s.return_ids()}
        for spec in inflight:
            if all(self.store.is_ready(o) for o in spec.return_ids()):
                continue
            lost = [
                d for d in spec.deps
                if not self.store.is_ready(d) and d not in will_produce
            ]
            if lost:
                self.events.emit(
                    "WARNING", "runtime",
                    "re-driven task dropped: input lost with the old head",
                    task=spec.name, missing=lost[0],
                )
                for oid in spec.return_ids():
                    self.store.put_error(oid, ObjectLostError(lost[0]))
                    self._object_ready(oid)
                continue
            spec.attempt = 0
            try:
                self.submit_task(spec, allow_pending=True)
            except Exception:
                continue  # malformed snapshot entry: skip, don't block boot

    def _respawn_unbound_actors(self) -> None:
        """Adoption grace expired: recreate restored actors whose worker
        never came back.  Named/detached actors respawn unconditionally
        (persistent by contract); anonymous actors — the records this PR
        made durable — charge their restart budget for the outage death,
        exactly as a live-head worker crash would (ray:
        gcs_actor_manager.h:258 counts ALIVE->dead transitions)."""
        specs = []
        with self.lock:
            doomed = []
            for aid in list(self._restored_actors):
                ar = self.actors.get(aid)
                self._restored_actors.discard(aid)
                if not (
                    ar is not None
                    and ar.info.state == RESTARTING
                    and ar.worker_id is None
                    and ar.info.creation_spec is not None
                ):
                    continue
                info = ar.info
                info.worker_id = None
                if info.detached or info.name:
                    specs.append(info.creation_spec)
                elif info.max_restarts == -1 or info.num_restarts < info.max_restarts:
                    self.metrics["actor_restarts"] += 1
                    self.events.emit(
                        "WARNING", "actor",
                        "anonymous actor restarting after head outage",
                        actor_id=aid, restart=info.num_restarts + 1,
                    )
                    # set_actor_state journals the charged budget, so a
                    # SECOND head bounce restores the decremented budget.
                    self.state.set_actor_state(
                        aid, RESTARTING, num_restarts=info.num_restarts + 1
                    )
                    specs.append(info.creation_spec)
                else:
                    doomed.append((aid, ar))
            for aid, ar in doomed:
                self.state.set_actor_state(
                    aid, DEAD,
                    death_cause="died during head outage; restart budget exhausted",
                )
                self._fail_actor_queue(ar, ActorDiedError(aid))
        for spec in specs:
            self.submit_task(spec)

    def _reap_ownerless_actors(self, candidates: List[str]) -> None:
        """Owner-reconnect grace expired: restored non-detached actors
        whose owning driver (job) never re-attached die with their job —
        the restarted head finishes what OnJobFinished would have done on
        a live head, and journals the job as FINISHED so the NEXT bounce
        does not resurrect them."""
        doomed = []
        with self.lock:
            for aid in candidates:
                ar = self.actors.get(aid)
                if ar is None or ar.info.state == DEAD:
                    continue
                did = ar.info.owner_did
                if did and did not in self.drivers:
                    doomed.append((aid, did))
            for _aid, did in doomed:
                if self.state.jobs.get(did, {}).get("state") != "FINISHED":
                    self.state.set_job_state(did, "FINISHED", reason="never re-attached")
        for aid, _did in doomed:
            self.events.emit(
                "INFO", "actor", "reaping actor of non-returning owner",
                actor_id=aid,
            )
            self.kill_actor(aid, no_restart=True)

    # ------------------------------------------------------------------
    # refcounting (owner side)

    def _addref_local(self, oid: str) -> None:
        self.store.add_ref(oid)

    def _decref_local(self, oid: str) -> None:
        if self._shutdown:
            return
        contained = None
        with self.lock:
            if self.store.refcount(oid) == 1:
                contained = self.contained_map.pop(oid, None)
            freed = self.store.remove_ref(oid)
            if freed:
                # No ref can ever need this object again — its lineage
                # entry is dead weight (ray: lineage release callback,
                # task_manager.h:116).
                entry = self.lineage.pop(oid, None)
                if entry is not None:
                    self.lineage_bytes -= self._lineage_cost(entry)
                self._inline_lineage.discard(oid)
                self.object_sizes.pop(oid, None)
                self.object_meta.pop(oid, None)
                self._xfer_plans.pop(oid, None)  # freed mid-broadcast
                # Remote copies die with the ownership release (ray: the
                # owner's directory drives eviction on every holder node).
                locs = self.object_locations.pop(oid, None)
                if locs:
                    for n in locs:
                        self._daemon_send(n, ("delete_object", oid))
        if contained:
            for c in contained:
                self._decref_local(c)

    def _store_contained(self, oid: str, contained: List[str]) -> None:
        if not contained:
            return
        with self.lock:
            self.contained_map[oid] = list(contained)
        for c in contained:
            self.store.add_ref(c)

    # ------------------------------------------------------------------
    # object ledger (memory introspection plane)

    def _obj_event(self, oid: str, event: str, nbytes=None, node=None) -> None:
        """Append one object lifecycle event (bounded ring; deque append
        is GIL-atomic — callable from under the store lock)."""
        try:
            self.object_events.append(
                {
                    "t": time.time(),
                    "oid": oid,
                    "event": event,
                    "bytes": nbytes,
                    "node": node or self.head_node_id,
                }
            )
        except Exception:
            pass  # observability never takes the control plane down

    def _on_store_lifecycle(self, oid: str, event: str, nbytes) -> None:
        # OwnerStore hook: spill/restore/free transitions (may fire under
        # store._lock — keep this append-only).
        self._obj_event(oid, event, nbytes)

    def _note_object(self, oid: str, creator: str) -> None:
        """First sighting of a sealed object: creation time + creator for
        the ledger's age/owner attribution (GIL-atomic dict write)."""
        if oid not in self.object_meta:
            self.object_meta[oid] = (time.time(), creator)

    def reclaim_dead_refs(self, force: bool = False) -> int:
        """Drop the outstanding ref borrows of crashed processes whose
        reclaim grace lapsed (the dead-holder leak suspects): each borrow
        decrefs like the lost refop del would have, freeing the bytes the
        dead holder pinned.  Returns the number of holders reclaimed.
        Runs on the io-loop reap tick; force=True (tests, shutdown paths)
        ignores the grace."""
        now = time.monotonic()
        with self.lock:
            doomed = [
                (wid, rec)
                for wid, rec in self._dead_refs.items()
                if force or now >= rec["reclaim_at"]
            ]
            for wid, _rec in doomed:
                self._dead_refs.pop(wid, None)
        for wid, rec in doomed:
            refs = rec.get("refs") or {}
            self.events.emit(
                "INFO", "object", "dead holder refs reclaimed",
                worker_id=wid, objects=len(refs),
                node_id=rec.get("node"),
            )
            for oid, n in refs.items():
                for _ in range(max(int(n), 0)):
                    self._decref_local(oid)
        return len(doomed)

    def _ledger_conn_refs(self):
        """Holder-side inputs of the ledger join: conn-tracked borrow
        tables (workers + attached drivers), this head process's own
        live-ref table, the pushed refs_push snapshots (sites/owned
        enrichment), and node/pid attribution per holder."""
        from ray_tpu._private import refs as refs_mod

        with self.lock:
            conn_refs: Dict[str, Dict[str, int]] = {
                w: dict(m) for w, m in self.worker_refs.items() if m
            }
            for did, m in self.driver_refs.items():
                if m:
                    conn_refs[did] = dict(m)
            proc_info: Dict[str, tuple] = {}
            for wid, h in self.workers.items():
                if h.state != "dead":
                    proc_info[wid] = (h.node_id, h.pid)
            for did in self.drivers:
                proc_info[did] = (self.driver_nodes.get(did), None)
        head_snap = refs_mod.snapshot_refs()
        conn_refs["head"] = {
            oid: rec[0] for oid, rec in head_snap["refs"].items()
        }
        proc_info["head"] = (self.head_node_id, os.getpid())
        pushed = self.ledger.snapshot()
        pushed["head"] = head_snap
        return conn_refs, pushed, proc_info

    def memory_records(self, limit: Optional[int] = None) -> List[dict]:
        """Per-object ledger records: the owner tables (store, directory,
        sizes, meta) joined with every holder-side ref table — the
        `ray memory` data model (SURVEY §2.1)."""
        from ray_tpu._private import config as _config
        from ray_tpu._private import telemetry as _telemetry

        store_table, rc, ready = self.store.snapshot_table()
        with self.lock:
            locations = {
                o: sorted(s) for o, s in self.object_locations.items()
            }
            sizes = dict(self.object_sizes)
            meta = dict(self.object_meta)
            dead = {w: dict(r) for w, r in self._dead_refs.items()}
        conn_refs, pushed, proc_info = self._ledger_conn_refs()
        recs = _telemetry.build_memory_records(
            store_table, rc, ready, locations, sizes, meta,
            conn_refs, pushed, dead, proc_info,
            now=time.time(), leak_age_s=_config.get("leak_age_s"),
        )
        return recs[:limit] if limit else recs

    def memory_summary(
        self,
        group_by: Optional[str] = None,
        top: int = 20,
        include_events: bool = False,
    ) -> dict:
        from ray_tpu._private import telemetry as _telemetry

        out = _telemetry.summarize_memory_records(
            self.memory_records(), group_by=group_by, top=top
        )
        if include_events:
            out["events"] = list(self.object_events)[-200:]
        return out

    # ------------------------------------------------------------------
    # profiling plane (profiler.py): cluster-wide sampling control + merge

    def profile_start(self, hz: Optional[float] = None) -> dict:
        """Start the sampler cluster-wide: locally in this process, and by
        pubsub broadcast in every subscribed worker ("profiler" channel,
        key "ctl").  Idempotent; returns the effective rate."""
        from ray_tpu._private import profiler as _profiler

        eff = _profiler.start(hz)
        self.pubsub.publish("profiler", "ctl", "start", eff)
        self.events.emit(
            "INFO", "profiler", "cluster-wide sampling started", hz=eff
        )
        return {"hz": eff}

    def profile_stop(self) -> dict:
        """Stop sampling cluster-wide.  Workers push a final table on the
        stop broadcast; tables already pushed stay in the sink for
        profile_report (cumulative payloads make this race-free)."""
        from ray_tpu._private import profiler as _profiler

        self.pubsub.publish("profiler", "ctl", "stop")
        _profiler.stop()
        return {"stopped": True}

    def profile_report(
        self, node: Optional[str] = None, pid: Optional[int] = None
    ) -> dict:
        """Merged flamegraph: every pushed per-process table plus a fresh
        local snapshot, optionally filtered to one node or pid."""
        from ray_tpu._private import profiler as _profiler

        snap = _profiler.snapshot_payload()
        if snap.get("n"):
            self.profiles.ingest("head", snap, node=self.head_node_id)
        return self.profiles.merged(node=node, pid=pid)

    def task_summary_local(self, slow: int = 10) -> dict:
        """Stage-attributed task summary over the finished-task ring +
        live tasks (the `ray_tpu tasks` body; pure fold in telemetry.py)."""
        from ray_tpu._private import telemetry as _telemetry

        now = time.time()
        with self.lock:
            events = [dict(e) for e in self.task_events]
            live = []
            for tid, rec in self.tasks.items():
                stages = dict(rec.stages)
                last = max(stages.values()) if stages else now
                live.append(
                    {
                        "task_id": tid,
                        "name": rec.spec.name,
                        "state": rec.state,
                        "stages": stages,
                        "age_s": round(now - stages.get("submit", last), 6),
                        "stuck_s": round(now - last, 6),
                    }
                )
        out = _telemetry.summarize_task_events(events, live, slow=slow)
        out["live"] = sorted(live, key=lambda t: -t["stuck_s"])[: max(slow, 0)]
        return out

    def _blocked_get_detail(self, oids) -> str:
        """Critical-path hint for a timed-out get(): which lifecycle stage
        each still-pending producing task is stuck in, and for how long —
        the one-line diagnosis a p99 hunt needs (never raises)."""
        try:
            from ray_tpu._private import telemetry as _telemetry

            now = time.time()
            parts = []
            with self.lock:
                for oid in list(oids)[:4]:
                    tid = oid.split(":")[1] if oid.startswith("o:") else None
                    rec = self.tasks.get(tid) if tid else None
                    if rec is None:
                        continue
                    present = [
                        s for s in _telemetry.STAGE_ORDER
                        if isinstance(rec.stages.get(s), (int, float))
                    ]
                    if not present:
                        continue
                    last = present[-1]
                    label = _telemetry.STAGE_LABELS.get(last, last)
                    durs = _telemetry.stage_durations(rec.stages)
                    hist = " ".join(
                        f"{k}={v:.3f}s" for k, v in durs.items()
                    )
                    parts.append(
                        f"task {tid} ({rec.spec.name}) stuck in stage "
                        f"'{label}' for {now - rec.stages[last]:.3f}s"
                        + (f" after [{hist}]" if hist else "")
                    )
            return "; ".join(parts)
        except Exception:
            return ""

    def get_logs_all(self, n: Optional[int] = None) -> dict:
        """Aggregate log tail across every worker that produced output,
        with node/pid attribution (`ray_tpu logs --all`)."""
        with self.lock:
            wids = list(self.worker_logs)
            info = {
                wid: (h.node_id, h.pid) for wid, h in self.workers.items()
            }
        out = {}
        for wid in wids:
            node, pid = info.get(wid, (None, None))
            out[wid] = {
                "node": node,
                "pid": pid,
                "lines": self.get_logs(wid, n),
            }
        return out

    def _ledger_tick(self) -> None:
        """Refresh the Prometheus-facing ledger gauges (per-node store/
        spilled bytes, per-node leak-suspect bytes) from a fresh join,
        and run the orphan reclaim sweep.  Runs on the head telemetry
        thread each push tick."""
        from ray_tpu._private import config as _config
        from ray_tpu._private import telemetry as _telemetry

        records = self.memory_records()
        summary = _telemetry.summarize_memory_records(records, top=0)
        # Orphan reclaim: a NO-LIVE-HOLDER suspect that stays flagged
        # across leak_orphan_reclaim_s of consecutive ticks has no path
        # back to a positive refcount (any process that could still send
        # the missing add would list the oid in its pushed ref table and
        # un-flag it) — free it, LOUDLY.  The shape this closes: after a
        # head bounce the restored store has no refcounts, a re-driven
        # task re-seals its result at rc 0, and the owner's already-sent
        # release sits buffered forever (the chaos soak's ledger
        # convergence assertion found exactly this).
        grace = _config.get("leak_orphan_reclaim_s")
        if grace > 0 and _config.get("refs_push"):
            now = time.monotonic()
            flagged = getattr(self, "_orphan_flagged", None)
            if flagged is None:
                flagged = self._orphan_flagged = {}
            current = {
                r["object_id"]: r
                for r in records
                if r["leak"] == "no-live-holder"
            }
            for oid in list(flagged):
                if oid not in current:
                    flagged.pop(oid, None)
            for oid, r in current.items():
                first = flagged.setdefault(oid, now)
                if now - first < grace:
                    continue
                flagged.pop(oid, None)
                self.events.emit(
                    "WARNING", "object",
                    "orphaned object reclaimed (no live holder)",
                    object_id=oid, size_bytes=r["size_bytes"],
                    age_s=r["age_s"],
                )
                self._decref_local(oid)  # rc 0 + known -> frees the bytes
        g_bytes, g_leak = _telemetry.ledger_gauges()
        leak_by_node: Dict[str, float] = {}
        for r in summary["leaks"]:
            node = next(
                (
                    h["node"]
                    for h in r["holders"]
                    if h.get("dead") and h.get("node")
                ),
                None,
            ) or "head"
            leak_by_node[node] = leak_by_node.get(node, 0.0) + float(
                r["size_bytes"] or 0
            )
        nodes = set(summary["nodes"]) | set(leak_by_node)
        stale = getattr(self, "_ledger_gauge_nodes", set()) - nodes
        for node, rec in summary["nodes"].items():
            g_bytes.set(
                rec["store_bytes"], tags={"node": str(node), "tier": "store"}
            )
            g_bytes.set(
                rec["spilled_bytes"],
                tags={"node": str(node), "tier": "spilled"},
            )
        for node in nodes:
            g_leak.set(leak_by_node.get(node, 0.0), tags={"node": str(node)})
        for node in stale:  # removed nodes zero out instead of lingering
            g_bytes.set(0.0, tags={"node": str(node), "tier": "store"})
            g_bytes.set(0.0, tags={"node": str(node), "tier": "spilled"})
            g_leak.set(0.0, tags={"node": str(node)})
        self._ledger_gauge_nodes = nodes

    # ------------------------------------------------------------------
    # worker pool (ray: src/ray/raylet/worker_pool.h:156)

    def _daemon_send(self, node_id: str, msg: tuple) -> None:
        conn = self.node_daemons.get(node_id)
        if conn is None:
            return
        try:
            conn.send(msg)
        except OSError:
            pass

    def _on_driver_death(self, did: str) -> None:
        """An attached driver's conn EOF'ed (exit or kill -9): the head
        lives on.  Drop the driver's ref borrows, kill its non-detached
        actors; lifetime="detached" actors keep serving
        (ray: gcs_actor_manager OnJobFinished + gcs_job_manager)."""
        self.telemetry.forget(did)
        self.ledger.forget(did)
        self.profiles.forget(did)
        with self.lock:
            self.drivers.pop(did, None)
            self.driver_nodes.pop(did, None)
            self._drop_remote_subs(did)
            self.state.set_job_state(did, "FINISHED", reason="driver death")
            refs = self.driver_refs.pop(did, {})
            doomed = [
                aid
                for aid, ar in self.actors.items()
                if ar.info.owner_did == did
                and not ar.info.detached
                and ar.info.state != DEAD
            ]
        for oid, count in refs.items():
            for _ in range(count):
                self._decref_local(oid)
        for aid in doomed:
            self.kill_actor(aid, no_restart=True)

    @_locked
    def _on_daemon_death(self, node_id: str) -> None:
        """Caller holds self.lock.  Node failure: the daemon's whole worker
        pool dies with it (the daemon terminates its children on exit)."""
        self.node_daemons.pop(node_id, None)
        self.node_object_endpoints.pop(node_id, None)
        self._daemon_heartbeats.pop(node_id, None)
        self.node_daemon_pids.pop(node_id, None)
        if node_id in self._expected_node_removals:
            self._expected_node_removals.discard(node_id)
            self.events.emit("INFO", "node", "node removed", node_id=node_id)
            planned = True
        else:
            self.events.emit("ERROR", "node", "node died", node_id=node_id)
            planned = False
        # Lifecycle: any tracked node leaving — planned depart OR death
        # (including a death MID-DRAIN, which from here on is exactly the
        # existing death path: lineage/retry covers what evacuation had
        # not yet moved) — lands in the terminal DEPARTED state.
        if node_id in self.node_lifecycle:
            self._set_node_lifecycle(
                node_id, "DEPARTED",
                reason="removed" if planned else "died",
            )
        # Copies on the dead node are gone; objects whose ONLY copy lived
        # there become lost-bytes (gets fall through to lineage
        # reconstruction, exactly like a lost spill file).
        for oid in list(self.object_locations):
            locs = self.object_locations[oid]
            locs.discard(node_id)
            if not locs:
                del self.object_locations[oid]
        # Transfer plans: the dead node's in-flight slot frees, and any
        # relay feed it was serving is withdrawn — downstreams fall back
        # to the sealed tail of their plan or re-ask (re-plan, not wedge).
        for oid in list(self._xfer_plans):
            self._release_pull_slot_locked(oid, node_id)
            st = self._xfer_plans.get(oid)
            if st is None:
                continue
            for ep, f in list(st["feeds"].items()):
                if f.get("node") == node_id:
                    del st["feeds"][ep]
        self.state.remove_node(node_id)
        for wid, h in list(self.workers.items()):
            if h.node_id == node_id and h.state != "dead":
                if isinstance(h.proc, _RemoteProcHandle):
                    h.proc.dead = True
                self._on_worker_crash(wid)
        # A MESH gang that lost this host is torn as a whole: withdraw it
        # and open a RESHAPING episode (the io-loop sweep advances it).
        self._withdraw_mesh_gangs(node_id)

    def _child_env(self, extra: Dict[str, str]) -> Dict[str, str]:
        """Base env for child processes (workers/daemons): driver address +
        authkey + a PYTHONPATH carrying the driver's module search path."""
        import sys

        host, port = self.address
        env = os.environ.copy()
        env.update(
            {
                "RAY_TPU_DRIVER_HOST": host,
                "RAY_TPU_DRIVER_PORT": str(port),
                "RAY_TPU_AUTHKEY": self._authkey.hex(),
            }
        )
        env.update(extra)
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        paths = [pkg_root] + [p for p in sys.path if p] + (
            env.get("PYTHONPATH", "").split(os.pathsep) if env.get("PYTHONPATH") else []
        )
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(paths))
        return env

    def add_daemon_node(
        self,
        num_cpus: float = 1.0,
        resources: Optional[Dict] = None,
        labels: Optional[Dict[str, str]] = None,
        wait_timeout: float = 30.0,
        store_root: Optional[str] = None,
    ) -> str:
        """Launch a node daemon PROCESS on this machine and wait for it to
        register (the test-side analogue of starting a raylet on another
        host; in a real deployment the daemon starts remotely pointing at
        this driver's address)."""
        import json
        import subprocess
        import sys

        nid = ids.node_id()
        env = self._child_env(
            {
                "RAY_TPU_NODE_CONFIG": json.dumps(
                    {
                        "node_id": nid,
                        "session": self.session_name,
                        "num_cpus": num_cpus,
                        "resources": resources or {},
                        "labels": labels or {},
                        "store_root": store_root,
                    }
                ),
            }
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_daemon"],
            env=env,
            close_fds=True,
        )
        self._daemon_procs[nid] = proc
        deadline = time.monotonic() + wait_timeout
        while time.monotonic() < deadline:
            if nid in self.node_daemons:
                return nid
            if proc.poll() is not None:
                self._daemon_procs.pop(nid, None)
                raise RuntimeError(f"node daemon exited rc={proc.returncode}")
            time.sleep(0.01)
        # Kill the straggler BEFORE raising, or it could register moments
        # later as a phantom node the caller was told doesn't exist.
        try:
            proc.terminate()
        except OSError:
            pass
        self._daemon_procs.pop(nid, None)
        raise TimeoutError("node daemon did not register in time")

    def _spawn_worker(self, node_id: str, env_key, renv, prestart: bool = False) -> WorkerHandle:
        if node_id in self.node_daemons:
            # Remote-node spawn: the daemon execs the worker on its host;
            # the worker connects straight back to this driver.
            wid = ids.worker_id()
            self.metrics["workers_spawned"] += 1
            self._daemon_send(node_id, ("spawn_worker", wid, renv or {}))
            handle = WorkerHandle(
                wid, node_id, env_key, renv, _RemoteProcHandle(self, node_id, wid)
            )
            self.workers[wid] = handle
            if prestart:
                self.starting_pool.setdefault((node_id, env_key), []).append(wid)
            return handle
        return self._spawn_local_worker(node_id, env_key, renv, prestart)

    def _spawn_local_worker(self, node_id: str, env_key, renv, prestart: bool = False) -> WorkerHandle:
        # Workers are exec'ed as fresh interpreters (`python -m ..worker_proc`)
        # rather than multiprocessing children: mp's spawn/forkserver children
        # re-import the driver's __main__ module during bootstrap, which
        # re-runs unguarded user scripts (and fork would inherit the driver's
        # threads + live XLA client).  Matches the reference, whose raylet
        # execs default_worker.py (ray: src/ray/raylet/worker_pool.h:156,
        # python/ray/_private/workers/default_worker.py).  When the zygote
        # fork server is up, spawns fork from its pre-imported interpreter
        # instead (~2ms vs ~250ms) — see zygote.py.
        import subprocess
        import sys

        wid = ids.worker_id()
        self.metrics["workers_spawned"] += 1
        from ray_tpu._private.runtime_env import worker_env_entries

        env_vars = (renv or {}).get("env_vars") or {}
        extra = {
            "RAY_TPU_WORKER_ID": wid,
            "RAY_TPU_SESSION": self.session_name,
            # stdout redirects to a log file (block-buffered by default):
            # unbuffered, or prints sit invisible until the worker exits.
            "PYTHONUNBUFFERED": "1",
            # Head-node workers share the HEAD store (explicit, so a
            # RAY_TPU_STORE_DIR inherited from any outer environment can
            # never leak a foreign node's store into these workers).
            "RAY_TPU_STORE_DIR": self.store.shm.dir,
            **worker_env_entries(renv),
        }
        proc = self._zygote_fork(wid, extra, env_vars)
        if proc is None:
            env = self._child_env(extra)
            # runtime_env vars must exist at interpreter start (sitecustomize
            # may import jax before worker_main applies them).
            env.update({k: str(v) for k, v in env_vars.items()})
            from ray_tpu._private.log_monitor import open_worker_logs

            outf, errf = open_worker_logs(self.log_dir, wid)
            try:
                popen = subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu._private.worker_proc"],
                    env=env,
                    close_fds=True,
                    stdout=outf,
                    stderr=errf,
                )
            finally:
                outf.close()  # the child holds its own dups; files outlive it
                errf.close()
            proc = _PopenHandle(popen)
        handle = WorkerHandle(wid, node_id, env_key, renv, proc)
        self.workers[wid] = handle
        if prestart:
            # Only unleased spawns are advertised as leasable; a demand spawn
            # is handed straight to its task.
            self.starting_pool.setdefault((node_id, env_key), []).append(wid)
        return handle

    def _zygote_fork(self, wid: str, extra: Dict[str, str], env_vars) -> Optional[_ZygoteProcHandle]:
        """Request a worker fork from the zygote; None = use the exec path
        (zygote not up yet / just died — it is (re)spawned in the
        background so the NEXT spawn forks)."""
        from ray_tpu._private import config as _config

        if not _config.get("use_zygote"):
            return None
        conn = self._zygote_conn
        if conn is None:
            self._ensure_zygote()
            return None
        # Start from the driver-env delta since the zygote's spawn: the
        # exec path re-snapshots os.environ per spawn, and fork-served
        # workers must not silently diverge (e.g. a token exported after
        # init must reach both kinds of worker).
        base = self._zygote_env or {}
        overrides = {
            k: v for k, v in os.environ.items() if base.get(k) != v
        }
        overrides.update(extra)
        overrides.update({k: str(v) for k, v in (env_vars or {}).items()})
        # The axon sitecustomize hook was stripped from the zygote's env
        # (it would import jax there, and forking a live XLA client is
        # undefined); restore it for the child so first jax use in the
        # worker still reaches the TPU.
        if self._zygote_axon_hook is not None:
            overrides.setdefault("PALLAS_AXON_POOL_IPS", self._zygote_axon_hook)
        from ray_tpu._private.log_monitor import worker_log_paths

        os.makedirs(self.log_dir, exist_ok=True)
        out_path, err_path = worker_log_paths(self.log_dir, wid)
        try:
            conn.send(("fork", wid, overrides, out_path, err_path))
        except OSError:
            self._zygote_conn = None
            self._ensure_zygote()
            return None
        return _ZygoteProcHandle(self._zygote_proc)

    def _ensure_zygote(self) -> None:
        """Spawn the fork server (once; respawned if it dies).  Never
        blocks: callers fall back to exec'ed workers until the zygote's
        handshake lands."""
        import subprocess
        import sys

        if self._shutdown:
            return
        if self._zygote_spawning:
            # Pending spawn — unless it died before ever handshaking
            # (import crash): then respawn.
            if not (
                self._zygote_conn is None
                and self._zygote_proc is not None
                and self._zygote_proc.poll() is not None
            ):
                return
        self._zygote_spawning = True
        env = self._child_env({"PYTHONUNBUFFERED": "1"})
        # Keep jax out of the zygote (see zygote.py docstring).
        self._zygote_axon_hook = env.pop("PALLAS_AXON_POOL_IPS", None)
        self._zygote_env = dict(env)  # per-fork overrides diff against this
        try:
            self._zygote_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.zygote"],
                env=env,
                close_fds=True,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        except OSError:
            self._zygote_spawning = False

    def _lease_worker(self, node_id: str, spec: TaskSpec) -> WorkerHandle:
        renv = spec.runtime_env or None
        env_key = _runtime_env_key(renv)
        pool = self.idle_pool.get((node_id, env_key))
        while pool:
            wid = pool.pop()
            h = self.workers.get(wid)
            if h is not None and h.state == "idle":
                return h
        # A spawned-but-not-yet-connected worker is leasable: its task is
        # queued in pending_sends and flushed on connect.
        pool = self.starting_pool.get((node_id, env_key))
        while pool:
            wid = pool.pop()
            h = self.workers.get(wid)
            if h is not None and h.state == "starting":
                return h
        if env_key is None and node_id == self.head_node_id:
            # Pool miss under default env: learn the burst size so the
            # next wave binds to prestarted workers instead of paying a
            # boot on the critical path (ray: worker_pool.h:156 prestart;
            # the io-loop tick tops the pool back up to this target while
            # the driver waits on results — converting barrier idle time
            # into worker boots).
            self._prestart_target = min(self._prestart_target + 1, 64)
            self._prestart_miss_t = time.monotonic()
        return self._spawn_worker(node_id, env_key, renv)

    def _return_worker(self, h: WorkerHandle) -> None:
        if h.state == "dead":
            return
        # Safety net: returning a still-leased worker (conn-reset
        # re-drive, any future path) must revoke its lease first or the
        # held resources would strand.  No recursion — revoke pops the
        # binding before it ever calls back here.
        le = self.lease_by_worker.get(h.worker_id)
        if le is not None:
            self._revoke_lease_locked(
                le, cause="worker_returned", return_worker=False
            )
        h.state = "idle"
        h.current_task = None
        h.idle_since = time.monotonic()
        self.idle_pool.setdefault((h.node_id, h.env_key), []).append(h.worker_id)

    def _send(self, h: WorkerHandle, msg: tuple) -> None:
        if h.conn is None:
            h.pending_sends.append(msg)
        else:
            try:
                # error -> the existing OSError path (delivery lost, like a
                # conn that broke mid-send); drop -> same, minus the raise.
                if faults.ENABLED and faults.point(
                    "head.send", key=msg[0] if msg else None
                ) == "drop":
                    return
                h.conn.send(msg)
            except OSError:
                pass

    # ------------------------------------------------------------------
    # io-shard fabric (io_shard.py): spawn/supervise shard processes, hand
    # conns off after the auth handshake, route their traffic both ways.

    def _start_io_shards(self, n: int) -> None:
        import tempfile

        from multiprocessing.connection import Listener as _Listener

        from ray_tpu._private import io_shard as _io_shard

        # AF_UNIX (required for SCM_RIGHTS fd passing) + pid-unique path:
        # a restarted head in the same session binds a fresh socket.
        path = os.path.join(
            tempfile.gettempdir(), f"raytpu-shards-{os.getpid():x}.sock"
        )
        try:
            os.unlink(path)
        except OSError:
            pass
        self._shard_listener = _Listener(
            address=path, family="AF_UNIX", authkey=self._authkey
        )
        self._shard_listener_path = path
        threading.Thread(
            target=self._shard_accept_loop, daemon=True,
            name="raytpu-shard-accept",
        ).start()
        for i in range(n):
            self._io_shards[i] = _io_shard.spawn_shard_process(
                i, path, self._authkey, self.session_name
            )
        # Bounded wait for the fabric: conns handshaken before a shard is
        # live stay head-direct for their lifetime, so give the shards a
        # beat to hello before the first worker wave connects.  Falling
        # through on timeout degrades to the in-process loop, never fails.
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if all(h.alive for h in self._io_shards.values()):
                break
            if any(h.proc.poll() is not None for h in self._io_shards.values()):
                break  # a shard died at spawn; supervision will respawn it
            time.sleep(0.02)

    def _shard_accept_loop(self) -> None:
        """Accept the per-shard channel pair (plain hello each: batched
        ctl for messages, raw fd channel for SCM_RIGHTS handoffs); a shard
        with both channels up goes live and starts receiving handoffs."""
        from ray_tpu._private import wire

        while not self._shutdown:
            try:
                conn = self._shard_listener.accept()
            except (OSError, EOFError):
                if self._shutdown:
                    return
                continue
            except Exception:
                continue  # authkey challenge failed: a stranger, not a shard
            try:
                hello = conn.recv()
            except (EOFError, OSError):
                continue
            if not (isinstance(hello, tuple) and len(hello) >= 3):
                conn.close()
                continue
            kind, idx, pid = hello[0], hello[1], hello[2]
            sh = self._io_shards.get(idx)
            if sh is None or sh.respawn_at:
                conn.close()  # unknown or already-declared-dead shard
                continue
            if kind == "io_shard":
                sh.ctl_conn = wire.batching(wire.wrap(conn))
                sh.pid = pid
            elif kind == "io_shard_fd":
                sh.fd_conn = conn
                sh.pid = pid
            else:
                conn.close()
                continue
            if sh.ctl_conn is not None and sh.fd_conn is not None and not sh.alive:
                sh.alive = True
                with self.lock:
                    self._conn_to_shard[sh.ctl_conn] = idx
                    self._conns_version += 1
                self.events.emit(
                    "INFO", "io_shard", "io shard online", shard=idx, pid=pid
                )

    def _pick_io_shard(self, peer_id: str):
        """Conn-hash over the LIVE shards (a dead shard's slice rehashes
        onto survivors at reconnect); None = keep the conn head-direct."""
        shards = self._io_shards
        if not shards:
            return None
        live = [h for _i, h in sorted(shards.items()) if h.alive]
        if not live:
            return None
        import zlib

        return live[zlib.crc32(str(peer_id).encode()) % len(live)]

    def _shard_route(self, conn, kind: str, peer_id: str):
        """(registree, shard): the ShardConnProxy to put in the conn maps
        when a live shard will own this conn, else (conn, None).  The
        caller registers the returned object, then (shard path) calls
        _complete_handoff to actually ship the fd."""
        from ray_tpu._private import io_shard as _io_shard

        sh = self._pick_io_shard(peer_id)
        if sh is None:
            return conn, None
        with self.lock:
            self._shard_conn_seq += 1
            conn_id = f"sc{self._shard_conn_seq}"
        return _io_shard.ShardConnProxy(sh, conn_id, kind, str(peer_id)), sh

    def _complete_handoff(self, sh, proxy, conn) -> None:
        """Ship a registered conn's fd to its shard.  Order matters: flush
        the real conn (handshake frames queued on its BatchingConn must
        hit the wire before anything the shard writes), dispatch frames
        decoded during the handshake but not yet delivered (the shard can
        only read the socket after the fd lands), then send the fd and
        close this process's copy."""
        try:
            _wire.flush_conn(conn)
        except (OSError, ValueError):
            pass  # dead socket: adopt anyway; the shard reports EOF at once
        sh.conns[proxy.conn_id] = proxy
        leftovers = []
        try:
            while conn.pending_frames():
                leftovers.append(conn.recv())
        except (EOFError, OSError):
            pass
        if leftovers:
            self._dispatch_sharded_msgs(proxy, leftovers)
        try:
            sh.adopt(proxy.conn_id, proxy.kind, proxy.peer_id, conn.fileno())
        except (OSError, ValueError):
            # Shard died mid-handoff: fail its conns over (this one's peer
            # reconnects through the normal window).
            self._on_io_shard_death(sh.idx)
        try:
            conn.close()
        except OSError:
            pass

    def _dispatch_sharded_msgs(self, proxy, msgs: List[tuple]) -> None:
        """Route a sharded conn's decoded messages through the same
        handlers the in-process loop uses, resolved by the proxy's
        registered identity (per-conn order is the shard_fwd list order —
        the invariant tests/test_io_shard.py pins)."""
        if proxy.kind == "daemon":
            nid = self._conn_to_daemon.get(proxy)
            if nid is None:
                return
            for m in msgs:
                self._handle_daemon_msg(nid, m)
        elif proxy.kind == "driver":
            did = self._conn_to_driver.get(proxy)
            if did is None:
                return
            for m in msgs:
                try:
                    self._handle_msg(did, m)
                except Exception:
                    import traceback

                    traceback.print_exc()
        else:  # "ready" — a worker conn
            wid = self._conn_to_worker.get(proxy)
            if wid is None:
                return
            self._handle_msgs(wid, msgs)

    def _sharded_conn_eof(self, proxy) -> None:
        """A shard reported a handed-off conn's EOF: the SOCKET died, so
        run the same death path the in-process loop runs."""
        proxy._closed = True
        if proxy.kind == "daemon":
            nid = self._conn_to_daemon.get(proxy)
            if nid is not None:
                self._daemon_conn_eof(proxy, nid)
        elif proxy.kind == "driver":
            did = self._conn_to_driver.get(proxy)
            if did is not None:
                self._driver_conn_eof(proxy, did)
        else:
            wid = self._conn_to_worker.get(proxy)
            if wid is not None:
                self._worker_conn_eof(proxy, wid)

    def _sharded_conn_orphaned(self, proxy) -> None:
        """A SHARD died, not the peer: every owned conn's fd closed at
        once while the peers live on.  Unlike a per-conn EOF this is a
        transient reset — the same class drivers already get a grace for
        — so give each peer its reconnect window instead of declaring a
        crash and churning every actor the dead shard happened to carry.
        Peers reconnect within seconds and re-handshake onto live shards;
        one that never comes back falls to the usual detectors (deferred
        crash below, daemon heartbeat timeout)."""
        from ray_tpu._private import config as _config

        proxy._closed = True
        window = _config.get("reconnect_window_s")
        if proxy.kind == "daemon":
            nid = self._conn_to_daemon.get(proxy)
            if nid is None:
                return
            hb_timeout = _config.get("health_check_timeout_ms")
            if window > 0 and hb_timeout > 0:
                # Drop the conn binding only: the daemon's re-hello
                # rebinds it (the node record survives); the heartbeat
                # timeout catches a daemon that never returns.
                with self.lock:
                    self._conn_to_daemon.pop(proxy, None)
                    self._conns_version += 1
            else:
                self._daemon_conn_eof(proxy, nid)
        elif proxy.kind == "driver":
            did = self._conn_to_driver.get(proxy)
            if did is not None:
                self._driver_conn_eof(proxy, did)  # has its own grace
        else:
            wid = self._conn_to_worker.get(proxy)
            if wid is None:
                return
            if window <= 0:
                self._worker_conn_eof(proxy, wid)  # classic mode: EOF = death
                return
            with self.lock:
                self._conn_to_worker.pop(proxy, None)
                self._conns_version += 1
                h = self.workers.get(wid)
                if h is not None and h.conn is proxy:
                    # Back to the pre-ready buffering state: sends queue
                    # in pending_sends and drain at the re-handshake.
                    h.conn = None
                # Crash only if the reconnect never lands (the handshake
                # clears this on arrival).
                self._deferred_crashes[wid] = time.monotonic() + min(
                    window, 8.0
                )

    def _handle_shard_msg(self, idx: int, msg: tuple) -> None:
        sh = self._io_shards.get(idx)
        if sh is None or not (isinstance(msg, tuple) and msg):
            return
        if msg[0] == "shard_fwd":
            proxy = sh.conns.get(msg[1])
            if proxy is not None:
                # Bodies arrive raw (native untouched, pickled ones
                # shard-validated + re-encoded): decode here — the ONLY
                # decode native bodies ever get.  wire.recv faults fired
                # on the shard; firing again here would double-drop.
                msgs = []
                for body in msg[2]:
                    try:
                        msgs.append(_wire.decode_body(body))
                    except Exception:
                        import traceback

                        traceback.print_exc()
                if msgs:
                    self._dispatch_sharded_msgs(proxy, msgs)
        elif msg[0] == "shard_eof":
            proxy = sh.conns.pop(msg[1], None)
            if proxy is not None:
                self._sharded_conn_eof(proxy)
        elif msg[0] == "metrics_push":
            self.telemetry.ingest(f"io_shard:{idx}", msg[1])

    def _on_io_shard_death(self, idx: int) -> None:
        """Fail over a dead shard: every conn it owned is dead (the fds
        died with the process), so run each one's EOF path — peers see
        the same socket EOF and reconnect onto live shards.  Idempotent
        (respawn_at doubles as the death-processed marker)."""
        from ray_tpu._private import config as _config

        sh = self._io_shards.get(idx)
        if sh is None:
            return
        with self.lock:
            if sh.respawn_at:
                return  # death already processed
            sh.alive = False
            sh.respawn_at = time.monotonic() + _config.get("io_shard_restart_s")
            if sh.ctl_conn is not None:
                self._conn_to_shard.pop(sh.ctl_conn, None)
                self._conns_version += 1
        for c in (sh.ctl_conn, sh.fd_conn):
            try:
                if c is not None:
                    c.close()
            except OSError:
                pass
        try:
            sh.proc.terminate()  # a hung-but-alive shard must actually die
        except OSError:
            pass
        self.telemetry.forget(f"io_shard:{idx}")
        self.events.emit(
            "WARNING", "io_shard", "io shard died; failing over its conns",
            shard=idx, conns=len(sh.conns),
        )
        for conn_id in list(sh.conns):
            proxy = sh.conns.pop(conn_id, None)
            if proxy is not None:
                self._sharded_conn_orphaned(proxy)

    def _supervise_io_shards(self, now: float) -> None:
        """io-loop tick: respawn dead shards after the backoff (their
        conns already failed over; reconnecting peers hash onto the
        refreshed live set)."""
        from ray_tpu._private import io_shard as _io_shard

        for idx, sh in list(self._io_shards.items()):
            if self._shutdown:
                return
            if sh.proc.poll() is None:
                continue  # running (or still starting pre-hello)
            if not sh.respawn_at:
                # Died without the ctl EOF landing yet (spawn failure,
                # pre-hello crash): process the death now.
                self._on_io_shard_death(idx)
                continue
            if now >= sh.respawn_at:
                self._io_shards[idx] = _io_shard.spawn_shard_process(
                    idx, self._shard_listener_path, self._authkey,
                    self.session_name,
                )
                self.events.emit(
                    "INFO", "io_shard", "io shard respawned", shard=idx
                )

    # ------------------------------------------------------------------
    # IO threads

    def _accept_loop(self):
        # Each connection's first-message handshake runs on its own thread:
        # a starting worker opens a kv_fetch side-channel BEFORE sending
        # "ready" on its main conn, so a serial accept loop would deadlock
        # (blocked recv'ing the main conn's handshake while the fetch conn
        # waits for service).
        from ray_tpu._private import wire
        from ray_tpu._private.netutil import set_nodelay

        while not self._shutdown:
            try:
                conn = self.listener.accept()
            except (OSError, EOFError):
                if self._shutdown:
                    return
                continue
            except Exception:
                continue  # accept-level failure; keep serving
            set_nodelay(conn)
            # The authkey challenge runs on the per-conn thread, NOT here:
            # inline challenges serialize every connect behind one thread —
            # at a 200-worker burst that was a measured ~16ms × N accept
            # queue (the head's own connect RTT to a busy fresh child).
            threading.Thread(
                target=self._auth_and_handshake, args=(conn,), daemon=True,
                name="raytpu-handshake",
            ).start()

    def _auth_and_handshake(self, rawconn) -> None:
        """Mutual HMAC challenge (what Listener(authkey=...) ran inline in
        accept), then the application handshake.  Same order as the stdlib
        server side — deliver first, answer second — so unchanged clients
        (multiprocessing.connection.Client with authkey) interoperate."""
        from multiprocessing.connection import answer_challenge, deliver_challenge

        from ray_tpu._private import wire

        try:
            deliver_challenge(rawconn, self._authkey)
            answer_challenge(rawconn, self._authkey)
        except Exception:  # stranger failed the auth challenge
            try:
                rawconn.close()
            except OSError:
                pass
            return
        self._handshake(wire.wrap(rawconn))

    def _handshake(self, conn) -> None:
        from ray_tpu._private.wire import PROTOCOL_VERSION, ProtocolError

        try:
            first = conn.recv()
        except ProtocolError as e:
            # Version/schema mismatch: tell the peer WHY before closing —
            # the clean rejection the raw-pickle plane never had
            # (ray: gRPC status + proto version negotiation).
            try:
                conn.send(("protocol_error", PROTOCOL_VERSION, str(e)))
            except OSError:
                pass
            conn.close()
            return
        except (OSError, EOFError):
            conn.close()
            return
        try:
            self._dispatch_handshake(conn, first)
        except Exception:
            import traceback

            traceback.print_exc()
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch_handshake(self, conn, first) -> None:
        from ray_tpu._private import wire

        if first[0] in ("ready", "driver", "daemon"):
            # Long-lived control conns get the coalescing sender: the
            # head's reply/pub/fence streams to this peer ride one
            # physical write per flush wave instead of one per frame.
            # Wrapped BEFORE registration so every map (conn_to_*,
            # selector) holds the same object identity.  One-shot conns
            # (kv/object fetch) and the zygote stay direct.
            conn = wire.batching(conn)
        if first[0] == "kv_fetch":
            # One-shot fetch channel: a STARTING worker materializes its
            # runtime-env packages before its main conn says "ready"
            # (the main conn can't serve requests yet — replies park
            # behind the ready handshake).
            try:
                conn.send(self.state.kv_get(first[1]))
            except OSError:
                pass
            conn.close()
            return
        if first[0] == "object_fetch":
            # One-shot transfer conn: a remote node pulls an object from
            # the HEAD store (this listener doubles as the head's object
            # server — no extra port).  Same streaming body as the daemon
            # ObjectServer, same admission bound, served on this
            # handshake thread.  Relay-capable peers (3rd field) may be
            # served out of an in-flight pull's transfer board.
            from ray_tpu._private import object_plane

            relay_ok = len(first) > 2 and bool(first[2])
            with self._transfer_sem:
                object_plane.stream_object(
                    conn, self.store.get_raw_packed, first[1],
                    self.store.read_board if relay_ok else None,
                )
            return
        if first[0] == "driver":
            # Attached driver client (head-split mode): ("driver", did,
            # pid[, t_sent]).  Reply with session metadata, then a second
            # message declares whether the driver co-locates with the head
            # store (zero-copy reads) or stays remote (ray://-style: conn
            # + transfer plane).
            did, _pid = first[1], first[2]
            if len(first) > 3 and isinstance(first[3], float):
                self.clock_offsets[did] = time.time() - first[3]
            try:
                from ray_tpu._private import config as _config

                conn.send(
                    (
                        "driver_ack",
                        {
                            "session": self.session_name,
                            "namespace": self.namespace,
                            "store_dir": self.store.shm.dir,
                            # Clients adopt the HEAD's reconnect window (the
                            # env knob lives in the head process, not in
                            # every attaching driver).
                            "reconnect_window_s": _config.get("reconnect_window_s"),
                        },
                    )
                )
                second = conn.recv()
            except (OSError, EOFError):
                conn.close()
                return
            shared = bool(second[2]) if second[0] == "driver_store" else False
            # Shard the conn AFTER the two-way hello exchange above: the
            # proxy enters the maps, the real socket ships to its shard.
            reg, sh = self._shard_route(conn, "driver", did)
            with self.lock:
                old = self.drivers.get(did)
                if old is not None and old is not reg:
                    # Reconnect over a LIVE head (transient TCP reset): the
                    # old conn's pending EOF must clean only itself — not
                    # declare the reconnected driver dead (the EOF handler
                    # checks drivers[did] identity) — and the borrow counts
                    # this driver still holds must survive.
                    self._conn_to_driver.pop(old, None)
                    try:
                        old.close()
                    except OSError:
                        pass
                self.drivers[did] = reg
                self._driver_death_grace.pop(did, None)  # reconnect won
                self.driver_nodes[did] = (
                    self.head_node_id if shared else f"drvnode-{did}"
                )
                self.driver_refs.setdefault(did, {})
                self._conn_to_driver[reg] = did
                self._conns_version += 1
                # Attached drivers are this build's jobs (ray:
                # gcs_job_manager): the journaled transition lets a
                # restarted head know which owners were already live.
                self.state.set_job_state(did, "RUNNING", pid=_pid)
            if sh is not None:
                self._complete_handoff(sh, reg, conn)
            return
        if first[0] == "daemon":
            # Node daemon registration: ("daemon", node_id, cfg, pid).
            _, node_id, cfg, _pid = first
            res = {"CPU": float(cfg.get("num_cpus", 1.0)), **(cfg.get("resources") or {})}
            if isinstance(cfg.get("clock"), float):
                self.clock_offsets[f"daemon:{node_id}"] = (
                    time.time() - cfg["clock"]
                )
            reg, sh = self._shard_route(conn, "daemon", node_id)
            with self.lock:
                if node_id not in self.state.nodes:
                    self.state.register_node(
                        NodeInfo(
                            node_id, dict(res), dict(res),
                            labels=dict(cfg.get("labels") or {}),
                        )
                    )
                ep = cfg.get("object_endpoint")
                if ep:
                    self.node_object_endpoints[node_id] = tuple(ep)
                self.node_daemons[node_id] = reg
                self._conn_to_daemon[reg] = node_id
                self.node_daemon_pids[node_id] = int(_pid)
                self._conns_version += 1
                # Lifecycle: a provider-launched node registering flips
                # REQUESTED/STARTING -> ACTIVE; a node that was DRAINING
                # when the head bounced RESUMES draining — the volatile
                # NodeInfo.draining flag is re-derived from the journaled
                # lifecycle record, so no new leases land on it and the
                # reconciler picks the drain back up.
                lc = self.node_lifecycle.get(node_id)
                if lc is not None:
                    if lc.get("state") in ("REQUESTED", "STARTING"):
                        self._set_node_lifecycle(node_id, "ACTIVE")
                    elif lc.get("state") == "DRAINING":
                        self.state.set_node_draining(node_id, True)
                self.events.emit("INFO", "node", "node registered", node_id=node_id)
                # Fresh liveness clock: a stale entry from a previous
                # incarnation of this node_id would instantly time the
                # reconnected daemon out before its first heartbeat.
                self._daemon_heartbeats[node_id] = time.monotonic()
                self._dispatch()
            if sh is not None:
                self._complete_handoff(sh, reg, conn)
            return
        if first[0] == "zygote":
            # Fork server up: route subsequent local spawns through it.
            with self.lock:
                self._zygote_conn = conn
                self._zygote_spawning = False
            threading.Thread(
                target=self._zygote_loop, args=(conn,), daemon=True,
                name="raytpu-zygote",
            ).start()
            return
        if first[0] == "env_failed":
            # The worker's runtime-env setup failed BEFORE it could serve:
            # deterministic (a retry reinstalls the same broken env), so
            # its leased task fails with RuntimeEnvSetupError, not a
            # retriable crash (ray: RuntimeEnvSetupError semantics).
            with self.lock:
                h = self.workers.get(first[1])
                if h is not None and h.state != "dead":
                    # (storing for an already-classified worker would leak)
                    self._env_failures[first[1]] = str(first[2])
                    self._deferred_crashes.pop(first[1], None)
                    self._on_worker_crash(first[1])
            conn.close()
            return
        if first[0] != "ready":
            conn.close()
            return
        wid = first[1]
        if len(first) > 6 and isinstance(first[6], float):
            # Clock-offset estimate: receive time minus the sender's send
            # stamp (includes one-way latency — ms on loopback, fine for
            # ordering spans across processes in the merged timeline).
            self.clock_offsets[wid] = time.time() - first[6]
        # Shard routing decided up front: the conn maps register the proxy
        # (reg) while handshake-time direct traffic keeps using the real
        # conn; the fd ships only after registration completes.
        reg, sh = self._shard_route(conn, "ready", wid)
        adopted = False
        with self.lock:
            if len(first) > 4 and first[4]:
                self.worker_peer_endpoints[wid] = tuple(first[4])
            h = self.workers.get(wid)
            if h is None:
                h = self._adopt_worker(reg, first)
                if h is None:
                    conn.close()
                    return
                adopted = True
            else:
                h.pid = first[2]
        if adopted:
            if sh is not None:
                self._complete_handoff(sh, reg, conn)
            return
        # Flush messages queued while the worker was starting OFF the
        # runtime lock (pipe I/O under the global lock stalls the whole
        # control plane if the pipe buffer is full; the concurrency lint's
        # blocking-under-lock pass flags the old shape).  Ordering holds:
        # h.conn stays None until the backlog drains, so concurrent
        # _send()s keep appending to pending_sends and every queued frame
        # precedes the first direct send; no other thread sees this conn
        # before the publication block below registers it.
        while True:
            with self.lock:
                pending = h.pending_sends
                if not pending:
                    h.conn = reg
                    # The reconnect landed: cancel any pending EOF-grace
                    # crash (set when this worker's shard died, or by the
                    # daemon-report defer) — firing it now would kill the
                    # healed worker.
                    self._deferred_crashes.pop(wid, None)
                    if h.state == "starting":
                        h.state = "idle"
                        h.idle_since = time.monotonic()
                        sp = self.starting_pool.get((h.node_id, h.env_key))
                        if sp and wid in sp:
                            sp.remove(wid)
                        self.idle_pool.setdefault(
                            (h.node_id, h.env_key), []
                        ).append(wid)
                    self._conn_to_worker[reg] = wid
                    self._conns_version += 1
                    self._grant_parked_leases(wid)
                    break
                h.pending_sends = []
                self._pending_send_flushes = (
                    getattr(self, "_pending_send_flushes", 0) + len(pending)
                )
                # Task frames queued while the worker booted go out NOW:
                # stamp their "pushed" stage (still under the lock — the
                # record may be concurrently finished by another conn).
                push_t = time.time()
                for msg in pending:
                    if msg[0] in ("task", "create_actor"):
                        prec = self.tasks.get(msg[1].task_id)
                        if prec is not None:
                            prec.stages.setdefault("pushed", push_t)
            for msg in pending:
                try:
                    conn.send(msg)
                except OSError:
                    pass
        announced = (
            first[7] if len(first) > 7 and isinstance(first[7], list) else None
        )
        if announced is not None:
            # Reconnect hello with an executor announcement: re-drive the
            # relayed work the dead conn lost (see _redrive_worker_relays).
            with self.lock:
                self._redrive_worker_relays(h, wid, set(announced))
        if sh is not None:
            # Publication done: post-handoff sends route through the
            # proxy (the shard buffers them until the fd lands below).
            self._complete_handoff(sh, reg, conn)
        with self.lock:
            self._dispatch()

    @_locked
    def _adopt_worker(self, conn, first) -> Optional[WorkerHandle]:
        """Caller holds self.lock.  A worker this head never spawned says
        "ready": after a head restart, surviving workers reconnect within
        the window and are adopted — a restored actor bound to the worker
        resumes ALIVE with its memory state intact (ray: workers
        re-registering with a restarted GCS via raylet resubscription).
        Note: adopted actors occupy node resources the fresh scheduler has
        not reserved; transient overcommit until they exit is accepted."""
        from ray_tpu._private import config as _config

        if _config.get("reconnect_window_s") <= 0:
            return None  # classic mode: unknown workers are rejected
        wid, pid = first[1], first[2]
        node_id = first[3] if len(first) > 3 else None
        announce = first[5] if len(first) > 5 else None
        nid = node_id or self.head_node_id
        if nid in self.node_daemons:
            proc: Any = _RemoteProcHandle(self, nid, wid)
        else:
            proc = _AdoptedHandle(self, wid)
        h = WorkerHandle(wid, nid, None, None, proc)
        h.conn = conn
        h.pid = pid
        self.workers[wid] = h
        self._conn_to_worker[conn] = wid
        self._conns_version += 1
        bound = None
        for aid, ar in self.actors.items():
            if ar.info.worker_id == wid and ar.info.state == RESTARTING:
                bound = aid
                break
        if bound is None and announce is not None:
            # Reconciliation: the worker re-announced the live actor it
            # hosts.  Normally the journal already restored the record
            # (the loop above missed only because worker_id drifted); with
            # the journal lost or disabled, the announcement itself
            # carries the creation spec and re-registers the actor — the
            # third leg of snapshot + journal + re-announcement.
            bound = self._reconcile_announced_actor(wid, nid, announce)
        if bound is not None:
            ar = self.actors[bound]
            ar.worker_id = wid
            h.state = "actor"
            h.actor_id = bound
            self._restored_actors.discard(bound)
            self.state.set_actor_state(bound, ALIVE, worker_id=wid, node_id=nid)
            self._on_actor_alive(bound)
        else:
            h.state = "idle"
            # Stamp idleness NOW: the constructor default of 0.0 reads as
            # idle-since-boot and the reaper would kill the adoptee on its
            # next tick — destroying what adoption exists to preserve.
            h.idle_since = time.monotonic()
            self.idle_pool.setdefault((nid, None), []).append(wid)
        self._dispatch()
        return h

    @_locked
    def _reconcile_announced_actor(self, wid: str, nid: str, announce) -> Optional[str]:
        """Caller holds self.lock.  A reconnecting worker announced the
        actor it hosts: bind it to the restored record, or — when NO
        record survived (journal disabled/lost) — re-register the actor
        from the announced creation spec (ray: workers re-registering
        their actors with a restarted GCS).  Returns the actor_id to bind
        or None (worker is adopted as a plain idle worker)."""
        try:
            aid = announce.get("actor_id")
            spec = announce.get("creation_spec")
        except AttributeError:
            return None
        if not aid:
            return None
        ar = self.actors.get(aid)
        info = self.state.get_actor(aid)
        if ar is not None and info is not None:
            if info.state not in (RESTARTING, PENDING_CREATION) or ar.worker_id:
                return None  # DEAD, or another instance already bound
            creation = info.creation_spec
            rec = self.tasks.get(creation.task_id) if creation is not None else None
            if rec is not None:
                if rec.state not in ("PENDING", "READY") or rec.cancelled:
                    return None  # a respawn already started: it wins
                # A queued-but-undispatched respawn loses to the LIVE
                # instance (memory state preserved beats state reset).
                rec.cancelled = True
                self.tasks.pop(creation.task_id, None)
            return aid
        if spec is None:
            return None
        info = ActorInfo(
            actor_id=aid,
            name=getattr(spec, "actor_name", None),
            namespace=getattr(spec, "actor_namespace", None) or self.namespace,
            max_restarts=getattr(spec, "max_restarts", 0),
            creation_spec=spec,
            detached=getattr(spec, "lifetime", None) == "detached",
            state=RESTARTING,
            worker_id=wid,
            node_id=nid,
        )
        try:
            self.state.register_actor(info)  # journals the rebuilt record
        except ValueError:
            return None  # name re-taken while the record was lost
        self.actors[aid] = ActorRuntime(info)
        self.events.emit(
            "WARNING", "actor",
            "actor record rebuilt from worker re-announcement",
            actor_id=aid, worker_id=wid,
        )
        return aid

    @_locked
    def _redrive_worker_relays(self, h, wid: str, announced: set) -> None:
        """Caller holds self.lock.  A reconnecting worker announced the
        relayed tasks it still holds (queued or executing).  In-flight
        work the head attributes to this worker that the worker does NOT
        hold was lost with the dead conn — a task push that never
        arrived, or a done/result frame that died in the socket (an
        io-shard death loses both shapes while the worker lives on).

        Plain tasks are provably not running anywhere (the worker doesn't
        have them), so they retry on their budget or fail loudly — never
        wedge a get().  Lost actor calls carry the at-most-once
        uncertainty (the call may have EXECUTED with only its done lost):
        budgeted ones (max_task_retries) re-push to the live instance —
        the contract that allows re-execution — and unbudgeted ones fail
        with the same uncertainty error a worker crash yields."""
        if h.actor_id is not None:
            ar = self.actors.get(h.actor_id)
            if ar is None:
                return
            lost = [t for t in ar.in_flight if t not in announced]
            for tid in lost:
                rec = self.tasks.get(tid)
                ar.in_flight.pop(tid, None)
                if rec is None:
                    continue
                if rec.spec.attempt < rec.spec.max_retries:
                    rec.spec.attempt += 1
                    self.metrics["tasks_retried"] += 1
                    self._push_actor_task(ar, rec)
                    continue
                err = WorkerCrashedError(
                    f"relayed actor call {rec.spec.name} was lost with its "
                    "connection (io fabric reset); the call may or may not "
                    "have executed — set max_task_retries to allow re-drive"
                )
                self.tasks.pop(tid, None)
                for oid in rec.spec.return_ids():
                    self.store.put_error(oid, err)
                    self._object_ready(oid)
                for c in rec.spec.contained_refs:
                    self._decref_local(c)
            if lost:
                self.events.emit(
                    "WARNING", "worker",
                    "re-drove relayed actor calls lost with conn",
                    worker_id=wid, actor_id=h.actor_id, lost=len(lost),
                )
            return
        tid = h.current_task
        if tid is None or tid in announced:
            return
        rec = self.tasks.get(tid)
        h.current_task = None
        if h.state == "busy":
            self._return_worker(h)
        if rec is None or rec.cancelled:
            return
        self.events.emit(
            "WARNING", "worker", "re-driving relayed task lost with conn",
            worker_id=wid, task=rec.spec.name,
        )
        if rec.spec.attempt < rec.spec.max_retries:
            rec.spec.attempt += 1
            self._retry_task_record(rec)
        else:
            self._fail_task_record(rec, wid, WorkerCrashedError(
                f"task {rec.spec.name}'s result was lost with its "
                "connection (io fabric reset) after its retry budget"
            ))

    def _io_loop(self):
        import selectors

        from ray_tpu._private import config as _cfg
        from ray_tpu._private.io_shard import ShardConnProxy as _ShardConnProxy

        sel = selectors.DefaultSelector()
        registered: set = set()
        registered_version = -1
        last_reap = 0.0
        last_topup = 0.0
        while not self._shutdown:
            # Reap workers that died before ever connecting (spawn failure,
            # import crash): conn-EOF detection can't see them.
            now = time.monotonic()
            if now - last_reap > 0.5:
                last_reap = now
                with self.lock:
                    for wid, h in list(self.workers.items()):
                        if (
                            h.conn is None
                            and h.state not in ("dead",)
                            and h.proc is not None
                            and not h.proc.is_alive()
                        ):
                            if (
                                h.state == "starting"
                                and wid not in self._env_failures
                                and wid not in self._deferred_crashes
                            ):
                                # Give a possible env_failed hello (separate
                                # conn) a beat to land before classifying.
                                self._deferred_crashes[wid] = now + 2.0
                            elif wid not in self._deferred_crashes:
                                self._on_worker_crash(wid)
                    # Deferred daemon-worker EOFs whose daemon never
                    # reported (hung daemon / lost message): classify now.
                    for wid, deadline in list(self._deferred_crashes.items()):
                        if now >= deadline:
                            self._deferred_crashes.pop(wid, None)
                            h = self.workers.get(wid)
                            if h is not None and h.state != "dead":
                                self._on_worker_crash(wid)
                    # Drivers whose conn reset on a live head and never
                    # re-handshook within the grace: now they're dead.
                    for did, deadline in list(self._driver_death_grace.items()):
                        if now >= deadline:
                            self._driver_death_grace.pop(did, None)
                            if did in self.drivers and self.drivers[
                                did
                            ] not in self._conn_to_driver:
                                self._on_driver_death(did)
                    # Task leases idle past RAY_TPU_LEASE_IDLE_S return
                    # their worker + resources to the shared pool.
                    if self.task_leases:
                        self._revoke_idle_leases(now)
                    # Function-export fences that timed out fail loudly.
                    if self._fn_fences:
                        self._sweep_fn_fences(now)
                    # Idle-worker reaping (ray: worker_pool idle killing):
                    # default-env head workers beyond the prestart floor
                    # that sat idle >60s exit, so a burst's pool shrinks
                    # back instead of holding memory forever.
                    floor = max(
                        _cfg.get("worker_prestart_count"), self._prestart_target
                    )
                    pool = self.idle_pool.get((self.head_node_id, None))
                    if pool and len(pool) > floor:
                        killed = 0
                        for wid in list(pool):
                            if len(pool) <= floor or killed >= 8:
                                break
                            h = self.workers.get(wid)
                            if h is None:
                                pool.remove(wid)
                                continue
                            if h.state == "idle" and now - h.idle_since > 60.0:
                                pool.remove(wid)
                                killed += 1
                                self._expected_worker_stops.add(wid)
                                self._send(h, ("kill",))
                    # Heartbeat timeouts: a hung (not dead) daemon or a
                    # half-open conn keeps the socket alive but stops
                    # heartbeating — declare the node dead so its leased
                    # tasks retry elsewhere instead of wedging.
                    hb_timeout = _cfg.get("health_check_timeout_ms") / 1000.0
                    if hb_timeout > 0:
                        for dconn, nid in list(self._conn_to_daemon.items()):
                            last = self._daemon_heartbeats.get(nid)
                            if last is None:
                                # Pre-heartbeat daemons (or ones from an
                                # older protocol) start their clock at
                                # first sight, not at epoch.
                                self._daemon_heartbeats[nid] = now
                            elif now - last > hb_timeout:
                                self.events.emit(
                                    "WARNING", "node",
                                    "heartbeat timeout: declaring node dead",
                                    node_id=nid, silent_s=round(now - last, 1),
                                )
                                self._conn_to_daemon.pop(dconn, None)
                                self._conns_version += 1
                                self._daemon_heartbeats.pop(nid, None)
                                try:
                                    dconn.close()
                                except OSError:
                                    pass
                                self._on_daemon_death(nid)
                # Off the runtime lock: a respawn is a subprocess spawn.
                if self._io_shards:
                    self._supervise_io_shards(now)
                # Dead-holder ref reclaim rides the same tick (its own
                # lock dance inside; decrefs may fan daemon deletes).
                if self._dead_refs:
                    self.reclaim_dead_refs()
                # Elastic MESH gangs: advance RESHAPING episodes.  Off the
                # runtime lock — the reshape fault points can delay/crash;
                # each mutation step re-takes the lock and re-checks.
                self._sweep_reshaping_pgs(now)
            if self._prestart_target > 0 and now - last_topup > 0.05:
                # Throttled: an every-iteration lock acquire here convoys
                # with the hot message path during drains.
                last_topup = now
                with self.lock:
                    t = self._prestart_target
                    if now - self._prestart_miss_t > 5.0:
                        if now - self._prestart_decay_t > 5.0:
                            self._prestart_target = t // 2
                            self._prestart_decay_t = now
                    else:
                        key = (self.head_node_id, None)
                        have = len(self.idle_pool.get(key) or ()) + len(
                            self.starting_pool.get(key) or ()
                        )
                        # ≤8 spawns per tick bounds the lock hold; the loop
                        # runs ≥20Hz so a 50-wide burst refills within a
                        # wave's barrier.
                        for _ in range(min(t - have, 8)):
                            self._spawn_worker(
                                self.head_node_id, None, None, prestart=True
                            )
            # Persistent epoll registration (diffed, not rebuilt): the old
            # per-iteration `multiprocessing.connection.wait` constructed a
            # poll set of ALL conns on EVERY wakeup — O(live workers) per
            # message, the measured collapse at 800+ live actors (ray:
            # asio's reactor keeps persistent registrations the same way).
            if self._conns_version != registered_version:
                with self.lock:
                    registered_version = self._conns_version
                    # Sharded conns are ShardConnProxy stand-ins: the
                    # owning shard epolls the real socket; here we epoll
                    # only direct conns plus each shard's ctl channel.
                    current = {
                        c
                        for c in (
                            set(self._conn_to_worker)
                            | set(self._conn_to_daemon)
                            | set(self._conn_to_driver)
                        )
                        if not isinstance(c, _ShardConnProxy)
                    } | set(self._conn_to_shard)
                for conn in registered - current:  # removals FIRST (fd reuse)
                    try:
                        sel.unregister(conn)
                    except (KeyError, ValueError, OSError):
                        pass
                for conn in current - registered:
                    try:
                        sel.register(conn, selectors.EVENT_READ)
                    except (KeyError, ValueError, OSError):
                        current.discard(conn)
                registered = current
            if not registered:
                time.sleep(0.02)
                continue
            try:
                readable = [key.fileobj for key, _ in sel.select(timeout=0.05)]
            except OSError:
                continue
            # Shard ctl channels first (they multiplex daemon traffic
            # too), then daemon conns: an OOM-kill report must be applied
            # before the victim worker's own conn EOF (same select round)
            # so the crash classifies as OOM, not a generic worker death.
            readable.sort(
                key=lambda c: (
                    c not in self._conn_to_shard,
                    c not in self._conn_to_daemon,
                )
            )
            for conn in readable:
                sidx = self._conn_to_shard.get(conn)
                if sidx is not None:
                    # One recv here drains a whole shard_fwd batch — many
                    # conns' decoded traffic per physical read; the
                    # per-conn syscall fan-in lives in the shard process.
                    smsgs = []
                    seof = False
                    try:
                        smsgs.append(conn.recv())
                        while len(smsgs) < 256 and conn.poll(0):
                            smsgs.append(conn.recv())
                        while conn.pending_frames():
                            smsgs.append(conn.recv())
                    except (EOFError, OSError):
                        seof = True
                    for sm in smsgs:
                        try:
                            self._handle_shard_msg(sidx, sm)
                        except Exception:
                            import traceback

                            traceback.print_exc()
                    if seof:
                        self._on_io_shard_death(sidx)
                    continue
                nid = self._conn_to_daemon.get(conn)
                if nid is not None:
                    # Drain the whole readable run INCLUDING decoded batch
                    # sub-frames: a daemon's heartbeat piggybacks on its
                    # log_lines/worker_exited batch, and a buffered tail
                    # would otherwise strand until the next physical frame.
                    dmsgs = []
                    try:
                        dmsgs.append(conn.recv())
                        while len(dmsgs) < 256 and conn.poll(0):
                            dmsgs.append(conn.recv())
                        while conn.pending_frames():
                            dmsgs.append(conn.recv())
                    except (EOFError, OSError):
                        for dmsg in dmsgs:
                            self._handle_daemon_msg(nid, dmsg)
                        self._daemon_conn_eof(conn, nid)
                        continue
                    for dmsg in dmsgs:
                        self._handle_daemon_msg(nid, dmsg)
                    continue
                did = self._conn_to_driver.get(conn)
                if did is not None:
                    # Drain like a worker conn (attached drivers batch
                    # their oneway/req streams too), including any decoded
                    # sub-frames left past the cap.
                    eof = False
                    msgs = []
                    try:
                        msgs.append(conn.recv())
                        while len(msgs) < 256 and conn.poll(0):
                            msgs.append(conn.recv())
                        while conn.pending_frames():
                            msgs.append(conn.recv())
                    except (EOFError, OSError):
                        eof = True
                    for msg in msgs:
                        try:
                            self._handle_msg(did, msg)
                        except Exception:
                            import traceback

                            traceback.print_exc()
                    if not eof:
                        continue
                    self._driver_conn_eof(conn, did)
                    continue
                wid = self._conn_to_worker.get(conn)
                if wid is None:
                    continue
                # Drain the conn: receive every queued message, THEN handle
                # the run in batches under one lock acquisition.  Per-message
                # lock round-trips convoy against the N submitting client
                # threads (measured: 4-client task throughput collapsed 4x
                # with per-message locking; the reference batches the same
                # way in its io-service event handlers).  The cap bounds
                # PHYSICAL reads; decoded batch sub-frames past it are
                # drained too — the socket shows no data for them, so the
                # selector would never wake for a buffered tail.
                eof = False
                msgs = []
                try:
                    msgs.append(conn.recv())
                    while len(msgs) < 256 and conn.poll(0):
                        msgs.append(conn.recv())
                    while conn.pending_frames():
                        msgs.append(conn.recv())
                except (EOFError, OSError):
                    eof = True
                if msgs:
                    self._handle_msgs(wid, msgs)
                if eof:
                    self._worker_conn_eof(conn, wid)
            # End of the select round: every reply/pub/fence queued while
            # handling this wave goes out as one physical write per conn
            # (the flush-before-blocking-wait rule — select() is this
            # thread's blocking wait).
            _wire.flush_dirty()

    # Conn-EOF paths, shared by the in-process io loop and the shard
    # fabric (a shard_eof report — or a shard death, which closes every
    # owned fd — must land on exactly the same death handling).

    def _daemon_conn_eof(self, conn, nid: str) -> None:
        with self.lock:
            self._conn_to_daemon.pop(conn, None)
            self._conns_version += 1
            self._on_daemon_death(nid)

    def _driver_conn_eof(self, conn, did: str) -> None:
        from ray_tpu._private import config as _config

        with self.lock:
            self._conn_to_driver.pop(conn, None)
            self._conns_version += 1
            superseded = self.drivers.get(did) is not conn
        if not superseded:
            window = _config.get("reconnect_window_s")
            if window > 0:
                # Transient reset on a LIVE head: give the driver's
                # reconnect loop a beat before freeing its refs and
                # killing its actors (a same-millisecond EOF would
                # otherwise always beat the re-handshake).
                with self.lock:
                    self._driver_death_grace[did] = (
                        time.monotonic() + min(window, 5.0)
                    )
            else:
                self._on_driver_death(did)

    def _worker_conn_eof(self, conn, wid: str) -> None:
        with self.lock:
            self._conn_to_worker.pop(conn, None)
            self._conns_version += 1
            h = self.workers.get(wid)
            if (
                h is not None
                and isinstance(h.proc, _RemoteProcHandle)
                and h.node_id in self.node_daemons
                and wid not in self._oom_kills
            ):
                # Daemon-owned worker: wait briefly for the daemon's
                # worker_exited (carries the OOM rider) before
                # classifying the crash.
                self._deferred_crashes[wid] = time.monotonic() + 2.0
            else:
                self._on_worker_crash(wid)

    def _handle_daemon_msg(self, nid: str, dmsg) -> None:
        if not (isinstance(dmsg, tuple) and dmsg):
            return
        if dmsg[0] == "log_lines":
            # A remote node's monitor forwarded fresh worker output: same
            # sink as head-local files.
            self._on_log_lines(dmsg[1], dmsg[2], dmsg[3])
        elif dmsg[0] == "heartbeat":
            self._daemon_heartbeats[nid] = time.monotonic()
        elif dmsg[0] == "metrics_push":
            self.telemetry.ingest(f"daemon:{nid}", dmsg[1])
        elif dmsg[0] == "worker_oom_killed":
            with self.lock:
                self._oom_kills[dmsg[1]] = dmsg[2:]
        elif dmsg[0] == "worker_exited":
            # A remote child died (possibly before connecting): the
            # driver-side reaper can't see it, the daemon can.
            with self.lock:
                h = self.workers.get(dmsg[1])
                if h is not None and isinstance(h.proc, _RemoteProcHandle):
                    h.proc.dead = True
                self._deferred_crashes.pop(dmsg[1], None)
                if h is not None and h.state != "dead":
                    # The daemon's report is authoritative on WHY: its OOM
                    # rider survives even when the victim's own conn EOF
                    # won the message race.
                    if len(dmsg) > 3 and dmsg[3] is not None:
                        self._oom_kills.setdefault(dmsg[1], tuple(dmsg[3]))
                    if (
                        h.conn is None
                        and h.state == "starting"
                        and dmsg[1] not in self._oom_kills
                        and dmsg[1] not in self._env_failures
                    ):
                        # A starting worker that died without connecting
                        # usually failed env setup; its env_failed hello
                        # rides a separate conn — wait briefly so the
                        # crash classifies as RuntimeEnvSetupError, not a
                        # retriable generic death.
                        self._deferred_crashes[dmsg[1]] = (
                            time.monotonic() + 2.0
                        )
                    else:
                        self._on_worker_crash(dmsg[1])
                else:
                    # Crash already classified (EOF saw the earlier
                    # worker_oom_killed): drop any re-inserted rider or it
                    # leaks forever.
                    self._oom_kills.pop(dmsg[1], None)

    # ------------------------------------------------------------------
    # message handling

    def _handle_msgs(self, wid: str, msgs: List[tuple]) -> None:
        """Handle a drained run of messages, folding consecutive hot-path
        kinds (done/refop) into ONE lock acquisition.  Failures are
        per-message: one bad handler must not drop the already-drained
        messages behind it (a swallowed 'done' wedges its task forever)."""
        import traceback

        i, n = 0, len(msgs)
        while i < n:
            if msgs[i][0] in ("done", "refop"):
                with self.lock:
                    while i < n and msgs[i][0] in ("done", "refop"):
                        try:
                            self._handle_hot_locked(wid, msgs[i])
                        except Exception:
                            traceback.print_exc()
                        i += 1
            else:
                try:
                    self._handle_msg(wid, msgs[i])
                except Exception:
                    traceback.print_exc()
                i += 1

    @_locked
    def _handle_hot_locked(self, wid: str, msg: tuple) -> None:
        # caller holds self.lock
        if msg[0] == "done":
            self._on_task_done(
                wid, msg[1], msg[2], msg[3],
                timing=msg[4] if len(msg) > 4 else None,
            )
            return
        # Every sender's outstanding borrows are conn-tracked (drivers in
        # driver_refs, workers in worker_refs): a holder dying mid-hold
        # leaves exactly the refs its lost dels would have released — the
        # ledger flags them as dead-holder leak suspects and
        # reclaim_dead_refs drops them after the grace.
        tracked = self.driver_refs.get(wid)
        if tracked is None:
            tracked = self.worker_refs.get(wid)
            if tracked is None:
                tracked = self.worker_refs.setdefault(wid, {})
        if msg[1] == "add":
            self.store.add_ref(msg[2])
            tracked[msg[2]] = tracked.get(msg[2], 0) + 1
        else:
            self._decref_local(msg[2])
            c = tracked.get(msg[2], 0) - 1
            if c > 0:
                tracked[msg[2]] = c
            else:
                tracked.pop(msg[2], None)

    def _handle_msg(self, wid: str, msg: tuple) -> None:
        kind = msg[0]
        if kind in ("done", "refop"):
            with self.lock:
                self._handle_hot_locked(wid, msg)
        elif kind == "object_copied":
            # A worker pulled a copy into its node's store: record it so
            # siblings on that node read locally — unless the object was
            # freed while the pull was in flight (then reap the orphan).
            # The optional 4th field is the transfer path ("pull"/"relay")
            # the puller used — released slot + ledger label.
            oid, size = msg[1], msg[2]
            via = msg[3] if len(msg) > 3 else "pull"
            with self.lock:
                node = self._worker_node(wid)
                grants = self._pull_grants.get(oid)
                if grants:
                    grants.pop()  # this puller's grant: capacity freed
                    if not grants:
                        self._pull_grants.pop(oid, None)
                self._release_pull_slot_locked(oid, node)
                if wid in self.drivers and node != self.head_node_id:
                    return  # remote driver's private store: nobody else reads it
                if node == self.head_node_id:
                    # The worker wrote straight into the HEAD store's shm:
                    # without accounting, _free would never delete the
                    # segment and capacity tracking would undercount.
                    if self.store.is_ready(oid):
                        self.store.mark_shm_sealed(oid, size)
                    else:
                        self.store.shm.delete(oid)
                elif self.store.is_ready(oid):
                    self.object_locations.setdefault(oid, set()).add(node)
                    self.object_sizes.setdefault(oid, size)
                else:
                    self._daemon_send(node, ("delete_object", oid))
                    return
                # Ledger/timeline label carries the transfer path: a
                # "relay" event proves the copy rode an in-flight feed.
                self._obj_event(
                    oid, "relay" if via == "relay" else "transfer", size, node
                )
                # Unpark staggered pullers: the source set just grew
                # (deferred callbacks run after the lock drops).
                deferred = self.pubsub.publish("object_copied", oid, oid)
            for cb in deferred:
                cb(oid)
        elif kind == "actor_exit":
            with self.lock:
                ar = self.actors.get(msg[1])
                if ar:
                    ar.expected_death = True
                    ar.no_restart = True
        elif kind == "actor_announce":
            # Reconciliation hints from reconnecting CALLERS: each entry
            # names a direct actor route the peer held when the old head
            # died.  The rebuilt table (snapshot + journal + hosting-worker
            # re-announcement) normally already accounts for every one; an
            # entry it can't account for is surfaced as a WARNING event so
            # a durability gap is visible instead of silent.
            with self.lock:
                for aid, ep in msg[1]:
                    if self.state.get_actor(aid) is None:
                        self.events.emit(
                            "WARNING", "actor",
                            "peer re-announced an actor with no surviving record",
                            actor_id=aid, reporter=wid,
                            endpoint=list(ep) if ep else None,
                        )
        elif kind == "task_events":
            # Batched task-state reports for peer-executed (direct) tasks:
            # restores state-API/metrics visibility without a per-task
            # head message on the latency path.  RUNNING events come from
            # the CALLER at lease dispatch; completion events come from the
            # EXECUTOR — different processes, so a completion may arrive
            # first (the recent-done set keeps such entries from sticking
            # as RUNNING forever).
            off = self.clock_offsets.get(wid, 0.0)
            with self.lock:
                for e in msg[1]:
                    if off and isinstance(e.get("end_time"), float):
                        # Land the sender's timestamps on the head clock so
                        # the merged timeline orders across processes.
                        e["end_time"] += off
                        for s, v in list((e.get("stages") or {}).items()):
                            if isinstance(v, (int, float)):
                                e["stages"][s] = v + off
                    tid = e.get("task_id")
                    if e.get("state") == "RUNNING":
                        if tid not in self._direct_done_recent:
                            # Bounded: crashes on BOTH sides of a direct
                            # call can orphan an entry (no terminal event
                            # ever arrives), so cap with FIFO eviction.
                            while len(self.direct_running) >= 4096:
                                self.direct_running.pop(
                                    next(iter(self.direct_running))
                                )
                            self.direct_running[tid] = e
                        continue
                    self.direct_running.pop(tid, None)
                    if len(self._direct_done_recent) >= 4096:
                        self._direct_done_recent.discard(
                            self._direct_done_order.popleft()
                        )
                    self._direct_done_recent.add(tid)
                    self._direct_done_order.append(tid)
                    self.metrics["tasks_submitted"] += 1
                    self.metrics[
                        "tasks_finished" if e.get("state") == "FINISHED"
                        else "tasks_failed"
                    ] += 1
                    self.task_events.append(e)
                    # Direct-task events carry executor-side stage
                    # durations (exec_queue/running): same histograms as
                    # head-dispatched tasks, so `ray_tpu tasks --summary`
                    # spans both transports.
                    self._observe_stage_durations(e.get("durations"))
        elif kind == "spans":
            # Worker-side trace spans (util/tracing.py), batched off the
            # latency path like task events.  Corrected onto the head
            # clock at ingest (handshake-estimated offset) so the merged
            # timeline is one coherent clock across processes.
            from ray_tpu.util.tracing import apply_clock_offset

            spans = apply_clock_offset(msg[1], self.clock_offsets.get(wid, 0.0))
            with self.lock:
                self.trace_spans.extend(spans)
        elif kind == "metrics_push":
            # Periodic per-process telemetry snapshot (telemetry.py):
            # latest wins per sender; the head's telemetry tick folds the
            # aggregate into the time-series rings.
            self.telemetry.ingest(wid, msg[1])
        elif kind == "refs_push":
            # Periodic per-process live-ref table (refs.py snapshot_refs):
            # the worker leg of the object ledger — droppable, latest wins
            # per sender, joined with the owner tables by memory_summary.
            self.ledger.ingest(wid, msg[1])
        elif kind == "prof_push":
            # Periodic per-process collapsed-stack table (profiler.py):
            # cumulative since start, so latest-wins ingest + a sum across
            # senders is exact even when droppable pushes are lost.
            self.profiles.ingest(wid, msg[1], node=self._worker_node(wid))
        elif kind == "wire_stats":
            # Per-process wire counters reported by workers/drivers when
            # RAY_TPU_WIRE_STATS=1 (keyed by sender; cluster_metrics sums
            # them with the head's own counters).
            with self.lock:
                self.worker_wire_stats[wid] = dict(msg[1])
        elif kind == "direct_lineage":
            # A lease-dispatched task produced shm results: remember its
            # spec so the head can re-execute the producer if the bytes are
            # later lost (ray: task_manager.h:90 keeps lineage for ALL
            # direct tasks, not just relayed ones).
            spec = msg[1]
            if spec.actor_id is None:  # actor outputs are never re-executed
                with self.lock:
                    for rid in spec.return_ids():
                        self._lineage_record(rid, spec)
        elif kind == "subscribe":
            once = bool(msg[3]) if len(msg) > 3 else False
            with self.lock:
                subs = self.remote_subs.setdefault((msg[1], msg[2]), {})
                # A persistent subscription must never be downgraded by a
                # later once-subscribe from the same process.
                subs[wid] = subs.get(wid, once) and once
        elif kind == "unsubscribe":
            with self.lock:
                subs = self.remote_subs.get((msg[1], msg[2]))
                if subs is not None:
                    subs.pop(wid, None)
                    if not subs:
                        self.remote_subs.pop((msg[1], msg[2]), None)
        elif kind == "lease_return":
            with self.lock:
                self._release_peer_lease_locked(msg[1], return_worker=True)
        elif kind == "fence_ack":
            with self.lock:
                ent = self._pending_fences.pop(msg[1], None)
            if ent is not None:
                caller, req_id, awid, ep, restartable = ent
                self._reply(caller, req_id, True, ("direct", awid, ep, restartable))
        elif kind == "direct_seal":
            # A direct call's large result, sealed in the callee's node
            # store: enter it in the directory/accounting and hold the
            # caller's reference (released by the caller's refop del).
            # The executor's serialize-time guard borrows are swapped for
            # the stored-object borrows _store_contained just took.
            oid, size, contained = msg[1], msg[2], msg[3]
            with self.lock:
                self._store_contained(oid, contained)
                for c in contained:
                    self._decref_local(c)
                self._record_sealed(wid, oid, size)
                self.store.add_ref(oid)
                self._object_ready(oid)
        elif kind == "promote":
            # A caller-owned inline result escaped its owner: register the
            # bytes here so any process can resolve the ref.  Idempotent —
            # a shm twin may already be registered via direct_seal.
            oid, packed, contained = msg[1], msg[2], msg[3]
            with self.lock:
                if not self.store.is_ready(oid):
                    self._store_contained(oid, contained)
                    self._put_packed(oid, packed)
                    self._note_object(oid, wid)
                    self._obj_event(oid, "seal", len(packed))
                    from ray_tpu._private import telemetry as _tele

                    _tele.count_copy("promote", len(packed))
                    self.store.add_ref(oid)
                    self._object_ready(oid)
        elif kind == "promote_error":
            oid = msg[1]
            with self.lock:
                if not self.store.is_ready(oid):
                    self.store.put_error(oid, cloudpickle.loads(msg[2]))
                    self.store.add_ref(oid)
                    self._object_ready(oid)
        elif kind in ("seal_ow", "put_ow"):
            # Fire-and-forget worker put (locally-minted id; for seal_ow the
            # segment is already in the worker's node store, for put_ow the
            # packed bytes ride the message).
            oid, data, contained = msg[1], msg[2], msg[3]
            with self.lock:
                self.metrics["objects_put"] += 1
                self._store_contained(oid, contained)
                if kind == "seal_ow":
                    self._record_sealed(wid, oid, data)
                else:
                    self._put_packed(oid, data)
                    self._note_object(oid, wid)
                    self._obj_event(oid, "seal", len(data))
                self._object_ready(oid)
        elif kind == "req":
            req_id, op, payload = msg[1], msg[2], msg[3]
            try:
                result = self._handle_req(wid, req_id, op, payload)
            except Exception as e:  # reply with error
                self._reply(wid, req_id, False, e)
                return
            if result is not _PARKED:
                self._reply(wid, req_id, True, result)

    @_locked
    def _drop_remote_subs(self, wid: str) -> None:
        for ck, subs in list(self.remote_subs.items()):
            subs.pop(wid, None)
            if not subs:
                self.remote_subs.pop(ck, None)

    def _remote_publish(self, channel: str, key: Any, args: tuple) -> None:
        """Publisher hook: push this publish to remote subscribers over
        their control conns (pubsub.py remote delivery).  Exact-key and
        wildcard ("*") subscriptions both fire; the frame carries the key
        so wildcard subscribers can route.

        Delivery is ASYNC via a dedicated sender thread: publishes run
        under the runtime lock, and a subscriber that stops draining its
        conn would otherwise block the send — and with it the whole
        control plane (the same reason in-process subscribers have
        deferred=True)."""
        if not self.remote_subs:
            return
        if faults.ENABLED:
            try:
                if faults.point("pubsub.publish", key=str(channel)) == "drop":
                    return  # publish lost before fan-out
            except faults.InjectedFault:
                return  # same observable outcome as drop for a publish
        with self.lock:
            entries = self.remote_subs.get((channel, key))
            wildcard = self.remote_subs.get((channel, "*"))
            targets = dict(wildcard or ())
            if entries:
                targets.update(entries)
            # once-flagged in EITHER registration (the merge above lets an
            # exact persistent sub shadow a wildcard once flag).
            once_wids = {
                wid
                for d in (entries, wildcard)
                if d
                for wid, once in d.items()
                if once
            }
        delivered = []
        for wid, _once in targets.items():
            try:
                self._pub_queue.put_nowait((wid, ("pub", channel, key, args)))
            except Exception:
                # Full: push dropped (subscriber hopelessly behind).  The
                # once-sub is NOT consumed — a one-shot event must not
                # vanish because a log flood filled the queue.
                continue
            delivered.append(wid)
        if once_wids.intersection(delivered):
            with self.lock:
                # Consume delivered once-entries from BOTH the exact-key
                # and the wildcard registration (a once+wildcard sub must
                # not fire on every later publish forever), and ONLY
                # still-once entries: a re-subscribe (or persistent
                # upgrade) that landed during the send window must
                # survive this delivery.
                for ck in ((channel, key), (channel, "*")):
                    entries = self.remote_subs.get(ck)
                    if not entries:
                        continue
                    for wid in delivered:
                        if entries.get(wid) is True:
                            entries.pop(wid, None)
                    if not entries:
                        self.remote_subs.pop(ck, None)

    def _pub_sender_loop(self) -> None:
        import queue as _queue

        while not getattr(self, "_shutdown", False):
            try:
                wid, msg = self._pub_queue.get(timeout=1.0)
            except Exception:
                continue
            # Drain the whole publish WAVE before flushing: a publish
            # fanning to N subscribers (or a burst of publishes) lands as
            # one physical write per subscriber conn, replacing the old
            # per-subscriber per-message write loop.
            while True:
                self._reply_raw(wid, msg)
                try:
                    wid, msg = self._pub_queue.get_nowait()
                except _queue.Empty:
                    break
            # This thread is about to block in get(): flush first.
            _wire.flush_dirty()

    def _reply_raw(self, wid: str, msg: tuple) -> None:
        # Resolve the conn UNDER the lock, send OUTSIDE it: a subscriber
        # that stops draining must only stall the sender thread, never
        # the control plane (TypedConn.send serializes per-conn writers).
        with self.lock:
            h = self.workers.get(wid)
            if h is not None:
                if h.conn is None:
                    h.pending_sends.append(msg)
                    return
                conn = h.conn
            else:
                conn = self.drivers.get(wid)
        if conn is not None:
            try:
                conn.send(msg)
            except OSError:
                pass

    def _reply(self, wid: str, req_id: int, ok: bool, value: Any) -> None:
        with self.lock:
            h = self.workers.get(wid)
            if h is not None:
                self._send(h, ("reply", req_id, ok, value))
                return
            conn = self.drivers.get(wid)
        if conn is not None:
            try:
                conn.send(("reply", req_id, ok, value))
            except OSError:
                pass  # driver died; its EOF cleanup is in flight

    def _handle_req(self, wid: str, req_id: int, op: str, payload: Any) -> Any:
        self.req_counts[op] += 1
        if op == "get_object":
            return self._req_get_object(wid, req_id, payload)
        if op == "sync":
            return None  # put-backpressure barrier (worker flushes oneways)
        if op == "resolve_actor":
            return self._req_resolve_actor(wid, req_id, *payload)
        if op == "lease_worker":
            return self._req_lease_worker(wid, req_id, *payload)
        if op == "get_function":
            blob = self.state.get_function(payload)
            if blob is None:
                raise KeyError(f"unknown function {payload}")
            return blob
        if op == "export_function":
            fn_id, blob = payload
            self.state.export_function(fn_id, blob)
            return None
        if op == "submit":
            return self.submit_task(payload)
        if op == "actor_call":
            return self.submit_actor_task(payload)
        if op == "create_actor":
            return self.create_actor(
                payload, owner_did=wid if wid in self.drivers else None
            )
        if op == "get_actor_named":
            name, nsp = payload
            info = self.state.get_named_actor(name, nsp or self.namespace)
            if info is None or info.state == DEAD:
                raise ValueError(f"no actor named {name!r}")
            spec = info.creation_spec
            return (
                info.actor_id,
                spec.actor_method_names or [],
                getattr(spec, "actor_max_concurrency", 1),
                getattr(spec, "actor_max_task_retries", 0),
            )
        if op == "actor_state":
            info = self.state.get_actor(payload)
            return info.state if info else None
        if op == "kill_actor":
            actor_id, no_restart = payload
            self.kill_actor(actor_id, no_restart)
            return None
        if op == "cancel":
            oid, force = payload
            self.cancel(oid, force)
            return None
        if op == "wait_objects":
            return self._req_wait_objects(wid, req_id, *payload)
        if op == "kv_put":
            self.state.kv_put(*payload)
            return None
        if op == "kv_get":
            return self.state.kv_get(*payload)
        if op == "kv_del":
            self.state.kv_del(*payload)
            return None
        if op == "kv_keys":
            return self.state.kv_keys(*payload)
        if op == "pg_create":
            bundles, strategy, name = payload[0], payload[1], payload[2]
            pg_id = payload[3] if len(payload) > 3 else None
            return self.create_placement_group(bundles, strategy, name, pg_id).pg_id
        if op == "pg_state":
            pg = self.state.placement_groups.get(payload)
            return pg.state if pg else None
        if op == "pg_remove":
            self.remove_placement_group(payload)
            return None
        if op == "pg_info":
            return self.pg_info(payload)
        if op == "pg_reshape":
            return self.pg_reshape(payload)
        if op == "cluster_resources":
            return self.cluster_resources()
        if op == "available_resources":
            return self.available_resources()
        if op == "get_logs":
            return self.get_logs(*payload)
        if op == "telemetry":
            # Attached-driver surface for `ray_tpu metrics` / `status`.
            return self.telemetry.summary()
        if op == "demand_summary":
            # Elastic-capacity demand view (`ray_tpu status` / the
            # autoscaler's attached-mode consumers).
            return self.demand_summary()
        if op == "node_lifecycle":
            # Journaled node-lifecycle records (tests/soaks verify replay
            # across head bounces through this).
            with self.lock:
                return {
                    nid: dict(rec)
                    for nid, rec in self.node_lifecycle.items()
                }
        if op == "node_drain":
            # Attached-mode drain trigger (the soak's scale-down lever;
            # ray: DrainNode RPC).  The embedded reconciler advances the
            # drain through evacuation + depart.
            return self.start_node_drain(payload)
        if op == "telemetry_series":
            return self.telemetry.series_snapshot(payload)
        if op == "memory_summary":
            # Object-ledger join for `ray_tpu memory` / /api/memory from
            # an attached client: same answer the head-local API gives.
            return self.memory_summary(**(payload or {}))
        if op == "list_object_refs":
            return self.memory_records(limit=(payload or {}).get("limit"))
        if op == "get_logs_all":
            return self.get_logs_all(payload)
        if op == "profile":
            # Cluster-wide sampling profiler (profiler.py): ("start", hz),
            # ("stop",), or ("report", {node,pid}).  start/stop broadcast
            # over pubsub to every subscribed worker; report merges the
            # pushed tables plus a fresh local snapshot.  None of these
            # block — the CLI does the sampling-window sleep client-side.
            action = payload[0]
            if action == "start":
                return self.profile_start(payload[1] if len(payload) > 1 else None)
            if action == "stop":
                return self.profile_stop()
            if action == "status":
                # Late-subscriber sync: a worker that subscribed after a
                # cluster-wide start polls this once and catches up.
                from ray_tpu._private import profiler as _profiler

                return _profiler.status()
            if action == "report":
                return self.profile_report(
                    **(payload[1] if len(payload) > 1 and payload[1] else {})
                )
            raise ValueError(f"unknown profile action {action!r}")
        if op == "state_list":
            # Attachable state API (util/state.py): --address clients and
            # the dashboard route list_* verbs here and get the head's
            # answers instead of requiring an in-process runtime.
            verb, kwargs = payload
            from ray_tpu.util import state as _state_api

            fns = {
                "tasks": _state_api.list_tasks,
                "actors": _state_api.list_actors,
                "objects": _state_api.list_objects,
                "nodes": _state_api.list_nodes,
                "workers": _state_api.list_workers,
                "placement_groups": _state_api.list_placement_groups,
                "cluster_events": _state_api.list_cluster_events,
                "summarize_tasks": _state_api.summarize_tasks,
                "cluster_metrics": _state_api.cluster_metrics,
                "spans": _state_api.list_spans,
                "task_summary": _state_api.task_summary,
            }
            fn = fns.get(verb)
            if fn is None:
                raise ValueError(f"unknown state verb {verb!r}")
            return fn(**(kwargs or {}))
        if op == "timeline":
            # Merged chrome-trace timeline (`ray_tpu timeline` from an
            # attached driver): task rows + clock-corrected spans from
            # every process of the cluster.  The optional payload is a
            # window ({"last": seconds} / {"since": epoch-seconds}) so
            # the export is bounded by the span ring, not a full dump.
            from ray_tpu.dashboard import timeline as _timeline

            window = payload if isinstance(payload, dict) else {}
            return _timeline(
                last=window.get("last"), since=window.get("since")
            )
        raise ValueError(f"unknown op {op}")

    def _req_resolve_actor(self, wid: str, req_id: int, actor_id: str,
                           need_fence: bool):
        """Directory lookup for the direct transport (peer.py).

        Replies ("direct", worker_id, endpoint, restartable).  Restartable
        actors are direct-eligible too — the caller's transport follows
        the restart FSM through "pending" replies while RESTARTING and
        re-resolves the new instance's endpoint (ray:
        direct_actor_task_submitter.h:67).  When the caller previously
        relayed calls (need_fence), the reply is parked until a marker
        flushed through the actor worker's control conn is acked: every
        relayed call is then provably in the executor queue, so the
        caller's first direct push cannot overtake one.
        """
        with self.lock:
            info = self.state.get_actor(actor_id)
            ar = self.actors.get(actor_id)
            if info is None or ar is None or info.state == DEAD:
                return ("dead", None, None, False)
            restartable = (info.max_restarts or 0) != 0
            if info.state != ALIVE or not ar.worker_id:
                return ("pending", None, None, restartable)
            ep = self.worker_peer_endpoints.get(ar.worker_id)
            h = self.workers.get(ar.worker_id)
            if ep is None or h is None or h.conn is None:
                return ("ineligible", None, None, restartable)
            if not need_fence:
                return ("direct", ar.worker_id, ep, restartable)
            self._fence_counter += 1
            fid = f"f{self._fence_counter}"
            self._pending_fences[fid] = (wid, req_id, ar.worker_id, ep, restartable)
            self._send(h, ("fence", fid))
            return _PARKED

    def _req_lease_worker(self, wid: str, req_id: int, resources: Dict[str, float]):
        """Grant a reusable worker lease for one scheduling key
        (ray: NodeManager::HandleRequestWorkerLease, node_manager.h:508 +
        the submitter-side pooling of direct_task_transport.h:75).

        The reservation goes through the same scheduler as head-dispatched
        tasks, so policy (incl. spillback to another node when one fills)
        and backpressure (("busy",) when the cluster is full → the caller
        relays through the queued head path) are inherited rather than
        reimplemented.  A grant on a still-spawning worker parks until its
        ready handshake delivers the peer endpoint."""
        probe = TaskSpec(
            task_id="lease-probe", name="lease", fn_id="", args_blob=b"",
            resources=dict(resources),
        )
        with self.lock:
            try:
                node = self.scheduler.select_node(probe)
            except ValueError:
                return ("infeasible",)
            if node is None or not self.scheduler.acquire(node, probe.resources):
                return ("busy",)
            h = self._lease_worker(node, probe)
            h.state = "peer_leased"
            self._lease_counter += 1
            lease_id = f"lease-{self._lease_counter}"
            self.peer_leases[lease_id] = (h.worker_id, node, dict(resources), wid)
            ep = self.worker_peer_endpoints.get(h.worker_id)
            if h.conn is not None and ep is not None:
                return ("ok", lease_id, h.worker_id, ep)
            if h.conn is not None and ep is None:
                # Connected worker without a peer listener (bind failed):
                # useless for direct push — undo the grant.
                self._release_peer_lease_locked(lease_id, return_worker=True)
                return ("busy",)
            self._parked_peer_leases.setdefault(h.worker_id, []).append(
                (wid, req_id, lease_id)
            )
            return _PARKED

    @_locked
    def _release_peer_lease_locked(self, lease_id: str, return_worker: bool) -> None:
        rec = self.peer_leases.pop(lease_id, None)
        if rec is None:
            return
        worker_id, node, resources, _caller = rec
        self.scheduler.release(node, resources)
        h = self.workers.get(worker_id)
        if return_worker and h is not None and h.state == "peer_leased":
            self._return_worker(h)
        self._dispatch()

    @_locked
    def _grant_parked_leases(self, wid: str) -> None:
        """Caller holds self.lock: a worker's ready handshake landed —
        complete lease grants that were waiting on its peer endpoint."""
        parked = self._parked_peer_leases.pop(wid, None)
        if not parked:
            return
        ep = self.worker_peer_endpoints.get(wid)
        for caller, req_id, lease_id in parked:
            if ep is not None and lease_id in self.peer_leases:
                self._reply(caller, req_id, True, ("ok", lease_id, wid, ep))
            else:
                # No peer endpoint (listener bind failed) or the lease was
                # already released: the worker itself is alive and
                # connected — return it to the pool or it would sit in
                # state "peer_leased" forever, invisible to the scheduler.
                self._release_peer_lease_locked(lease_id, return_worker=True)
                self._reply(caller, req_id, True, ("busy",))

    def _zygote_loop(self, conn) -> None:
        """Recv loop for the zygote's conn: pid attributions for forked
        workers and exit reports for reaped ones (boot crashes that never
        produced a worker conn to EOF)."""
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            if msg[0] == "forked":
                wid, pid = msg[1], msg[2]
                with self.lock:
                    h = self.workers.get(wid)
                    if h is not None and isinstance(h.proc, _ZygoteProcHandle):
                        h.proc.set_pid(pid)
            elif msg[0] == "worker_exited":
                wid = msg[1]
                with self.lock:
                    h = self.workers.get(wid)
                    if h is None or h.state == "dead":
                        continue
                    if (
                        h.conn is None
                        and h.state == "starting"
                        and wid not in self._env_failures
                        and wid not in self._deferred_crashes
                    ):
                        # Boot crash: give a possible env_failed hello
                        # (separate conn) a beat before classifying, like
                        # the reaper does.
                        self._deferred_crashes[wid] = time.monotonic() + 2.0
                    else:
                        self._on_worker_crash(wid)
        with self.lock:
            if self._zygote_conn is conn:
                self._zygote_conn = None
                self._zygote_spawning = False

    def _admit_pull(self, wid: str, req_id: int, oid: str, eps: list):
        """Broadcast admission.  Two regimes:

        relay_pipeline=1 (default) — PIPELINED TRANSFER PLAN: the reply's
        endpoint list is [assigned feed] + sealed-source fallbacks.  A
        feed is a sealed copy OR a node still pulling (its transfer board
        re-serves landed chunks mid-flight, object_plane._stream_relay),
        each carrying at most relay_fanout downstreams; every admitted
        puller immediately registers as a feed itself, so an N-node cold
        broadcast forms a chain/tree where all hops stream concurrently.
        A dead relay costs its downstreams one fallback hop (the sealed
        tail of their plan) or one re-ask (which re-plans); it never
        wedges the broadcast.

        relay_pipeline=0 — classic STAGGERED rounds (ray: push_manager.h
        bounds in-flight pushes; the pull twin bounds concurrent pulls
        per SOURCE COPY): grants capped at sealed copies, excess pullers
        park until object_copied grows the source set — ~log2(N)
        source-bandwidth rounds."""
        from ray_tpu._private import config as _cfg

        import time as _t

        now = _t.monotonic()
        horizon = now - _cfg.get("object_transfer_timeout_s")
        if not _cfg.get("relay_pipeline"):
            with self.lock:
                grants = [t for t in self._pull_grants.get(oid, ()) if t > horizon]
                if len(grants) >= max(len(eps), 1):
                    self._pull_grants[oid] = grants
                    self.metrics["pull_parks"] += 1
                    self._park_pull(wid, req_id, oid)
                    return _PARKED
                grants.append(now)
                self._pull_grants[oid] = grants
                self._pull_rr += 1
                k = self._pull_rr % len(eps) if eps else 0
            return ("pull", eps[k:] + eps[:k])
        fanout = max(_cfg.get("relay_fanout"), 1)
        with self.lock:
            node = self._worker_node(wid)
            st = self._xfer_plans.setdefault(oid, {"feeds": {}, "pulling": {}})
            feeds, pulling = st["feeds"], st["pulling"]
            for ep in eps:  # sealed sources may have grown since last ask
                f = feeds.setdefault(
                    tuple(ep), {"load": 0, "sealed": False, "node": None}
                )
                f["sealed"] = True
            for n_, (_ep, ts_) in list(pulling.items()):
                if ts_ < horizon:  # dead puller that never reported back
                    self._release_pull_slot_locked(oid, n_)
            st = self._xfer_plans.setdefault(oid, {"feeds": feeds, "pulling": pulling})
            if node in pulling:
                # A re-ask from a node already pulling means its previous
                # plan failed (or a sibling worker races it): release the
                # old slot and re-plan fresh.
                self._release_pull_slot_locked(oid, node)
                st = self._xfer_plans.setdefault(
                    oid, {"feeds": feeds, "pulling": pulling}
                )
            # SEALED-FIRST: fill the sources' fanout before chaining off
            # relays — bushier trees mean fewer checksummed relay hops
            # (each hop costs a verify+re-sum of the whole object, about
            # a memcpy's worth of CPU) and shorter failure cascades,
            # while the per-feed fanout bound still caps source egress.
            cands = [
                (not f["sealed"], f["load"], ep)
                for ep, f in feeds.items()
                if f["load"] < fanout and f.get("node") != node
            ]
            if not cands:
                self.metrics["pull_parks"] += 1
                self._park_pull(wid, req_id, oid)
                return _PARKED
            cands.sort(key=lambda c: (c[0], c[1]))
            _relay, _load, feed_ep = cands[0]
            feeds[feed_ep]["load"] += 1
            pulling[node] = (feed_ep, now)
            rep = self.node_object_endpoints.get(node)
            if rep is not None and tuple(rep) != feed_ep:
                # The requester's node serves its own in-flight pull's
                # board from now on: register it as a relay feed.
                rf = feeds.setdefault(
                    tuple(rep), {"load": 0, "sealed": False, "node": node}
                )
                rf["node"] = node
            plan = [list(feed_ep)] + [
                list(ep) for ep in eps if tuple(ep) != feed_ep
            ]
        return ("pull", plan)

    def _release_pull_slot_locked(self, oid: str, node: str) -> None:
        """Caller holds self.lock.  Free `node`'s slot in oid's transfer
        plan (its pull finished, failed, or decayed); drop the plan when
        fully quiesced — sealed feeds rebuild from the directory on the
        next ask."""
        st = self._xfer_plans.get(oid)
        if st is None:
            return
        ent = st["pulling"].pop(node, None)
        if ent is not None:
            f = st["feeds"].get(ent[0])
            if f is not None and f["load"] > 0:
                f["load"] -= 1
        if not st["pulling"] and not any(
            f["load"] > 0 for f in st["feeds"].values()
        ):
            self._xfer_plans.pop(oid, None)

    def _park_pull(self, wid: str, req_id: int, oid: str) -> None:
        """Caller holds self.lock.  Park a staggered puller until a new
        copy registers (or a 5s fallback timer — a failed pull must not
        strand the queue), then re-run the admission."""
        token = {"done": False, "sub": None, "timer": None}

        def serve(_oid=None):
            with self.lock:
                if token["done"]:
                    return
                token["done"] = True
                if token["sub"] is not None:
                    self.pubsub.unsubscribe(token["sub"])
                if token["timer"] is not None:
                    token["timer"].cancel()
            try:
                result = self._req_get_object(wid, req_id, oid)
            except Exception as e:  # noqa: BLE001 — reply with the error
                self._reply(wid, req_id, False, e)
                return
            if result is not _PARKED:
                self._reply(wid, req_id, True, result)

        token["sub"] = self.pubsub.subscribe(
            "object_copied", oid, lambda _o: serve(), once=True, deferred=True
        )
        t = threading.Timer(5.0, serve)
        t.daemon = True
        token["timer"] = t
        t.start()

    def _park_get(self, wid: str, req_id: int, oid: str) -> None:
        """Caller holds self.lock: one once-subscription per parked get;
        the reply runs DEFERRED (outside the runtime lock — it does store
        reads and a conn send)."""
        import functools

        self.pubsub.subscribe(
            "object_ready", oid,
            functools.partial(self._serve_parked_get, wid, req_id),
            once=True, deferred=True,
        )

    def _serve_parked_get(self, wid: str, req_id: int, oid: str) -> None:
        try:
            value = self._object_reply_value(oid, self._worker_node(wid))
            if isinstance(value, tuple) and value[0] == "pull":
                # The just-computed-object broadcast is the thundering
                # herd: N parked gets wake together — admission must gate
                # them exactly like first-ask pulls.
                value = self._admit_pull(wid, req_id, oid, value[1])
                if value is _PARKED:
                    return
            self._reply(wid, req_id, True, value)
        except Exception as e:  # noqa: BLE001 — reply with the error
            self._reply(wid, req_id, False, e)

    def _req_get_object(self, wid: str, req_id: int, oid: str):
        with self.lock:
            if not self.store.is_ready(oid):
                # A lost-but-lineaged object (typically a journaled inline
                # result whose bytes died with the previous head) would
                # otherwise park forever: kick a reconstruction first, then
                # park behind it.  Harmless when the producer is already in
                # flight (_reconstruct dedupes by task_id).
                if oid in self.lineage:
                    self._reconstruct(oid)
                self._park_get(wid, req_id, oid)
                return _PARKED
        try:
            value = self._object_reply_value(oid, self._worker_node(wid))
            if isinstance(value, tuple) and value[0] == "pull":
                return self._admit_pull(wid, req_id, oid, value[1])
            return value
        except ObjectLostError:
            # Bytes vanished (evicted past spill / spill file lost): lineage
            # re-execution (ray: object_recovery_manager.h:41) — park the
            # request behind the reconstructed producer.
            with self.lock:
                if self._reconstruct(oid):
                    self._park_get(wid, req_id, oid)
                    return _PARKED
            raise

    def _req_wait_objects(
        self, wid: str, req_id: int, oids: List[str], num_returns: int,
        timeout: Optional[float],
    ):
        """Event-driven worker wait (replaces the old check_ready poll loop):
        park until num_returns of oids are ready, reply with the flag list.
        A timer bounds parked time when the caller gave a timeout."""
        with self.lock:
            flags = [self.store.is_ready(o) for o in oids]
            pendings = [o for o, f in zip(oids, flags) if not f]
            if sum(flags) >= num_returns or not pendings:
                return flags
            if timeout is not None and timeout <= 0:
                return flags
            import functools

            token = {
                "need": num_returns - sum(flags),
                "wid": wid,
                "req_id": req_id,
                "oids": oids,
                "done": False,
                "timer": None,
                "subs": [],
            }
            for o in pendings:
                token["subs"].append(
                    self.pubsub.subscribe(
                        "object_ready", o,
                        functools.partial(self._on_wait_oid_ready, token),
                        once=True,
                    )
                )
            if timeout is not None:
                t = threading.Timer(timeout, self._wait_token_timeout, args=(token,))
                t.daemon = True
                token["timer"] = t
                t.start()
            return _PARKED

    @_locked
    def _on_wait_oid_ready(self, token, _oid: str) -> None:
        # runs inline inside publish, under self.lock (_object_ready holds it)
        token["need"] -= 1
        if token["need"] <= 0:
            self._wait_token_reply(token)

    @_locked
    def _wait_token_reply(self, token) -> None:
        """Caller holds self.lock.  Reply once and drop the token's
        remaining subscriptions (a timed-out token would otherwise leak
        until its oids happen to become ready)."""
        if token["done"]:
            return
        token["done"] = True
        if token["timer"] is not None:
            token["timer"].cancel()
        for sub in token["subs"]:
            self.pubsub.unsubscribe(sub)
        flags = [self.store.is_ready(o) for o in token["oids"]]
        self._reply(token["wid"], token["req_id"], True, flags)

    def _wait_token_timeout(self, token) -> None:
        with self.lock:
            self._wait_token_reply(token)

    @staticmethod
    def _lineage_cost(spec) -> int:
        return len(spec.args_blob or b"") + 256  # blob + record overhead

    @_locked
    def _lineage_record(self, oid: str, spec) -> None:
        """Caller holds self.lock.  Remember oid's producer spec for
        lineage reconstruction, within the LRU budget (ray:
        task_manager.h:97-104 lineage footprint accounting)."""
        if oid not in self.lineage:
            self.lineage_bytes += self._lineage_cost(spec)
        self.lineage[oid] = spec
        while self.lineage and (
            len(self.lineage) > self.lineage_max
            or self.lineage_bytes > self.lineage_max_bytes
        ):
            evicted, old = self.lineage.popitem(last=False)
            self.lineage_bytes -= self._lineage_cost(old)
            self._inline_lineage.discard(evicted)

    @_locked
    def _reconstruct(self, oid: str) -> bool:
        """Re-execute the producer task of a lost object.  Caller holds
        self.lock.  Returns False when no lineage exists (driver put() /
        actor-task outputs / lineage evicted)."""
        spec = self.lineage.get(oid)
        if spec is None:
            return False
        if spec.task_id in self.tasks:
            return True  # reconstruction already in flight
        if spec.fn_id and self.state.get_function(spec.fn_id) is None:
            # PR-4 edge, closed: the fn blob isn't exported yet (a journal
            # torn-tail ate the export, or the re-execution raced the
            # owner's re-export after a head bounce).  PARK this
            # reconstruction on a function-export FENCE instead of
            # dispatching a task that can only fail "unknown function" —
            # the export hook re-kicks it, and the io-loop tick fails it
            # loudly after _FN_FENCE_TIMEOUT_S so a never-returning owner
            # can't wedge the get forever.
            since, oids = self._fn_fences.setdefault(
                spec.fn_id, (time.monotonic(), [])
            )
            if oid not in oids:
                oids.append(oid)
            with self.store._available:
                for rid in spec.return_ids():
                    self.store._ready.pop(rid, None)
            self.events.emit(
                "WARNING", "lineage",
                "re-execution parked on pending function export",
                fn_id=spec.fn_id, object_id=oid,
            )
            return True
        # Dependencies may have been freed since the original run: recurse
        # up the lineage first (ray: recovery walks the lineage DAG).  A dep
        # that is "ready" but with lost bytes is handled lazily when the
        # worker's get parks on it.  This must run BEFORE invalidating this
        # task's own readiness flags: a dep with no lineage aborts the whole
        # reconstruction, and popped flags would leave every sibling return
        # id permanently un-ready (gets would park forever instead of
        # raising ObjectLostError).
        for d in set(spec.deps):
            if not self.store.is_ready(d) and not self._reconstruct(d):
                return False
        # Invalidate readiness of every return of this task so gets re-park
        # and wait() blocks until the re-execution completes.
        with self.store._available:
            for rid in spec.return_ids():
                self.store._ready.pop(rid, None)
        self.submit_task(spec)
        return True

    def _on_function_export(self, fn_id: str) -> None:
        """GlobalState export hook (fires OUTSIDE state.lock): release
        lineage re-executions parked on this function's fence."""
        with self.lock:
            ent = self._fn_fences.pop(fn_id, None)
            if ent is None:
                return
            for oid in ent[1]:
                try:
                    self._reconstruct(oid)
                except Exception:
                    continue

    def _sweep_fn_fences(self, now_mono: float) -> None:
        """io-loop tick (holds self.lock): a fence nobody re-exported
        within the timeout fails its parked gets LOUDLY instead of
        parking them forever."""
        for fn_id, (since, oids) in list(self._fn_fences.items()):
            if now_mono - since < _FN_FENCE_TIMEOUT_S:
                continue
            self._fn_fences.pop(fn_id, None)
            err = ObjectLostError(
                f"lineage re-execution waited {_FN_FENCE_TIMEOUT_S:.0f}s "
                f"for function {fn_id} to be re-exported; the owner never "
                "re-exported it"
            )
            for oid in oids:
                self.store.put_error(oid, err)
                self._object_ready(oid)
            self.events.emit(
                "WARNING", "lineage", "function-export fence timed out",
                fn_id=fn_id, objects=len(oids),
            )

    def _worker_node(self, wid: str) -> str:
        h = self.workers.get(wid)
        if h is not None:
            return h.node_id
        # Attached drivers read objects as their negotiated pseudo-node:
        # the head node when co-located (zero-copy), a store-less node id
        # when remote (forces inline/pull replies).
        return self.driver_nodes.get(wid, self.head_node_id)

    def _record_sealed(self, wid: str, oid: str, size: int) -> None:
        """A worker sealed a large result into ITS node's store: head-node
        seals land in the owner store's accounting; remote seals only enter
        the object directory (the bytes live on that node until pulled)."""
        node = self._worker_node(wid)
        with self.lock:
            self.object_sizes[oid] = size
        self._note_object(oid, wid)
        self._obj_event(oid, "seal", size, node)
        if node == self.head_node_id:
            self.store.mark_shm_sealed(oid, size)
            return
        with self.lock:
            self.object_locations.setdefault(oid, set()).add(node)
        self.store.mark_remote_sealed(oid)

    def _head_transfer_endpoint(self) -> Tuple[str, int]:
        """The address other nodes pull head-store objects from.  The
        listener may bind a wildcard (RAY_TPU_BIND_HOST=0.0.0.0), which is
        not routable — advertise the node_ip knob instead."""
        host, port = self.address
        if host in ("0.0.0.0", "", "::"):
            from ray_tpu._private import config as _config

            host = _config.get("node_ip")
        return (host, port)

    def _pull_endpoints(self, oid: str, exclude_head: bool = False) -> list:
        """Endpoints currently holding a copy, head store first (its
        listener serves object_fetch one-shots)."""
        eps = []
        if not exclude_head and self.store.has_local(oid):
            eps.append(self._head_transfer_endpoint())
        with self.lock:
            for n in self.object_locations.get(oid, ()):  # remote copies
                ep = self.node_object_endpoints.get(n)
                if ep is not None:
                    eps.append(ep)
        return eps

    def _object_reply_value(self, oid: str, requester_node: Optional[str] = None):
        """Build the get_object reply for a requester on requester_node:
        "inline" (small, bytes ride the control conn), "shm" (a copy is in
        the requester's OWN node store — mmap it), or ("pull", endpoints)
        (fetch over the transfer plane)."""
        err = self.store.error_for(oid)
        if err is not None:
            raise err
        if requester_node is None:
            requester_node = self.head_node_id
        if requester_node != self.head_node_id:
            with self.lock:
                local_copy = requester_node in self.object_locations.get(oid, ())
            if local_copy:
                return ("shm", None)
            obj = self.store._mem.get(oid)
            if obj is None:
                eps = self._pull_endpoints(oid)
                if eps:
                    return ("pull", eps)
                raise ObjectLostError(oid)
            # small: inline below
        else:
            if oid in self.store._in_shm:
                return ("shm", None)
            obj = self.store.get_sealed(oid)  # mem, or restore-from-spill
            if obj is None:
                eps = self._pull_endpoints(oid, exclude_head=True)
                if eps:
                    return ("pull", eps)
                raise ObjectLostError(oid)
            if oid in self.store._in_shm:  # a restore re-sealed it locally
                return ("shm", None)
        import pickle

        packed = bytes(
            ser.pack(bytes(obj.payload), [pickle.PickleBuffer(b) for b in obj.buffers])
        )
        return ("inline", packed)

    def _put_packed(self, oid: str, packed: bytes) -> None:
        payload, bufs = ser.unpack(memoryview(packed))
        import pickle

        self.object_sizes[oid] = len(packed)
        self.store.put_serialized(oid, bytes(payload), [pickle.PickleBuffer(b) for b in bufs])

    # ------------------------------------------------------------------
    # object readiness fan-out

    @_locked
    def _on_dep_ready(self, tid: str, _oid: str) -> None:
        # runs inline inside publish, under self.lock (_object_ready holds it)
        rec = self.tasks.get(tid)
        if rec is None:
            return
        rec.unmet_deps -= 1
        if rec.unmet_deps <= 0 and rec.state == "PENDING":
            rec.state = "READY"
            rec.stamp("queued")
            self.ready_queue.append(tid)

    def _object_ready(self, oid: str) -> None:
        with self.lock:
            # One publish fans out to every subscriber family: wait tokens
            # and dep-resolution run inline (they mutate scheduler state
            # under this lock); parked-get replies come back deferred and
            # run after the lock drops.
            deferred = self.pubsub.publish("object_ready", oid, oid)
            err = self.store.error_for(oid)
            if err is not None:
                # Propagate the error to ALREADY-QUEUED dependents eagerly:
                # bucketed dispatch only probes bucket heads, so a dependent
                # parked behind a blocked head would otherwise hang instead
                # of failing fast (the failure path is rare — an O(queue)
                # scan here costs nothing on the hot path).
                for shape in list(self.ready_queue.buckets.keys()):
                    q = self.ready_queue.buckets.get(shape)
                    if q is None:  # emptied by a nested propagation
                        continue
                    doomed = [
                        t for t in q
                        if (r := self.tasks.get(t)) is not None
                        and oid in r.spec.deps
                    ]
                    if doomed:
                        keep = deque(t for t in q if t not in set(doomed))
                        if keep:
                            self.ready_queue.buckets[shape] = keep
                        else:
                            self.ready_queue.buckets.pop(shape, None)
                        for t in doomed:
                            rec = self.tasks.get(t)
                            if rec is not None:
                                self._finish_with_error(rec, err, release=False)
            self._dispatch()
        for cb in deferred:
            cb(oid)

    # ------------------------------------------------------------------
    # submission (ray: CoreWorker::SubmitTask -> direct_task_transport.h:75)

    def submit_task(self, spec: TaskSpec, allow_pending: bool = False) -> List[str]:
        if (
            spec.runtime_env
            and not spec.runtime_env.get("_resolved")
            and (
                spec.runtime_env.get("working_dir")
                or spec.runtime_env.get("py_modules")
            )
        ):
            # Package local dirs into content-addressed KV entries ONCE;
            # workers fetch + extract (ray: runtime_env packaging/uri_cache).
            from ray_tpu._private.runtime_env import resolve_runtime_env

            spec.runtime_env = resolve_runtime_env(
                spec.runtime_env,
                lambda uri, data: self.state.kv_put(uri, data),
                self.session_name,
            )
        rec = TaskRecord(spec)
        rec.allow_pending = allow_pending
        return_ids = spec.return_ids()
        with self.lock:
            # Idempotent by task id: a client retrying across a head bounce
            # (its reply was lost) must not double-register the task
            # (ray: GCS dedupes re-registrations after failover the same
            # way).  Already-running: same record; already-finished: the
            # results are in the store.
            if spec.task_id in self.tasks or (
                return_ids and all(self.store.is_ready(o) for o in return_ids)
            ):
                return return_ids
            self.metrics["tasks_submitted"] += 1
            if spec.is_actor_creation:
                self.metrics["actors_created"] += 1
            if (
                self._spill_after > 0
                and len(self.tasks) >= self._spill_after
                and not spec.deps
                and not spec.contained_refs
                and not spec.runtime_env
                and self._lease_eligible(spec)
            ):
                # Backlog overflow: the spec rides a disk segment instead
                # of ~1KB of head memory; _dispatch reloads FIFO chunks
                # as the in-memory backlog drains.  No TaskRecord, no
                # dedupe entry — an overflow task re-submitted across a
                # head bounce re-runs (at-least-once, same contract as
                # direct dispatch).
                if self._ready_spill is None:
                    self._ready_spill = _ReadySpill(os.path.join(
                        f"/tmp/raytpu-spill-{self.session_name}",
                        "ready_overflow.bin",
                    ))
                self._ready_spill.append(spec)
                return return_ids
            self.tasks[spec.task_id] = rec
            for c in spec.contained_refs:
                self.store.add_ref(c)  # arg borrow for the task's lifetime
            import functools

            unmet = 0
            for d in set(spec.deps):
                if not self.store.is_ready(d):
                    self.pubsub.subscribe(
                        "object_ready", d,
                        functools.partial(self._on_dep_ready, spec.task_id),
                        once=True,
                    )
                    unmet += 1
            rec.unmet_deps = unmet
            if unmet == 0:
                rec.state = "READY"
                rec.stamp("queued")
                shape = self.ready_queue._shape_of(spec)
                # Submit→running FAST PATH: deps ready, bucket empty, and
                # an idle same-key leaseholder exists — push straight to
                # it and skip the whole dispatch scan (per-submit cost
                # O(1), not O(shapes)).  Dep errors still fail the task
                # exactly as the scan would.
                if not self.ready_queue.buckets.get(shape):
                    dep_err = None
                    for d in spec.deps:
                        e = self.store.error_for(d)
                        if e is not None:
                            dep_err = e
                            break
                    if dep_err is not None:
                        self._finish_with_error(rec, dep_err, release=False)
                        return return_ids
                    le = self._idle_lease_for(shape)
                    if le is not None:
                        self._dispatch_on_lease(le, rec)
                        return return_ids
                self.ready_queue.append(spec.task_id, shape)
            self._dispatch()
        return return_ids

    def create_actor(self, spec: TaskSpec, owner_did: Optional[str] = None) -> str:
        with self.lock:
            if spec.actor_id in self.actors:
                return spec.actor_id  # client retry across a head bounce
        info = ActorInfo(
            actor_id=spec.actor_id,
            name=spec.actor_name,
            max_restarts=spec.max_restarts,
            creation_spec=spec,
            namespace=spec.actor_namespace or self.namespace,
            owner_did=owner_did,
            detached=spec.lifetime == "detached",
        )
        self.state.register_actor(info)
        with self.lock:
            self.actors[spec.actor_id] = ActorRuntime(info)
        self.submit_task(spec)
        return spec.actor_id

    def submit_actor_task(self, spec: TaskSpec) -> List[str]:
        return_ids = spec.return_ids()
        with self.lock:
            if spec.task_id in self.tasks or (
                return_ids and all(self.store.is_ready(o) for o in return_ids)
            ):
                return return_ids  # client retry across a head bounce
            ar = self.actors.get(spec.actor_id)
            info = self.state.get_actor(spec.actor_id)
            if ar is None or info is None or info.state == DEAD:
                for oid in return_ids:
                    self.store.put_error(oid, ActorDiedError(spec.actor_id))
                    self._object_ready(oid)
                return return_ids
            rec = TaskRecord(spec)
            self.tasks[spec.task_id] = rec
            for c in spec.contained_refs:
                self.store.add_ref(c)
            # Actor calls are pushed directly to the actor's worker in
            # submission order (ray: direct_actor_task_submitter.h:67);
            # dependency resolution happens executor-side via parked gets.
            if info.state == ALIVE and ar.worker_id:
                self._push_actor_task(ar, rec)
            else:
                ar.queued.append(spec.task_id)
        return return_ids

    def _push_actor_task(self, ar: ActorRuntime, rec: TaskRecord) -> None:
        h = self.workers.get(ar.worker_id)
        if h is None:
            ar.queued.append(rec.spec.task_id)
            return
        rec.state = "RUNNING"
        rec.start_time = time.time()
        rec.stages["leased"] = rec.start_time
        rec.worker_id = h.worker_id
        rec.node_id = h.node_id
        ar.in_flight[rec.spec.task_id] = None
        blob = None
        if rec.spec.fn_id not in h.known_fns:
            blob = self.state.get_function(rec.spec.fn_id)
            h.known_fns.add(rec.spec.fn_id)
        self._send(h, ("task", rec.spec, blob))
        if h.conn is not None:
            rec.stamp("pushed")  # else: stamped at the pending-send flush

    # ------------------------------------------------------------------
    # dispatch loop (ray: cluster_task_manager.h + local_task_manager.h)

    @staticmethod
    def _strategy_shape_key(strategy):
        """Stable equality key for head-of-line grouping — the default repr
        embeds the instance address, which would make every task its own
        shape and silently disable the blocking."""
        from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            return ("affinity", strategy.node_id, strategy.soft)
        return strategy if isinstance(strategy, (str, type(None))) else repr(strategy)

    # ------------------------------------------------------------------
    # head-side lease reuse (ray: direct_task_transport.h:40-55 — the
    # SchedulingKey-keyed lease pool, applied to the head's own relayed
    # dispatch): the first task of a key pays full placement and BINDS
    # its worker to the key with resources held; same-key tasks then
    # bypass the scheduler entirely and push straight onto an idle
    # leaseholder.  All helpers run under self.lock.

    @staticmethod
    def _lease_eligible(spec) -> bool:
        return (
            spec.actor_id is None
            and not spec.is_actor_creation
            and spec.placement_group_id is None
            and spec.scheduling_strategy in (None, "DEFAULT", "SPREAD")
        )

    def _idle_lease_for(self, key) -> Optional[TaskLease]:
        leases = self.task_leases.get(key)
        if not leases:
            return None
        for le in list(leases):
            if le.idle_since is None:
                continue
            node = self.state.nodes.get(le.node_id)
            if node is not None and node.draining:
                # A late same-key task must NOT ride an idle lease onto a
                # draining node — revoke the binding (resources released,
                # worker returned for the depart to reap) so the task
                # re-drives through full placement elsewhere.
                self._revoke_lease_locked(le, cause="drain")
                continue
            h = self.workers.get(le.worker_id)
            if h is None or h.state != "busy" or h.current_task is not None:
                # Defensive: the crash path revokes synchronously, so a
                # stale binding here means the worker moved on without
                # us — drop the lease WITHOUT re-releasing resources (a
                # double release would inflate the node ledger).
                self._revoke_lease_locked(
                    le, cause="stale", release=False, return_worker=False
                )
                continue
            return le
        return None

    def _grant_lease_locked(self, key, h, node, spec) -> TaskLease:
        self._task_lease_seq += 1
        le = TaskLease(
            f"tl-{self._task_lease_seq}", key, h.worker_id, node,
            dict(spec.resources),
        )
        self.task_leases.setdefault(key, []).append(le)
        self.lease_by_worker[h.worker_id] = le
        self.metrics["task_leases_granted"] += 1
        self._journal_append(
            ("lease", "grant", le.lease_id, repr(key), h.worker_id, node,
             dict(spec.resources))
        )
        return le

    def _dispatch_on_lease(self, le: TaskLease, rec: TaskRecord) -> None:
        """Fast path: push a ready same-key task straight onto an idle
        leaseholder — no placement, no resource churn, no pool ops."""
        h = self.workers[le.worker_id]
        le.idle_since = None
        le.dispatched += 1
        self.metrics["lease_dispatches"] += 1
        now = time.monotonic()
        if now - le.last_extend_journal > self._lease_idle_s * 0.5:
            # Extends journal at half-idle-window granularity: restart
            # diagnostics see the lease was hot without paying one entry
            # per task (group commit batches these anyway).
            le.last_extend_journal = now
            self._journal_append(("lease", "extend", le.lease_id, le.dispatched))
        spec = rec.spec
        rec.state = "RUNNING"
        rec.start_time = time.time()
        rec.stages["leased"] = rec.start_time
        rec.node_id = le.node_id
        rec.worker_id = h.worker_id
        rec.lease = le
        h.current_task = spec.task_id
        blob = None
        if spec.fn_id not in h.known_fns:
            blob = self.state.get_function(spec.fn_id)
            h.known_fns.add(spec.fn_id)
        self._send(h, ("task", spec, blob))
        if h.conn is not None:
            rec.stamp("pushed")

    def _lease_task_finished(self, rec: TaskRecord, h) -> None:
        """A task finished (or retry-released) on a LIVE leaseholder:
        re-arm the lease and chain the next same-key task immediately —
        the completion-to-dispatch path the flamegraphs showed paying
        full placement per task."""
        le = rec.lease
        rec.lease = None
        rec.node_id = None
        le.idle_since = time.monotonic()
        if h is not None:
            h.current_task = None
        node = self.state.nodes.get(le.node_id)
        if node is not None and node.draining:
            # Drain-revoke instead of re-arm: chaining the next same-key
            # task here would keep re-busying capacity that is leaving.
            # The queued siblings re-drive through full placement onto
            # surviving nodes on the dispatch below.
            self._revoke_lease_locked(le, cause="drain")
            self._dispatch()
            return
        q = self.ready_queue.buckets.get(le.key)
        while q:
            tid = q[0]
            nrec = self.tasks.get(tid)
            if nrec is None or nrec.cancelled:
                q.popleft()
                continue
            dep_err = None
            for d in nrec.spec.deps:
                e = self.store.error_for(d)
                if e is not None:
                    dep_err = e
                    break
            if dep_err is not None:
                q.popleft()
                self._finish_with_error(nrec, dep_err, release=False)
                continue
            q.popleft()
            if not q:
                self.ready_queue.buckets.pop(le.key, None)
            self._dispatch_on_lease(le, nrec)
            return
        if q is not None and not q:
            self.ready_queue.buckets.pop(le.key, None)

    def _revoke_lease_locked(
        self, le: TaskLease, cause: str, release: bool = True,
        return_worker: bool = True,
    ) -> None:
        """Unbind a lease: journal the revocation, release its held
        resources (exactly once — the caller says whether this revoke
        still owns them), return the worker to the shared pool."""
        pool = self.task_leases.get(le.key)
        if pool is not None:
            try:
                pool.remove(le)
            except ValueError:
                pass
            if not pool:
                self.task_leases.pop(le.key, None)
        if self.lease_by_worker.get(le.worker_id) is le:
            self.lease_by_worker.pop(le.worker_id, None)
        if release:
            self.scheduler.release(le.node_id, le.resources)
        self.metrics["task_leases_revoked"] += 1
        self._journal_append(("lease", "revoke", le.lease_id, cause))
        if return_worker:
            h = self.workers.get(le.worker_id)
            if h is not None and h.state == "busy" and h.current_task is None:
                self._return_worker(h)

    def _revoke_one_idle_lease(self) -> bool:
        """Demand revocation: a different shape (or a placement group)
        can't place while idle leases pin resources — free the stalest
        one and let the caller retry.  Same-key idle leases can't reach
        here (dispatch consumes them first), so this never thrashes a
        hot stream."""
        best = None
        for pool in self.task_leases.values():
            for le in pool:
                if le.idle_since is None:
                    continue
                if best is None or le.idle_since < best.idle_since:
                    best = le
        if best is None:
            return False
        self._revoke_lease_locked(best, cause="demand")
        return True

    def _revoke_idle_leases(self, now_mono: float) -> None:
        """io-loop tick: leases idle past RAY_TPU_LEASE_IDLE_S return
        their worker + resources to the shared pool, so a burst's leases
        can't strand capacity (chaos leans on this + the crash-path
        revoke)."""
        revoked = False
        for pool in list(self.task_leases.values()):
            for le in list(pool):
                if (
                    le.idle_since is not None
                    and now_mono - le.idle_since > self._lease_idle_s
                ):
                    self._revoke_lease_locked(le, cause="idle-timeout")
                    revoked = True
        if revoked:
            self._dispatch()

    @_locked
    def _dispatch(self) -> None:
        # caller holds self.lock
        sp = self._ready_spill
        if (
            sp is not None
            and sp.count
            and len(self.tasks) <= max(self._spill_after // 2, 1000)
        ):
            # The in-memory backlog drained below the low watermark:
            # reload the next FIFO chunk of spilled overflow specs.
            for spec in sp.load(2000):
                if spec.task_id in self.tasks:
                    continue
                rec = TaskRecord(spec)
                rec.state = "READY"
                rec.stamp("queued")
                self.tasks[spec.task_id] = rec
                self.ready_queue.append(spec.task_id)
        for pg_id in list(self.pending_pgs):
            pg = self.state.placement_groups.get(pg_id)
            if pg is None or pg.state != "PENDING":
                self.pending_pgs.remove(pg_id)
                continue
            ok = self.scheduler.reserve_placement_group(pg)
            while not ok and self._revoke_one_idle_lease():
                # Idle leases were pinning the bundle capacity.
                ok = self.scheduler.reserve_placement_group(pg)
            if ok:
                self.pending_pgs.remove(pg_id)
        # Shape-bucketed dispatch (ray: ClusterTaskManager queues tasks per
        # scheduling class): probe ONE head task per shape; if it cannot
        # place, the whole bucket stays untouched this round.  Per-event
        # cost is O(shapes), not O(queued tasks) — rotating the full
        # backlog per completion was a measured 4x collapse at 4 clients
        # (the deeper the queue, the slower every completion).
        for shape in list(self.ready_queue.buckets.keys()):
            q = self.ready_queue.buckets.get(shape)
            while q:
                tid = q[0]
                rec = self.tasks.get(tid)
                if rec is None or rec.cancelled:
                    q.popleft()
                    continue
                spec = rec.spec
                # error propagation: if any dep errored, fail without running
                dep_err = None
                for d in spec.deps:
                    e = self.store.error_for(d)
                    if e is not None:
                        dep_err = e
                        break
                if dep_err is not None:
                    q.popleft()
                    self._finish_with_error(rec, dep_err, release=False)
                    continue
                if Scheduler.is_pg_task(spec):
                    sel = self.scheduler.select_pg(spec, spec.resources)
                    if sel is None:
                        if self._revoke_one_idle_lease():
                            continue  # freed pinned resources: retry head
                        break  # bucket blocked: siblings can't place either
                    node, bidx = sel
                    rec.pg = (self.scheduler._pg_for_spec(spec)[0], bidx)
                else:
                    # Lease fast path: an idle same-key leaseholder takes
                    # the task with zero placement work.
                    le = self._idle_lease_for(shape)
                    if le is not None:
                        q.popleft()
                        self._dispatch_on_lease(le, rec)
                        continue
                    try:
                        node = self.scheduler.select_node(spec)
                    except ValueError as e:
                        if self.allow_pending_infeasible or rec.allow_pending:
                            break
                        q.popleft()
                        self._finish_with_error(rec, e, release=False)
                        continue
                    if node is None or not self.scheduler.acquire(
                        node, spec.resources
                    ):
                        if self._revoke_one_idle_lease():
                            continue  # idle leases were the missing slack
                        break
                q.popleft()
                self._dispatch_placed(rec, node, shape)
            if not q:
                self.ready_queue.buckets.pop(shape, None)

    @_locked
    def _dispatch_placed(self, rec: TaskRecord, node: str, shape=None) -> None:
        # caller holds self.lock; resources for `node` already acquired
        spec = rec.spec
        tid = spec.task_id
        h = self._lease_worker(node, spec)
        rec.state = "RUNNING"
        rec.start_time = time.time()
        rec.stages["leased"] = rec.start_time
        rec.node_id = node
        rec.worker_id = h.worker_id
        h.current_task = tid
        if self._lease_eligible(spec):
            # First task of its SchedulingKey through full placement:
            # bind the worker to the key — same-key successors skip the
            # scheduler entirely (_dispatch_on_lease).
            rec.lease = self._grant_lease_locked(
                shape if shape is not None else self.ready_queue._shape_of(spec),
                h, node, spec,
            )
        if spec.is_actor_creation:
            h.state = "actor"
            h.actor_id = spec.actor_id
            ar = self.actors.get(spec.actor_id)
            if ar is not None:
                ar.worker_id = h.worker_id
                ar.placement = (
                    ("pg",) + rec.pg if rec.pg else ("node", node)
                )
        else:
            h.state = "busy"
        blob = None
        if spec.fn_id not in h.known_fns:
            blob = self.state.get_function(spec.fn_id)
            h.known_fns.add(spec.fn_id)
        kind = "create_actor" if spec.is_actor_creation else "task"
        self._send(h, (kind, spec, blob))
        if h.conn is not None:
            # A still-starting worker queues the frame in pending_sends;
            # the handshake flush stamps "pushed" then — so the lease
            # stage honestly carries the worker's whole boot time.
            rec.stamp("pushed")

    # ------------------------------------------------------------------
    # completion / failure

    def _release_for(self, rec: TaskRecord) -> None:
        if rec.lease is not None:
            # The LEASE owns the node resources: they release exactly once
            # at revoke (idle timeout, demand, worker death), never per
            # task — releasing here too would inflate the node ledger.
            rec.lease = None
            rec.node_id = None
            return
        if rec.pg is not None:
            self.scheduler.release_pg(rec.pg[0], rec.pg[1], rec.spec.resources)
            rec.pg = None
            rec.node_id = None
        elif rec.node_id:
            self.scheduler.release(rec.node_id, rec.spec.resources)
            rec.node_id = None

    def _release_actor_placement(self, ar: ActorRuntime) -> None:
        res = ar.info.creation_spec.resources
        if ar.placement is None:
            return
        if ar.placement[0] == "pg":
            self.scheduler.release_pg(ar.placement[1], ar.placement[2], res)
        else:
            self.scheduler.release(ar.placement[1], res)
        ar.placement = None

    @_locked
    def _on_task_done(self, wid: str, task_id: str, results, error_blob,
                      timing=None) -> None:
        # caller holds self.lock
        rec = self.tasks.pop(task_id, None)
        h = self.workers.get(wid)
        if rec is None:
            # Unknown/already-failed task (e.g. cancelled, actor queue
            # failed): its results are dropped, so the executor's
            # serialize-time guard borrows must still be released.
            if error_blob is None:
                for item in results:
                    for c in item[3]:
                        self._decref_local(c)
            return
        spec = rec.spec
        # Executor-side stage stamps (recv/start/end wall clock) land on
        # the head clock via the handshake-estimated per-conn offset —
        # the same correction task_events/spans get at ingest.
        if isinstance(timing, dict):
            off = self.clock_offsets.get(wid, 0.0)
            for src, dst in (
                ("recv", "received"), ("start", "running"), ("end", "exec_done"),
            ):
                v = timing.get(src)
                if isinstance(v, (int, float)):
                    rec.stages[dst] = v + off
        rec.stamp("done")
        if error_blob is not None and not (
            spec.retry_exceptions and spec.attempt < spec.max_retries
        ):
            # Only FINAL failures count — a retried attempt is not a failed
            # task (tasks_retried tracks attempts).
            self._record_task_end(rec, wid, "FAILED")
        ready_ids = []
        if error_blob is None:
            for item in results:
                oid, kind, data, contained = item
                self._store_contained(oid, contained)
                # Release the executor's serialize-time guard borrows now
                # that the stored-object borrow above holds the children
                # (see worker_proc._store_results).
                for c in contained:
                    self._decref_local(c)
                if kind == "shm":
                    self._record_sealed(wid, oid, data)
                else:
                    self._put_packed(oid, data)
                ready_ids.append(oid)
                if spec.actor_id is None:
                    self._lineage_record(oid, spec)
                    if kind != "shm":
                        # Inline bytes live ONLY in this process: journal
                        # the lineage entry so a post-restart get() can
                        # re-execute the producer instead of erroring
                        # (sealed results survive in node stores and need
                        # no journal).
                        self._inline_lineage.add(oid)
                        self._journal_append(("lineage", oid, spec))
            # Results stored + lineage recorded: the lifecycle record is
            # complete — stamp "sealed" and fold the stage durations into
            # the ring + histograms (the per-task state machine's fold).
            rec.stamp("sealed")
            self._record_task_end(rec, wid, "FINISHED")
            if spec.is_actor_creation:
                self._on_actor_alive(spec.actor_id)
        else:
            err = cloudpickle.loads(error_blob)
            if spec.retry_exceptions and spec.attempt < spec.max_retries:
                self._retry_task(rec, h)
                return
            for oid in spec.return_ids():
                self.store.put_error(oid, err)
                ready_ids.append(oid)
            if spec.is_actor_creation:
                ar = self.actors.get(spec.actor_id)
                self.state.set_actor_state(spec.actor_id, DEAD, death_cause=str(err))
                if ar:
                    self._fail_actor_queue(ar, ActorDiedError(f"creation failed: {err}"))
                    self._release_actor_placement(ar)
                    if h is not None:
                        self._send(h, ("kill",))
                        h.state = "dead"
        # release borrows
        for c in spec.contained_refs:
            self._decref_local(c)
        # free resources + worker
        if spec.actor_id is not None and not spec.is_actor_creation:
            ar = self.actors.get(spec.actor_id)
            if ar:
                ar.in_flight.pop(task_id, None)
        elif not spec.is_actor_creation:
            le = rec.lease
            if (
                le is not None
                and h is not None
                and h.state == "busy"
                and self.lease_by_worker.get(wid) is le
            ):
                # Leaseholder stays bound: chain the next same-key task
                # now, or idle within the lease window.
                self._lease_task_finished(rec, h)
            else:
                self._release_for(rec)
                if h is not None and h.state == "busy":
                    self._return_worker(h)
        for oid in ready_ids:
            self._object_ready(oid)
        if spec.is_actor_creation:
            # The creation return (always None, or the creation error) has
            # no ObjectRef holder anywhere — create_actor hands back the
            # actor ID, not a ref — so the stored bytes were orphaned at
            # refcount 0 forever.  Surfaced by the object ledger (every
            # actor left a no-live-holder suspect); freed here at the
            # source instead of exempted in the report.
            for oid in spec.return_ids():
                self.store.remove_ref(oid)
        self._dispatch()

    def _retry_task(self, rec: TaskRecord, h: Optional[WorkerHandle]) -> None:
        spec = rec.spec
        spec.attempt += 1
        self.metrics["tasks_retried"] += 1
        # A fresh attempt restarts the stage machine (stale executor/done
        # stamps from the failed attempt would disorder the telescoping);
        # the original submit time is kept so total wall stays honest.
        rec.stages = {"submit": rec.stages.get("submit", time.time())}
        if spec.actor_id is not None and not spec.is_actor_creation:
            # Relayed actor-call retry: re-push to the actor's executor
            # (the plain ready queue would lease a stateless worker and
            # run the method without the actor instance).
            ar = self.actors.get(spec.actor_id)
            info = self.state.get_actor(spec.actor_id)
            if ar is None or info is None or info.state == DEAD:
                self._finish_with_error(rec, ActorDiedError(spec.actor_id),
                                        release=False)
                return
            self.tasks[spec.task_id] = rec
            if info.state == ALIVE and ar.worker_id:
                self._push_actor_task(ar, rec)
            else:
                ar.queued.append(spec.task_id)
            return
        le = rec.lease
        if (
            le is not None
            and h is not None
            and h.state == "busy"
            and self.lease_by_worker.get(h.worker_id) is le
        ):
            # Error-retry on a live leaseholder: the lease re-arms (the
            # retried attempt likely re-dispatches right back onto it).
            self._lease_task_finished(rec, h)
        else:
            self._release_for(rec)
            if h is not None and h.state == "busy":
                self._return_worker(h)
        rec.state = "READY"
        rec.stamp("queued")
        rec.node_id = rec.worker_id = None
        self.tasks[spec.task_id] = rec
        self.ready_queue.append(spec.task_id)
        self._dispatch()

    def _finish_with_error(self, rec: TaskRecord, err: Exception, release: bool) -> None:
        spec = rec.spec
        self.tasks.pop(spec.task_id, None)
        self._record_task_end(rec, rec.worker_id, "FAILED")
        if release:
            self._release_for(rec)
        for c in spec.contained_refs:
            self._decref_local(c)
        for oid in spec.return_ids():
            self.store.put_error(oid, err)
            self._object_ready(oid)
        if spec.is_actor_creation:
            self.state.set_actor_state(spec.actor_id, DEAD, death_cause=str(err))
            ar = self.actors.get(spec.actor_id)
            if ar:
                self._fail_actor_queue(ar, ActorDiedError(str(err)))

    def _on_actor_alive(self, actor_id: str) -> None:
        ar = self.actors.get(actor_id)
        if ar is None:
            return
        ar._creation_crash_retries = 0  # fresh budget per successful start
        self.state.set_actor_state(actor_id, ALIVE, worker_id=ar.worker_id)
        while ar.queued:
            tid = ar.queued.popleft()
            rec = self.tasks.get(tid)
            if rec is not None and not rec.cancelled:
                self._push_actor_task(ar, rec)

    def _fail_actor_queue(self, ar: ActorRuntime, err: Exception) -> None:
        # (each popped record below is also logged to the task-event sink)
        doomed = list(ar.queued) + list(ar.in_flight)
        ar.queued.clear()
        ar.in_flight.clear()
        for tid in doomed:
            rec = self.tasks.pop(tid, None)
            if rec is None:
                continue
            self._record_task_end(rec, rec.worker_id, "FAILED")
            for oid in rec.spec.return_ids():
                self.store.put_error(oid, err)
                self._object_ready(oid)
            for c in rec.spec.contained_refs:
                self._decref_local(c)

    def _record_task_end(self, rec, wid, state: str) -> None:
        from ray_tpu._private import telemetry as _telemetry

        spec = rec.spec
        self.metrics["tasks_finished" if state == "FINISHED" else "tasks_failed"] += 1
        end = time.time()
        durations = _telemetry.stage_durations(rec.stages)
        self.task_events.append(
            {
                "task_id": spec.task_id,
                "name": spec.name,
                "state": state,
                "node_id": rec.node_id,
                "worker_id": wid,
                "actor_id": spec.actor_id,
                "parent_task_id": spec.parent_task_id,
                "attempt": spec.attempt,
                "end_time": end,
                "duration": (end - rec.start_time) if rec.start_time else 0.0,
                "creation": spec.is_actor_creation,
                "stages": dict(rec.stages),
                "durations": durations,
            }
        )
        self._observe_stage_durations(durations)

    def _observe_stage_durations(self, durations) -> None:
        """Fold one task's per-stage seconds into the
        task_stage_seconds{stage=...} histograms (never raises — the
        fold must not take the completion path down).  Tag resolution is
        cached per stage label: this runs for EVERY finished task (twice
        per direct task via task_events) and the per-observe merge+sort
        was a measured slice of the head's completion cost."""
        if not durations:
            return
        try:
            cache = self._stage_key_cache
            if cache is None:
                from ray_tpu._private import telemetry as _telemetry

                hist = _telemetry.task_stage_histogram()
                cache = self._stage_key_cache = (hist, {})
            hist, keys = cache
            for stage, v in durations.items():
                k = keys.get(stage)
                if k is None:
                    k = keys[stage] = hist.resolved_key({"stage": stage})
                hist.observe_resolved(k, v)
        except Exception:
            pass

    @_locked
    def _deps_locality(self, deps) -> Dict[str, int]:
        """{node_id: BYTES of dep objects local there} — feeds the
        scheduler's locality preference (dispatch path; called under
        self.lock via _dispatch).  Size-weighted, so a node holding one
        100MB argument beats a node holding three 1KB ones (ray: the
        hybrid policy's locality/load tradeoff weighs transfer cost);
        tiny deps (everything under the locality_min_bytes knob in total)
        yield no pull at all — spreading wins when the wire cost is noise."""
        from ray_tpu._private import config as _config

        scores: Dict[str, int] = {}
        for d in deps:
            size = self.object_sizes.get(d, 1)
            for n in self.object_locations.get(d, ()):
                scores[n] = scores.get(n, 0) + size
            if self.store.has_local(d):
                scores[self.head_node_id] = (
                    scores.get(self.head_node_id, 0) + size
                )
        floor = _config.get("locality_min_bytes")
        if scores and max(scores.values()) < floor:
            return {}
        return scores

    @_locked
    def _fail_task_record(
        self, rec: TaskRecord, wid: Optional[str], err: Exception,
        record_end: bool = True,
    ) -> None:
        """Caller holds self.lock.  Terminal task failure: pop + release,
        error every return id, drop borrowed refs (the shared epilogue of
        every crash/cancel/OOM/env-failure branch)."""
        spec = rec.spec
        self.tasks.pop(spec.task_id, None)
        self._release_for(rec)
        if record_end:
            self._record_task_end(rec, wid, "FAILED")
        for oid in spec.return_ids():
            self.store.put_error(oid, err)
            self._object_ready(oid)
        for c in spec.contained_refs:
            self._decref_local(c)

    @_locked
    def _retry_task_record(self, rec: TaskRecord) -> None:
        # caller holds self.lock
        self.metrics["tasks_retried"] += 1
        self._release_for(rec)
        rec.state = "READY"
        rec.stages = {"submit": rec.stages.get("submit", time.time())}
        rec.stamp("queued")
        rec.worker_id = None
        self.ready_queue.append(rec.spec.task_id)
        self._dispatch()

    @_locked
    def _on_worker_crash(self, wid: str) -> None:
        # caller holds self.lock.  Pop BOTH classification riders up front:
        # leaving them behind on duplicate notifications would leak entries
        # for the head's lifetime.
        oom = self._oom_kills.pop(wid, None)
        env_fail = self._env_failures.pop(wid, None)
        self.worker_peer_endpoints.pop(wid, None)
        # Telemetry: a dead process's gauges (queue depths) must not keep
        # contributing to the cluster aggregate (its own lock; no I/O).
        self.telemetry.forget(wid)
        self.ledger.forget(wid)
        self.profiles.forget(wid)
        # Ref borrows the dead process still held: park them as DEAD-
        # HOLDER leak suspects (attributed to this worker's node/pid by
        # `ray_tpu memory --leaks`), reclaimed after the grace so the
        # bytes don't stay pinned forever (ray: the owner releases a dead
        # borrower's references the same way).
        dead_refs = self.worker_refs.pop(wid, None)
        if dead_refs:
            from ray_tpu._private import config as _cfg_leak

            hh = self.workers.get(wid)
            self._dead_refs[wid] = {
                "refs": dead_refs,
                "node": hh.node_id if hh is not None else None,
                "pid": hh.pid if hh is not None else None,
                "t": time.time(),
                "reclaim_at": time.monotonic()
                + _cfg_leak.get("leak_reclaim_grace_s"),
            }
        self.clock_offsets.pop(wid, None)
        # Lease-dispatched tasks running ON this worker die with it; their
        # executors can never send the terminal event that would clear the
        # RUNNING entry (the caller's retry, if any, re-reports).
        for tid, e in list(self.direct_running.items()):
            if e.get("worker_id") == wid:
                self.direct_running.pop(tid, None)
        self._drop_remote_subs(wid)
        # Fences routed through this worker can never ack: fail them so the
        # caller falls back to the head path instead of hanging.
        for fid, ent in list(self._pending_fences.items()):
            if ent[2] == wid:
                self._pending_fences.pop(fid, None)
                # Restartable actor: "pending" keeps the caller relaying
                # until the new instance resolves; "dead" would pin the
                # relay path forever.
                verdict = "pending" if ent[4] else "dead"
                self._reply(ent[0], ent[1], True, (verdict, None, None, ent[4]))
        # A head-side task lease dies with its worker: revoke NOW (journal
        # + release the held resources exactly once) so the in-flight
        # task's retry below re-places through the scheduler instead of
        # binding to a ghost — chaos asserts no stranded capacity.
        tle = self.lease_by_worker.get(wid)
        if tle is not None:
            self._revoke_lease_locked(tle, cause="worker_death",
                                      return_worker=False)
        # Leases die with the worker they lease (callers see the peer conn
        # EOF and retry) and with the CALLER that held them (its workers
        # return to the pool).
        for lid, rec in list(self.peer_leases.items()):
            if rec[0] == wid:
                self._release_peer_lease_locked(lid, return_worker=False)
            elif rec[3] == wid:
                self._release_peer_lease_locked(lid, return_worker=True)
        parked = self._parked_peer_leases.pop(wid, None)
        if parked:
            for caller, req_id, lease_id in parked:
                self._release_peer_lease_locked(lease_id, return_worker=False)
                self._reply(caller, req_id, True, ("busy",))
        h = self.workers.pop(wid, None)
        if h is None or h.state == "dead":
            return  # duplicate notification (daemon report + conn EOF)
        if wid in self._expected_worker_stops:
            self._expected_worker_stops.discard(wid)
            self.events.emit(
                "INFO", "worker", "worker stopped",
                worker_id=wid, node_id=h.node_id, cause="node_removed",
            )
        else:
            self.metrics["worker_crashes"] += 1
            self.events.emit(
                "WARNING", "worker", "worker died",
                worker_id=wid, node_id=h.node_id,
                cause="oom_kill" if oom else (
                    "env_setup" if env_fail else "crash"
                ),
            )
        h.state = "dead"
        pool = self.idle_pool.get((h.node_id, h.env_key))
        if pool and wid in pool:
            pool.remove(wid)
        if h.actor_id is not None:
            self._on_actor_worker_crash(h, env_fail=env_fail)
            return
        tid = h.current_task
        if tid is None:
            return
        rec = self.tasks.get(tid)
        if rec is None:
            return
        spec = rec.spec
        if rec.cancelled:
            self._fail_task_record(
                rec, wid, TaskCancelledError(spec.name), record_end=False
            )
            return
        if env_fail is not None:
            from ray_tpu.exceptions import RuntimeEnvSetupError

            # Deterministic failure: reinstalling the same broken env on
            # retry would fail identically — no retry budget applies.
            self._fail_task_record(rec, wid, RuntimeEnvSetupError(env_fail))
            return
        if oom is not None:
            from ray_tpu._private import config as _config

            # OOM kills retry on their OWN budget (ray: task_oom_retries) —
            # a memory-pressure victim is not a task bug, and max_retries=0
            # tasks still deserve another placement.
            oom_attempts = getattr(spec, "oom_attempts", 0)
            if oom_attempts < _config.get("task_oom_retries"):
                spec.oom_attempts = oom_attempts + 1
                self._retry_task_record(rec)
                return
            rss, used, limit = oom
            self._fail_task_record(rec, wid, OutOfMemoryError(
                f"task {spec.name}'s worker was killed by the node memory "
                f"monitor (rss={rss >> 20}MiB, node usage {used >> 20}MiB "
                f"> limit {limit >> 20}MiB) after "
                f"{oom_attempts} OOM retries"
            ))
            return
        if spec.attempt < spec.max_retries:
            spec.attempt += 1
            self._retry_task_record(rec)
        else:
            self._fail_task_record(rec, wid, WorkerCrashedError(
                f"worker running task {spec.name} died unexpectedly"
            ))

    @_locked
    def _on_actor_worker_crash(
        self, h: WorkerHandle, env_fail: Optional[str] = None
    ) -> None:
        actor_id = h.actor_id
        ar = self.actors.get(actor_id)
        info = self.state.get_actor(actor_id)
        if ar is None or info is None or info.state == DEAD:
            return
        creation = ar.info.creation_spec
        if env_fail is not None:
            # Runtime-env setup failed for this actor's worker: retrying
            # would reinstall the same broken env — fail the actor NOW with
            # the setup error, not after 3 generic creation retries.
            from ray_tpu.exceptions import RuntimeEnvSetupError

            err = RuntimeEnvSetupError(env_fail)
            self._release_actor_placement(ar)
            self.state.set_actor_state(actor_id, DEAD, death_cause=env_fail)
            rec = self.tasks.pop(creation.task_id, None)
            if rec is not None:
                for oid in rec.spec.return_ids():
                    self.store.put_error(oid, err)
                    self._object_ready(oid)
            self._fail_actor_queue(ar, err)
            return
        crash_retries = getattr(ar, "_creation_crash_retries", 0)
        if (
            info.state in (PENDING_CREATION, RESTARTING)
            and crash_retries < 3
            and not ar.expected_death
            and not ar.no_restart
        ):
            # (expected_death/no_restart: a kill() during init must stay
            # dead, not resurrect through the scheduling-retry path.)
            ar._creation_crash_retries = crash_retries + 1
            # The worker died BEFORE the actor (re)initialized — a
            # scheduling/environment failure (e.g. it was placed on a node
            # whose daemon died in the same instant), not an actor death.
            # Re-schedule the creation without burning max_restarts budget,
            # matching the reference's GCS actor scheduler, which retries
            # placement and only counts ALIVE→dead transitions as restarts
            # (ray: gcs_actor_scheduler.h:111, gcs_actor_manager.h:258-266).
            self.tasks.pop(creation.task_id, None)
            self._release_actor_placement(ar)
            ar.worker_id = None
            rec = TaskRecord(creation)
            rec.state = "READY"
            rec.stamp("queued")
            self.tasks[creation.task_id] = rec
            self.ready_queue.append(creation.task_id)
            self._dispatch()
            return
        self._release_actor_placement(ar)
        err = ActorDiedError(
            f"actor {actor_id} died"
            + (" (killed)" if ar.expected_death else " unexpectedly")
        )
        can_restart = (
            not ar.no_restart
            and not ar.expected_death
            and (
                info.max_restarts == -1 or info.num_restarts < info.max_restarts
            )
        )
        # In-flight relayed calls: retry-budgeted ones re-queue onto the
        # restarted instance (same semantics as the direct path's recovery
        # re-drive; ray: max_task_retries); the rest fail ActorDiedError.
        # in_flight is insertion-ordered (push order == per-caller submit
        # order), so `requeue` comes out in submission order and the
        # extendleft below really does prepend "in order".
        requeue: List[str] = []
        for tid in list(ar.in_flight):
            rec = self.tasks.get(tid)
            if rec is None:
                continue
            if can_restart and rec.spec.attempt < rec.spec.max_retries:
                rec.spec.attempt += 1
                self.metrics["tasks_retried"] += 1
                requeue.append(tid)
                continue
            self.tasks.pop(tid, None)
            for oid in rec.spec.return_ids():
                self.store.put_error(oid, err)
                self._object_ready(oid)
            for c in rec.spec.contained_refs:
                self._decref_local(c)
        ar.in_flight.clear()
        if requeue:
            # Prepend in order: these predate anything already queued.
            ar.queued.extendleft(reversed(requeue))
        if can_restart:
            info.num_restarts += 1
            self.metrics["actor_restarts"] += 1
            self.events.emit(
                "WARNING", "actor", "actor restarting",
                actor_id=actor_id, restart=info.num_restarts,
            )
            self.state.set_actor_state(actor_id, RESTARTING)
            ar.worker_id = None
            # resubmit the creation task (restart FSM:
            # ray: gcs_actor_manager.h:258-266)
            import copy

            new_spec = copy.copy(creation)
            new_spec.task_id = ids.task_id()
            new_spec.attempt = 0
            ar.info.creation_spec = new_spec
            rec = TaskRecord(new_spec)
            rec.state = "READY"
            rec.stamp("queued")
            self.tasks[new_spec.task_id] = rec
            self.ready_queue.append(new_spec.task_id)
            self._dispatch()
        else:
            self.state.set_actor_state(actor_id, DEAD, death_cause="worker died")
            self._fail_actor_queue(ar, err)
            # The released placement may unblock queued work (e.g. a new
            # actor's creation parked on the resources this one held).
            self._dispatch()

    # ------------------------------------------------------------------
    # public API surface (driver side)

    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("ray_tpu.put() does not accept ObjectRefs")
        self.metrics["objects_put"] += 1
        oid = ids.object_id()
        contained = self.store.put(oid, value)
        size = self.store._in_shm.get(oid)
        if size:
            self.object_sizes[oid] = size  # locality scoring weight
        self._note_object(oid, "driver")
        self._obj_event(oid, "create", size)
        self._store_contained(oid, contained)
        self._object_ready(oid)
        return ObjectRef(oid)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        for r in refs:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"ray_tpu.get() takes ObjectRefs, got {type(r)}")
        oids = [r.id for r in refs]
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        # Flush-before-blocking-wait: task/kill frames this thread queued
        # (local-mode submits run on the caller's thread) must be on the
        # wire before we park on their results.
        _wire.flush_dirty()
        ready = self.store.wait(oids, len(oids), timeout)
        if len(ready) < len(oids):
            # Critical path: name the lifecycle stage each pending
            # producer is stuck in (the attribution plane's one-line
            # diagnosis for a blocked get).
            pending = [o for o in oids if o not in set(ready)]
            detail = self._blocked_get_detail(pending)
            raise GetTimeoutError(
                f"get timed out after {timeout}s"
                + (f"; critical path: {detail}" if detail else "")
            )
        values = [self._get_one_value(oid, deadline) for oid in oids]
        return values[0] if single else values

    def _get_one_value(self, oid: str, deadline: Optional[float]):
        """Fetch + deserialize one ready object; transparently reconstruct
        via lineage when its bytes are lost."""
        import time as _time

        for _ in range(3):  # bound cascading reconstructions per object
            err = self.store.error_for(oid)
            if err is not None:
                raise err
            obj = self.store.get_sealed(oid)
            if obj is None and self._fetch_remote(oid):
                obj = self.store.get_sealed(oid)
            if obj is not None:
                return obj.deserialize()
            with self.lock:
                if not self._reconstruct(oid):
                    raise ObjectLostError(oid)
            remaining = (
                None if deadline is None else max(deadline - _time.monotonic(), 0.0)
            )
            _wire.flush_dirty()  # the reconstruction dispatch just queued
            if not self.store.wait([oid], 1, remaining):
                raise GetTimeoutError(f"reconstruction of {oid} timed out")
        raise ObjectLostError(oid)

    def _fetch_remote(self, oid: str) -> bool:
        """Pull an object whose bytes live only on other nodes into the
        head store (driver-side consumption of remote results —
        ray: PullManager on the requesting raylet).  The sink's transfer
        board makes even this pull relay-servable to other nodes
        mid-flight (the head's listener serves its boards)."""
        from ray_tpu._private import object_plane

        eps = self._pull_endpoints(oid, exclude_head=True)
        if not eps:
            return False
        r = object_plane.pull_from_any(
            eps, self._authkey, oid, self.store.start_pull
        )
        return r is not None

    def wait_refs(self, refs, num_returns=1, timeout=None):
        oids = [r.id for r in refs]
        _wire.flush_dirty()  # same rule as get(): flush before parking
        ready_set = set(self.store.wait(oids, num_returns, timeout))
        ready, not_ready = [], []
        for r in refs:
            (ready if r.id in ready_set and len(ready) < num_returns else not_ready).append(r)
        return ready, not_ready

    def cancel(self, oid_or_ref, force: bool = False) -> None:
        oid = oid_or_ref.id if isinstance(oid_or_ref, ObjectRef) else oid_or_ref
        # object id "o:<task>:<i>" -> task id
        task_id = oid.split(":")[1] if oid.startswith("o:") else None
        if task_id is None:
            return
        with self.lock:
            rec = self.tasks.get(task_id)
            if rec is None:
                return
            rec.cancelled = True
            if rec.state in ("PENDING", "READY"):
                self.tasks.pop(task_id, None)
                for roid in rec.spec.return_ids():
                    self.store.put_error(roid, TaskCancelledError(rec.spec.name))
                    self._object_ready(roid)
            elif rec.state == "RUNNING" and force:
                h = self.workers.get(rec.worker_id)
                if h is not None:
                    self._send(h, ("kill",))
                    try:
                        h.proc.terminate()
                    except Exception:
                        pass

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        with self.lock:
            ar = self.actors.get(actor_id)
            if ar is None:
                return
            ar.expected_death = True
            ar.no_restart = ar.no_restart or no_restart
            h = self.workers.get(ar.worker_id) if ar.worker_id else None
        if h is not None:
            self._send(h, ("kill",))
            try:
                h.proc.terminate()
            except Exception:
                pass
        else:
            with self.lock:
                info = self.state.get_actor(actor_id)
                if info and info.state != DEAD:
                    self.state.set_actor_state(actor_id, DEAD, death_cause="killed")
                    self._fail_actor_queue(ar, ActorDiedError(actor_id))
                # Cancel the still-pending creation task, else its eventual
                # dispatch would resurrect the actor to ALIVE.
                for tid, rec in list(self.tasks.items()):
                    if (
                        rec.spec.is_actor_creation
                        and rec.spec.actor_id == actor_id
                        and rec.state in ("PENDING", "READY")
                    ):
                        rec.cancelled = True
                        self.tasks.pop(tid, None)
                        for oid in rec.spec.return_ids():
                            self.store.put_error(oid, ActorDiedError(actor_id))
                            self._object_ready(oid)
                        for c in rec.spec.contained_refs:
                            self._decref_local(c)

    # -- placement groups ----------------------------------------------------

    def create_placement_group(
        self, bundles, strategy, name=None, pg_id: Optional[str] = None
    ) -> PlacementGroupInfo:
        """pg_id may be CLIENT-minted so a request retried across a head
        bounce dedupes instead of creating (and leaking the reservations
        of) a second group."""
        with self.lock:
            if pg_id is not None and pg_id in self.state.placement_groups:
                return self.state.placement_groups[pg_id]
        pg = PlacementGroupInfo(
            pg_id=pg_id or ids.placement_group_id(),
            bundles=[{k: float(v) for k, v in b.items()} for b in bundles],
            strategy=strategy,
            name=name,
        )
        with self.lock:
            self.state.register_pg(pg)  # journaled (orig_bundles captured)
            if not self.scheduler.reserve_placement_group(pg):
                self.pending_pgs.append(pg.pg_id)
        return pg

    def remove_placement_group(self, pg_id: str) -> None:
        with self.lock:
            pg = self.state.placement_groups.get(pg_id)
            if pg is not None:
                self.scheduler.remove_placement_group(pg)
                if pg_id in self.pending_pgs:
                    self.pending_pgs.remove(pg_id)

    # -- elastic re-mesh (MESH gangs; SURVEY.md §7: one host's failure
    #    tears/reshapes the whole mesh, unlike independent-worker retry) --

    def pg_info(self, pg_id: str) -> Optional[dict]:
        """Gang introspection for elastic trainers: lifecycle state plus
        the reshape bookkeeping (generation, shrunk size, scale-up cue)."""
        with self.lock:
            pg = self.state.placement_groups.get(pg_id)
            if pg is None:
                return None
            return {
                "state": pg.state,
                "generation": pg.generation,
                "size": len(pg.bundles),
                "orig_size": len(pg.orig_bundles or pg.bundles),
                "bundle_nodes": dict(pg.bundle_nodes),
                "scale_up_ready": pg.scale_up_ready,
                "lost_node": pg.lost_node,
                # Monotonic stamp of the last RESHAPING entry (system-wide
                # clock on Linux): trainers subtract it from their own
                # monotonic "noticed" time to attribute the detect stage.
                "reshaping_since": pg.reshaping_since,
            }

    def _kill_gang_actors(self, pg_id: str) -> int:
        """Caller holds self.lock.  Kill every live actor scheduled inside
        the gang: SPMD collectives span all members, so the survivors of a
        torn mesh are dead weight pinning capacity the re-plan needs —
        and killing them gives the trainer one clean gang-wide
        ActorDiedError instead of a half-alive group."""
        killed = 0
        for aid, ar in list(self.actors.items()):
            placement = ar.placement
            if not placement or placement[0] != "pg" or placement[1] != pg_id:
                continue
            info = self.state.get_actor(aid)
            if info is None or info.state == DEAD:
                continue
            killed += 1
            self.kill_actor(aid, no_restart=True)
        return killed

    def _withdraw_mesh_gangs(self, node_id: str) -> None:
        """Caller holds self.lock.  Node loss: every CREATED MESH gang the
        dead host was a member of is withdrawn as a whole — surviving
        reservations released, gang actors killed — and enters a journaled
        RESHAPING episode.  The io-loop sweep then waits for a replacement
        host up to remesh_wait_s before re-planning a smaller box."""
        from ray_tpu._private import config as _config

        for pg in list(self.state.placement_groups.values()):
            if pg.strategy != "MESH" or pg.state != "CREATED":
                continue
            if node_id not in pg.bundle_nodes.values():
                continue
            if not self.scheduler.withdraw_gang(pg, node_id):
                continue
            wait_s = float(_config.get("remesh_wait_s"))
            self.state.set_pg_state(
                pg.pg_id, "RESHAPING",
                lost_node=node_id, scale_up_ready=False,
                reshape_deadline=time.monotonic() + wait_s,
                reshaping_since=time.monotonic(),
            )
            killed = self._kill_gang_actors(pg.pg_id)
            self.events.emit(
                "WARNING", "pg",
                "MESH gang lost a member host: gang withdrawn, RESHAPING",
                pg_id=pg.pg_id, lost_node=node_id, size=len(pg.bundles),
                actors_killed=killed, wait_s=wait_s,
            )

    def _sweep_reshaping_pgs(self, now: float) -> None:
        """Advance elastic re-mesh episodes (io-loop 0.5s tick).

        Runs OFF the runtime lock: the mesh.member_death / pg.reshape
        fault points below are delay/crash-capable, and every mutation
        step below re-takes the lock and re-checks state first — a racing
        remove_placement_group wins, the sweep never resurrects it.
        """
        from ray_tpu._private import config as _config

        with self.lock:
            reshaping = [
                pg for pg in self.state.placement_groups.values()
                if pg.state == "RESHAPING"
            ]
            shrunk = [
                pg for pg in self.state.placement_groups.values()
                if (
                    pg.state == "CREATED"
                    and pg.strategy == "MESH"
                    and pg.orig_bundles
                    and len(pg.bundles) < len(pg.orig_bundles)
                    and not pg.scale_up_ready
                )
            ]
        for pg in reshaping:
            if faults.ENABLED:
                if pg.pg_id not in self._remesh_announced:
                    self._remesh_announced.add(pg.pg_id)
                    faults.point("mesh.member_death", key=pg.pg_id)
                deadline = pg.reshape_deadline
                faults.point(
                    "pg.reshape",
                    key="shrink"
                    if deadline is not None and now >= deadline
                    else "wait",
                )
            with self.lock:
                if pg.state != "RESHAPING":
                    continue
                if pg.reshape_deadline is None:
                    # Restored mid-episode after a head bounce: the wait
                    # deadline is head-local, re-arm a fresh window.
                    pg.reshape_deadline = now + float(
                        _config.get("remesh_wait_s")
                    )
                # Full size first — a replacement host may have joined.
                ok = self.scheduler.reserve_placement_group(pg)
                did_shrink = False
                if not ok and now >= pg.reshape_deadline and len(pg.bundles) > 1:
                    # Wait window expired: shrink the box by one host
                    # (journaled) and re-plan, demand-revoking idle leases
                    # when fragmentation blocks the smaller box.  Another
                    # window must elapse before shrinking further.
                    self.state.set_pg_state(
                        pg.pg_id, "RESHAPING",
                        bundles=[dict(b) for b in pg.bundles[:-1]],
                        reshape_deadline=now
                        + float(_config.get("remesh_wait_s")),
                    )
                    did_shrink = True
                    ok = self.scheduler.reserve_placement_group(pg)
                    while not ok and self._revoke_one_idle_lease():
                        ok = self.scheduler.reserve_placement_group(pg)
                if ok:
                    self._remesh_announced.discard(pg.pg_id)
                    self.events.emit(
                        "INFO", "pg",
                        "MESH gang re-meshed"
                        + (" at reduced size" if did_shrink else ""),
                        pg_id=pg.pg_id, size=len(pg.bundles),
                        orig_size=len(pg.orig_bundles or pg.bundles),
                        generation=pg.generation,
                    )
                    self._dispatch()
        for pg in shrunk:
            if self.scheduler.can_plan_full(pg):
                with self.lock:
                    if pg.state == "CREATED" and not pg.scale_up_ready:
                        self.state.set_pg_state(
                            pg.pg_id, "CREATED", scale_up_ready=True
                        )
                        self.events.emit(
                            "INFO", "pg",
                            "MESH gang can scale back to full size",
                            pg_id=pg.pg_id, size=len(pg.bundles),
                            orig_size=len(pg.orig_bundles),
                        )

    def pg_reshape(self, pg_id: str) -> bool:
        """Trainer-initiated scale-up of a shrunk MESH gang back to its
        original size: kill the gang, withdraw its reservations, and
        re-enter RESHAPING at full size.  The reservation is attempted
        inline (and by every sweep tick after); the caller polls pg_info
        until generation advances."""
        if faults.ENABLED:
            faults.point("pg.reshape", key="expand")
        from ray_tpu._private import config as _config

        with self.lock:
            pg = self.state.placement_groups.get(pg_id)
            if (
                pg is None
                or pg.state != "CREATED"
                or not pg.orig_bundles
                or len(pg.bundles) >= len(pg.orig_bundles)
            ):
                return False
            self._kill_gang_actors(pg_id)
            self.scheduler.withdraw_gang(pg, dead_node="")
            self.state.set_pg_state(
                pg_id, "RESHAPING",
                bundles=[dict(b) for b in pg.orig_bundles],
                lost_node=None, scale_up_ready=False,
                reshape_deadline=time.monotonic()
                + float(_config.get("remesh_wait_s")),
                reshaping_since=time.monotonic(),
            )
            self.events.emit(
                "INFO", "pg", "MESH gang scale-up: RESHAPING to full size",
                pg_id=pg_id, size=len(pg.bundles),
            )
            if self.scheduler.reserve_placement_group(pg):
                self._remesh_announced.discard(pg_id)
                self._dispatch()
        return True

    # -- cluster info --------------------------------------------------------

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.state.alive_nodes():
            for k, v in n.resources.items():
                out[k] = out.get(k, 0.0) + v
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.state.alive_nodes():
            for k, v in n.available.items():
                out[k] = out.get(k, 0.0) + v
        return out

    # -- virtual nodes (test fixture: ray: python/ray/cluster_utils.py:99) ---

    def add_node(
        self,
        num_cpus: float = 1.0,
        resources: Optional[Dict] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> str:
        res = {"CPU": float(num_cpus), **(resources or {})}
        nid = ids.node_id()
        self.state.register_node(
            NodeInfo(nid, dict(res), dict(res), labels=dict(labels or {}))
        )
        with self.lock:
            self._dispatch()
        return nid

    def remove_node(self, node_id: str) -> None:
        with self.lock:
            # Planned removal (autoscaler downscale / Cluster API): the
            # ensuing daemon/worker EOFs must log as routine, not failures.
            self._expected_node_removals.add(node_id)
            self.state.remove_node(node_id)
            victims = [h for h in self.workers.values() if h.node_id == node_id]
            self._expected_worker_stops.update(h.worker_id for h in victims)
            if node_id not in self.node_daemons:
                # In-process node (no daemon conn whose EOF would emit the
                # event later) — record the removal now and don't leak the
                # expectation entry.
                self._expected_node_removals.discard(node_id)
                self.events.emit("INFO", "node", "node removed", node_id=node_id)
                if node_id in self.node_lifecycle:
                    self._set_node_lifecycle(
                        node_id, "DEPARTED", reason="removed"
                    )
            self._daemon_send(node_id, ("shutdown",))
            self.node_daemons.pop(node_id, None)
            # Planned or not, a MESH gang member leaving tears the gang.
            self._withdraw_mesh_gangs(node_id)
        for h in victims:
            try:
                h.proc.terminate()
            except Exception:
                pass
        # crash handling happens via conn EOF in the io loop

    # ------------------------------------------------------------------
    # elastic capacity: the loss-proof drain protocol.  DRAINING stops new
    # leases landing (scheduler filters + lease drain-revokes), the
    # reconciler waits for running tasks, sole-copy objects evacuate over
    # the PR-10 transfer plane, and only then does the daemon depart.  A
    # node that dies MID-DRAIN falls into _on_daemon_death unchanged —
    # lineage/retry covers whatever evacuation had not yet moved.

    def start_node_drain(self, node_id: str) -> bool:
        """Enter DRAINING: journaled lifecycle flip + the volatile
        NodeInfo.draining mark, idle leases on the node drain-revoked,
        parked same-key tasks re-driven elsewhere.  Idempotent."""
        if faults.ENABLED:
            faults.point("node.drain", key=node_id)
        with self.lock:
            node = self.state.nodes.get(node_id)
            if (
                node is None
                or not node.alive
                or node.is_head
                or node_id == self.head_node_id
            ):
                return False
            if not node.draining:
                self.state.set_node_draining(node_id, True)
                self._set_node_lifecycle(node_id, "DRAINING")
                for pool in list(self.task_leases.values()):
                    for le in list(pool):
                        if (
                            le.node_id == node_id
                            and le.idle_since is not None
                        ):
                            self._revoke_lease_locked(le, cause="drain")
                self._dispatch()
        return True

    def node_busy_count(self, node_id: str) -> int:
        """Workers on node_id still holding work: running/pushed tasks
        plus resident actors.  0 = quiesced (safe to evacuate+depart)."""
        with self.lock:
            busy = 0
            for h in self.workers.values():
                if h.node_id != node_id or h.state == "dead":
                    continue
                if h.current_task is not None or h.state == "actor":
                    busy += 1
            return busy

    def sole_copy_objects(self, node_id: str) -> List[str]:
        """Objects whose ONLY sealed copy lives on node_id (no head-store
        copy, no other node in the directory) — the bytes a depart would
        lose without evacuation."""
        with self.lock:
            return [
                oid
                for oid, locs in self.object_locations.items()
                if locs == {node_id} and not self.store.has_local(oid)
            ]

    def evacuate_node_objects(
        self, node_id: str, deadline: Optional[float] = None
    ) -> dict:
        """Pull every sole-copy object off node_id into the head store
        over the transfer plane (the head is a surviving node; its store
        re-serves the bytes to any later consumer).  Runs OFF the runtime
        lock — each pull is a network transfer.  Returns the evacuation
        ledger; `remaining` > 0 means bytes were NOT saved (deadline hit
        or the node died under us) and the caller decides whether to
        depart anyway (lineage then covers the loss)."""
        moved = failed = 0
        moved_bytes = 0
        for oid in self.sole_copy_objects(node_id):
            if deadline is not None and time.monotonic() > deadline:
                break
            if faults.ENABLED:
                faults.point("node.evacuate", key=oid)
            ok = False
            try:
                ok = self._fetch_remote(oid)
            except Exception:
                ok = False
            if ok and self.store.has_local(oid):
                moved += 1
                moved_bytes += self.object_sizes.get(oid, 0)
            else:
                failed += 1
        remaining = len(self.sole_copy_objects(node_id))
        if moved or failed or remaining:
            self.events.emit(
                "INFO" if remaining == 0 else "WARNING",
                "autoscale", "node evacuation",
                node_id=node_id, moved=moved, moved_bytes=moved_bytes,
                failed=failed, remaining=remaining,
            )
        return {
            "moved": moved,
            "moved_bytes": moved_bytes,
            "failed": failed,
            "remaining": remaining,
        }

    def depart_node(self, node_id: str) -> None:
        """Final drain step: planned removal (remove_node) + the terminal
        DEPARTED lifecycle record.  Workers still running tasks here die
        as EXPECTED stops — their in-flight tasks re-drive on their retry
        budget, same as any worker death."""
        if faults.ENABLED:
            faults.point("node.depart", key=node_id)
        self.remove_node(node_id)
        with self.lock:
            if node_id in self.node_lifecycle:
                self._set_node_lifecycle(
                    node_id, "DEPARTED", reason="removed"
                )

    # ------------------------------------------------------------------

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        atexit.unregister(self.shutdown)
        set_ref_hooks(None, None)
        if self._autoscaler is not None:
            try:
                self._autoscaler.stop()
            except Exception:
                pass
        if getattr(self, "_snapshot_storage", None) is not None:
            self._snapshot_storage.close()
        if getattr(self, "_journal", None) is not None:
            self._journal.close()
        if getattr(self, "_ready_spill", None) is not None:
            self._ready_spill.close()
        if getattr(self, "_mem_monitor", None) is not None:
            self._mem_monitor.stop()
        # Final log drain: crash output written moments ago must reach the
        # ring buffers/stdout before the session dies.
        try:
            self._log_monitor.flush()
            self._log_monitor.stop()
        except Exception:
            pass
        try:
            if _wire.stats_enabled():
                # Final per-process counters into the event log (workers'
                # snapshots were folded in live via their wire_stats
                # reports — see _handle_msg).
                self.events.emit(
                    "INFO", "wire", "head wire stats", **_wire.stats()
                )
            self.events.emit("INFO", "runtime", "session shutting down")
            self.events.close()
        except Exception:
            pass
        for nid in list(self.node_daemons):
            self._daemon_send(nid, ("shutdown",))
        for proc in self._daemon_procs.values():
            try:
                proc.terminate()
            except OSError:
                pass
        if self._zygote_proc is not None:
            try:
                self._zygote_proc.terminate()
            except OSError:
                pass
        for h in list(self.workers.values()):
            try:
                if h.conn is not None:
                    h.conn.send(("kill",))
            except OSError:
                pass
            try:
                h.proc.terminate()
            except Exception:
                pass
        # The kill/shutdown frames above are queued on batching conns:
        # push them out before the fds die with the process.  Sharded
        # worker kills ride each shard's ctl channel; the trailing
        # shutdown frame (same FIFO stream) makes the shard deliver them
        # before exiting.
        _wire.flush_dirty()
        for sh in getattr(self, "_io_shards", {}).values():
            try:
                if sh.ctl_conn is not None:
                    sh.ctl_conn.send(("shutdown",))
                    sh.ctl_conn.flush()
            except (OSError, ValueError):
                pass
            try:
                sh.proc.terminate()
            except OSError:
                pass
        if getattr(self, "_shard_listener", None) is not None:
            try:
                self._shard_listener.close()
            except OSError:
                pass
        try:
            self.listener.close()
        except OSError:
            pass
        deadline = time.monotonic() + 2.0
        for h in list(self.workers.values()):
            remaining = max(0.0, deadline - time.monotonic())
            try:
                h.proc.join(remaining)
            except Exception:
                pass
        self.store.destroy()
        global _runtime
        _runtime = None


_PARKED = object()
_runtime: Optional[Runtime] = None


def get_runtime() -> Runtime:
    if _runtime is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _runtime


def is_initialized() -> bool:
    return _runtime is not None


def init_runtime(**kwargs) -> Runtime:
    global _runtime
    if _runtime is not None:
        return _runtime
    _runtime = Runtime(**kwargs)
    return _runtime


def shutdown_runtime() -> None:
    global _runtime
    if _runtime is not None:
        rt = _runtime
        _runtime = None
        rt.shutdown()
