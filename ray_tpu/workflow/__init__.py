"""ray_tpu.workflow — durable workflows: DAGs with persisted step results.

ray: python/ray/workflow/ (api.py:120 run, :232 resume, :297 get_output;
workflow_storage.py; workflow_state_from_storage.py).  Every step's result
is written to storage before the workflow advances; resume() replays the
DAG, skipping steps whose results are already durable — so a crashed
driver (or machine) continues where it left off instead of recomputing.

Storage is a filesystem directory (workflow_dir/<workflow_id>/<step>.pkl
+ status files); steps are content-addressed by their position in the DAG.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode

RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"

_storage_dir: Optional[str] = None


def init(storage: Optional[str] = None) -> None:
    """Set the durable storage root (default: $TMPDIR/ray_tpu_workflows).

    Steps persist their results INTO this directory from the workers that
    run them, so on a multi-node cluster it must be a filesystem every node
    can write (NFS / GCS-fuse / Filestore) — the same shared-storage
    contract the reference imposes (ray: workflow requires a storage URL
    reachable from all nodes).  The single-host default is only durable
    against driver restarts on that host."""
    global _storage_dir
    _storage_dir = storage or os.path.join(
        tempfile.gettempdir(), "ray_tpu_workflows"
    )
    os.makedirs(_storage_dir, exist_ok=True)


def _storage() -> str:
    if _storage_dir is None:
        init()
    return _storage_dir


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _step_key(node: DAGNode, order: List[DAGNode]) -> str:
    """Stable step id: function name + position among same-named steps in
    topological order (deterministic across replays of the same DAG)."""
    idx = sum(
        1
        for other in order[: order.index(node)]
        if other._fn.__name__ == node._fn.__name__
    )
    return f"{node._fn.__name__}-{idx}"


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + f".tmp-{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


@ray_tpu.remote
def _run_step(wf_dir: str, key: str, fn_blob: bytes, args, kwargs):
    """Execute one step remotely, persisting the result BEFORE returning —
    the durability point (ray: workflow_storage commit-before-advance)."""
    import cloudpickle

    fn = cloudpickle.loads(fn_blob)

    # Upstream step results arrive as refs nested anywhere in the args
    # (only top-level task args auto-resolve): fetch them worker-side,
    # descending containers the same way the DAG substitution does.
    def resolve(value):
        if isinstance(value, ray_tpu.ObjectRef):
            return ray_tpu.get(value)
        if isinstance(value, list):
            return [resolve(v) for v in value]
        if isinstance(value, tuple):
            return tuple(resolve(v) for v in value)
        if isinstance(value, set):
            return {resolve(v) for v in value}
        if isinstance(value, dict):
            return {k: resolve(v) for k, v in value.items()}
        return value

    args = [resolve(a) for a in args]
    kwargs = {k: resolve(v) for k, v in kwargs.items()}
    out = fn(*args, **kwargs)
    _atomic_write(os.path.join(wf_dir, f"{key}.pkl"), pickle.dumps(out))
    return out


def run(
    dag: DAGNode,
    *,
    workflow_id: Optional[str] = None,
) -> Any:
    """Run a DAG durably; returns the final result (ray: workflow.run)."""
    return ray_tpu.get(run_async(dag, workflow_id=workflow_id), timeout=None)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None):
    """Submit a durable DAG; returns the final step's ObjectRef."""
    import cloudpickle
    import uuid

    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:10]}"
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    # Persist the DAG itself so resume() can replay it without user code.
    _atomic_write(os.path.join(wf_dir, "dag.pkl"), cloudpickle.dumps(dag))
    _atomic_write(os.path.join(wf_dir, "status"), RUNNING.encode())

    ref = _submit_dag(workflow_id, dag)

    # Completion marker: a tiny chained step flips status when the root
    # result lands (no driver thread needed; survives via resume if not).
    @ray_tpu.remote
    def _finalize(result, wf_dir=wf_dir):
        _atomic_write(os.path.join(wf_dir, "status"), SUCCEEDED.encode())
        return result

    out = _finalize.remote(ref)

    # A failed step never reaches _finalize (dep-error propagation), so a
    # watcher flips the durable status to FAILED when the root ref errors.
    import threading

    def _watch():
        try:
            ray_tpu.get(out, timeout=None)
        except Exception:
            try:
                _atomic_write(os.path.join(wf_dir, "status"), FAILED.encode())
            except OSError:
                pass

    threading.Thread(target=_watch, daemon=True, name="wf-watch").start()
    return out


def _submit_dag(workflow_id: str, dag: DAGNode):
    import cloudpickle

    wf_dir = _wf_dir(workflow_id)
    order = dag.topological_order()
    results: Dict[int, Any] = {}
    for node in order:
        key = _step_key(node, order)
        done_path = os.path.join(wf_dir, f"{key}.pkl")
        if os.path.exists(done_path):
            # Durable result exists: skip re-execution (resume semantics).
            with open(done_path, "rb") as f:
                results[id(node)] = ray_tpu.put(pickle.load(f))
            continue
        def subst(value):
            if isinstance(value, DAGNode):
                return results[id(value)]
            if isinstance(value, list):
                return [subst(v) for v in value]
            if isinstance(value, tuple):
                return tuple(subst(v) for v in value)
            if isinstance(value, set):
                return {subst(v) for v in value}
            if isinstance(value, dict):
                return {k: subst(v) for k, v in value.items()}
            return value

        args = [subst(a) for a in node._args]
        kwargs = {k: subst(v) for k, v in node._kwargs.items()}
        fn_blob = cloudpickle.dumps(node._fn._fn)
        results[id(node)] = _run_step.options(
            name=f"wf:{workflow_id}:{key}"
        ).remote(wf_dir, key, fn_blob, args, kwargs)
    return results[id(dag)]


def resume(workflow_id: str) -> Any:
    """Resume after a crash: completed steps load from storage, the rest
    re-execute (ray: workflow.resume :232)."""
    import cloudpickle

    wf_dir = _wf_dir(workflow_id)
    with open(os.path.join(wf_dir, "dag.pkl"), "rb") as f:
        dag = cloudpickle.load(f)
    ref = _submit_dag(workflow_id, dag)
    try:
        out = ray_tpu.get(ref, timeout=None)
    except Exception:
        _atomic_write(os.path.join(wf_dir, "status"), FAILED.encode())
        raise
    _atomic_write(os.path.join(wf_dir, "status"), SUCCEEDED.encode())
    return out


def get_status(workflow_id: str) -> str:
    try:
        with open(os.path.join(_wf_dir(workflow_id), "status"), "rb") as f:
            return f.read().decode()
    except FileNotFoundError:
        raise ValueError(f"no workflow {workflow_id!r}")


def get_output(workflow_id: str) -> Any:
    """Final result of a SUCCEEDED workflow (from durable storage)."""
    if get_status(workflow_id) != SUCCEEDED:
        raise ValueError(f"workflow {workflow_id} is {get_status(workflow_id)}")
    return resume(workflow_id)  # all steps durable: pure storage replay


def list_all() -> List[Dict[str, str]]:
    root = _storage()
    out = []
    for wid in sorted(os.listdir(root)):
        try:
            out.append({"workflow_id": wid, "status": get_status(wid)})
        except ValueError:
            continue
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
