"""ctypes binding + on-demand build for the C++ shm arena.

The .so is compiled once per source-hash into ~/.cache/ray_tpu_native (or
RAY_TPU_NATIVE_CACHE) and shared by every process of every session.  All
data movement stays in Python via ONE mmap of the arena file — the C++
side only does metadata (allocation + object table) under the
process-shared mutex.
"""

from __future__ import annotations

import ctypes
import hashlib
import mmap
import os
import subprocess
import threading
from typing import Optional

_build_lock = threading.Lock()
_lib = None
_lib_failed = False

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "shm_arena.cpp")


def _cache_dir() -> str:
    return os.environ.get(
        "RAY_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "ray_tpu_native"),
    )


def load_native() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native library; None when unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            with open(_SRC, "rb") as f:
                src = f.read()
            tag = hashlib.sha1(src).hexdigest()[:16]
            out_dir = _cache_dir()
            os.makedirs(out_dir, exist_ok=True)
            so_path = os.path.join(out_dir, f"shm_arena-{tag}.so")
            if not os.path.exists(so_path):
                tmp = so_path + f".tmp-{os.getpid()}"
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp, "-lpthread"],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, so_path)  # atomic: racing builders converge
            lib = ctypes.CDLL(so_path)
            lib.arena_init.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
            lib.arena_init.restype = ctypes.c_int
            lib.arena_open.argtypes = [ctypes.c_char_p]
            lib.arena_open.restype = ctypes.c_void_p
            lib.arena_close.argtypes = [ctypes.c_void_p]
            lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
            lib.arena_alloc.restype = ctypes.c_int64
            lib.arena_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.arena_seal.restype = ctypes.c_int
            lib.arena_lookup.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.arena_lookup.restype = ctypes.c_int64
            lib.arena_acquire.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.arena_acquire.restype = ctypes.c_int64
            lib.arena_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.arena_release.restype = ctypes.c_int
            lib.arena_state.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.arena_state.restype = ctypes.c_int
            lib.arena_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.arena_delete.restype = ctypes.c_int
            lib.arena_used.argtypes = [ctypes.c_void_p]
            lib.arena_used.restype = ctypes.c_uint64
            lib.arena_capacity.argtypes = [ctypes.c_void_p]
            lib.arena_capacity.restype = ctypes.c_uint64
            _lib = lib
        except Exception:
            _lib_failed = True
            _lib = None
    return _lib


class PinnedView:
    """Zero-copy view of a sealed object that PINS its bytes for its own
    lifetime (plasma's client-hold semantics): the arena will not reuse the
    memory until this object is garbage-collected, even if the object is
    deleted meanwhile (deferred free).  The view is READ-ONLY: sealed
    objects are immutable, and a reader scribbling into the shared mapping
    would corrupt every other holder of the object (same contract as the
    file backend's PROT_READ mmaps)."""

    __slots__ = ("view", "_finalizer", "__weakref__")

    def __init__(self, arena: "Arena", object_id: str, view: memoryview):
        self.view = view.toreadonly()
        import weakref

        self._finalizer = weakref.finalize(
            self, Arena._release_pin, arena, object_id
        )

    def __bytes__(self) -> bytes:
        return bytes(self.view)

    def __len__(self) -> int:
        return len(self.view)


class Arena:
    """One process's view of the session arena."""

    ID_MAX = 47

    @staticmethod
    def _release_pin(arena: "Arena", object_id: str) -> None:
        if not arena._closed:
            arena._lib.arena_release(arena._h, object_id.encode())

    def __init__(
        self, path: str, capacity: Optional[int] = None, fd: Optional[int] = None
    ):
        """Open (or create, when capacity is given) the arena at `path`.

        fd: join via an inherited/SCM_RIGHTS-passed file descriptor of the
        arena file instead of opening the path — the daemon hands its
        workers the open fd over the existing AF_UNIX channels (netutil
        send_fd/recv_fd), so a worker maps the store even when the path
        itself is not resolvable from its mount/permission view.  The fd
        is duplicated; the caller keeps ownership of its copy.
        """
        lib = load_native()
        if lib is None:
            raise RuntimeError("native arena unavailable (no g++ / build failed)")
        self._lib = lib
        self.path = path
        if fd is not None:
            # /proc/self/fd/N resolves the passed descriptor to the same
            # inode for the C++ side's own open(); the Python mapping
            # comes straight off the duplicated fd.
            dup = os.dup(fd)
            try:
                self._h = lib.arena_open(f"/proc/self/fd/{dup}".encode())
                if not self._h:
                    raise RuntimeError(f"arena_open via fd failed for {path}")
                self._mm = mmap.mmap(dup, 0)
            finally:
                os.close(dup)
            self._closed = False
            return
        if capacity is not None and not os.path.exists(path):
            if lib.arena_init(path.encode(), capacity) != 0 and not os.path.exists(path):
                raise RuntimeError(f"arena_init failed for {path}")
        self._h = lib.arena_open(path.encode())
        if not self._h:
            raise RuntimeError(f"arena_open failed for {path}")
        f = open(path, "r+b")
        try:
            self._mm = mmap.mmap(f.fileno(), 0)
        finally:
            f.close()
        self._closed = False

    # -- object ops -------------------------------------------------------
    def _check_id(self, object_id: str) -> bytes:
        b = object_id.encode()
        if len(b) > self.ID_MAX:
            # C-side ids are fixed-width; silently truncating would let
            # distinct ids collide.
            raise ValueError(f"object id longer than {self.ID_MAX} bytes: {object_id!r}")
        return b

    def create(self, object_id: str, data) -> None:
        """Allocate + copy + seal in one call (data: bytes-like)."""
        bid = self._check_id(object_id)
        view = memoryview(data).cast("B")
        off = self._lib.arena_alloc(self._h, bid, len(view))
        if off == -2:
            raise FileExistsError(object_id)
        if off == -3:
            raise RuntimeError("arena poisoned")
        if off < 0:
            raise MemoryError(
                f"arena full: need {len(view)}, used {self.used()} of {self.capacity()}"
            )
        self._mm[off : off + len(view)] = view
        if self._lib.arena_seal(self._h, bid) != 0:
            raise RuntimeError(f"seal failed for {object_id}")

    def allocate(self, object_id: str, size: int) -> memoryview:
        """Two-phase create: returns a writable view; call seal() after."""
        return self.allocate_at(object_id, size)[0]

    def allocate_at(self, object_id: str, size: int):
        """allocate() plus the slot's heap offset: (view, offset).  The
        transfer plane's pull board publishes the offset so the node's
        OTHER processes (the serving daemon) can relay the landed prefix
        of an in-flight pull straight out of this pending slot."""
        bid = self._check_id(object_id)
        off = self._lib.arena_alloc(self._h, bid, size)
        if off == -2:
            raise FileExistsError(object_id)
        if off == -3:
            raise RuntimeError("arena poisoned")
        if off < 0:
            raise MemoryError(f"arena full: need {size}")
        return memoryview(self._mm)[off : off + size], int(off)

    def peek(self, offset: int, size: int) -> memoryview:
        """READ-ONLY raw slice of the heap at (offset, size) — the relay
        server's view into a pending pull slot published via a transfer
        board.  Unpinned by design: the board protocol guarantees the
        slot stays allocated while the board file exists, and every
        relayed chunk carries a crc so a torn read is detected, never
        propagated."""
        return memoryview(self._mm)[offset : offset + size].toreadonly()

    def seal(self, object_id: str) -> None:
        if self._lib.arena_seal(self._h, self._check_id(object_id)) != 0:
            raise RuntimeError(f"seal failed for {object_id}")

    def get(self, object_id: str) -> Optional[PinnedView]:
        """Zero-copy PINNED view of a sealed object, or None.  The bytes
        stay valid for the PinnedView's lifetime even across delete."""
        bid = self._check_id(object_id)
        size = ctypes.c_uint64()
        off = self._lib.arena_acquire(self._h, bid, ctypes.byref(size))
        if off < 0:
            return None
        view = memoryview(self._mm)[off : off + size.value]
        return PinnedView(self, object_id, view)

    def contains(self, object_id: str) -> bool:
        size = ctypes.c_uint64()
        return (
            self._lib.arena_lookup(
                self._h, self._check_id(object_id), ctypes.byref(size)
            )
            >= 0
        )

    def is_pending(self, object_id: str) -> bool:
        """True when the id is taken but not sealed (creator may have died
        mid-write) — callers can delete + retry."""
        return self._lib.arena_state(self._h, self._check_id(object_id)) == 1

    def delete(self, object_id: str) -> bool:
        return self._lib.arena_delete(self._h, object_id.encode()) == 0

    def used(self) -> int:
        return self._lib.arena_used(self._h)

    def capacity(self) -> int:
        return self._lib.arena_capacity(self._h)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
        except BufferError:
            pass  # outstanding views keep the map alive until GC
        self._lib.arena_close(self._h)

    def destroy(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass
