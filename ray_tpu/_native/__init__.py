"""Native (C++) components.

SURVEY §2.1: the reference's hot runtime paths are C++; this package holds
the TPU-native equivalents.  Current components:

- shm_arena.cpp — the object-store core (plasma-core analogue,
  ray: src/ray/object_manager/plasma/store.h:55): a process-shared mmap
  arena with a mutex-protected first-fit allocator + open-addressed object
  table.  Readers in every process slice objects out of ONE mapping
  (zero per-object open/mmap syscalls).  Python binding: arena.py (ctypes).

Build happens on demand with g++ into a per-user cache; every consumer
falls back to the pure-Python implementation when the toolchain or
platform is unavailable, so the native layer is an accelerator, never a
hard dependency.
"""

from ray_tpu._native.arena import Arena, load_native  # noqa: F401
