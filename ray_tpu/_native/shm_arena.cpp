// shm_arena: process-shared object arena — the native core of the object
// store (plasma-core analogue; ray: src/ray/object_manager/plasma/store.h:55,
// plasma_allocator.h:44, eviction metadata lives Python-side).
//
// One mmap'd file per session holds:
//   [Header | object table (open addressing) | data heap]
// All mutation is under a pthread process-shared mutex in the header; the
// allocator is first-fit over an offset-sorted free list with coalescing.
// Readers in ANY process (driver or workers) mmap the same file once and
// slice objects out of it zero-copy — no per-object open/mmap syscalls,
// which is what the Python file-per-object store pays on every access.
//
// C ABI (ctypes-friendly); all functions return <0 on error:
//   -1 not found / no space   -2 already exists   -3 bad state

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t MAGIC = 0x52544055534852ULL;  // "RT@USHR"
constexpr uint32_t N_SLOTS = 1 << 16;            // object table capacity
constexpr uint32_t ID_MAX = 48;                  // max object-id length
constexpr uint64_t ALIGN = 64;

enum SlotState : uint32_t {
  SLOT_FREE = 0,
  SLOT_PENDING = 1,
  SLOT_SEALED = 2,
  SLOT_TOMBSTONE = 3,  // deleted; probe chains continue through it
  SLOT_DOOMED = 4,     // deleted while pinned; freed at last release
};

struct Slot {
  uint64_t hash;
  uint32_t state;
  uint32_t id_len;
  char id[ID_MAX];
  uint64_t offset;  // data offset from arena base
  uint64_t size;
  // Readers holding zero-copy views pin the slot (plasma's client-hold
  // semantics: pinned bytes are never reused — the file backend got this
  // for free from per-reader mmaps surviving unlink).
  uint32_t pins;
  uint32_t _pad;
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

constexpr uint32_t FREELIST_MAX = 4096;

struct Header {
  uint64_t magic;
  uint64_t capacity;     // total file size
  uint64_t heap_start;   // first data byte
  uint64_t bump;         // never-allocated frontier
  uint64_t used_bytes;   // live (pending+sealed) payload bytes
  uint32_t poisoned;     // a lock owner died mid-mutation: fail everything
  uint32_t _pad;
  pthread_mutex_t mu;    // process-shared
  uint32_t n_free;
  FreeBlock freelist[FREELIST_MAX];  // offset-sorted
  Slot slots[N_SLOTS];
};

uint64_t fnv1a(const char* s, uint32_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < len; i++) {
    h ^= (unsigned char)s[i];
    h *= 1099511628211ULL;
  }
  return h ? h : 1;
}

struct Handle {
  Header* hdr;
  uint64_t mapped;
  int fd;
};

uint64_t align_up(uint64_t x) { return (x + ALIGN - 1) & ~(ALIGN - 1); }

// Find the slot for id, or the first insertable slot when insert=true.
Slot* find_slot(Header* h, const char* id, uint32_t id_len, bool insert) {
  uint64_t hash = fnv1a(id, id_len);
  uint32_t idx = (uint32_t)(hash & (N_SLOTS - 1));
  Slot* first_insertable = nullptr;
  for (uint32_t probe = 0; probe < N_SLOTS; probe++) {
    Slot* s = &h->slots[(idx + probe) & (N_SLOTS - 1)];
    if (s->state == SLOT_FREE) {
      if (insert && first_insertable == nullptr) first_insertable = s;
      return insert ? first_insertable : nullptr;
    }
    if (s->state == SLOT_TOMBSTONE) {
      if (insert && first_insertable == nullptr) first_insertable = s;
      continue;
    }
    if (s->hash == hash && s->id_len == id_len &&
        memcmp(s->id, id, id_len) == 0) {
      return s;  // existing entry (caller checks state)
    }
  }
  return insert ? first_insertable : nullptr;
}

// First-fit allocate; splits blocks; falls back to the bump frontier.
int64_t alloc_bytes(Header* h, uint64_t size) {
  size = align_up(size);
  for (uint32_t i = 0; i < h->n_free; i++) {
    if (h->freelist[i].size >= size) {
      uint64_t off = h->freelist[i].offset;
      h->freelist[i].offset += size;
      h->freelist[i].size -= size;
      if (h->freelist[i].size == 0) {
        memmove(&h->freelist[i], &h->freelist[i + 1],
                (h->n_free - i - 1) * sizeof(FreeBlock));
        h->n_free--;
      }
      return (int64_t)off;
    }
  }
  if (h->bump + size <= h->capacity) {
    uint64_t off = h->bump;
    h->bump += size;
    return (int64_t)off;
  }
  return -1;
}

// Insert [offset,size) into the offset-sorted free list, coalescing.
void free_bytes(Header* h, uint64_t offset, uint64_t size) {
  size = align_up(size);
  // Frontier give-back: block touching the bump pointer shrinks it.
  if (offset + size == h->bump) {
    h->bump = offset;
    // absorb a trailing free block that now touches the frontier
    while (h->n_free > 0) {
      FreeBlock* last = &h->freelist[h->n_free - 1];
      if (last->offset + last->size == h->bump) {
        h->bump = last->offset;
        h->n_free--;
      } else {
        break;
      }
    }
    return;
  }
  uint32_t i = 0;
  while (i < h->n_free && h->freelist[i].offset < offset) i++;
  // coalesce with predecessor
  if (i > 0 && h->freelist[i - 1].offset + h->freelist[i - 1].size == offset) {
    h->freelist[i - 1].size += size;
    // and with successor
    if (i < h->n_free &&
        h->freelist[i - 1].offset + h->freelist[i - 1].size ==
            h->freelist[i].offset) {
      h->freelist[i - 1].size += h->freelist[i].size;
      memmove(&h->freelist[i], &h->freelist[i + 1],
              (h->n_free - i - 1) * sizeof(FreeBlock));
      h->n_free--;
    }
    return;
  }
  // coalesce with successor
  if (i < h->n_free && offset + size == h->freelist[i].offset) {
    h->freelist[i].offset = offset;
    h->freelist[i].size += size;
    return;
  }
  if (h->n_free >= FREELIST_MAX) return;  // leak rather than corrupt
  memmove(&h->freelist[i + 1], &h->freelist[i],
          (h->n_free - i) * sizeof(FreeBlock));
  h->freelist[i] = {offset, size};
  h->n_free++;
}

void free_slot_bytes(Header* h, Slot* s) {
  free_bytes(h, s->offset, s->size);
  h->used_bytes -= s->size;
  s->state = SLOT_TOMBSTONE;
}

}  // namespace

extern "C" {

// Create + initialize the arena file (driver, once per session).
int arena_init(const char* path, uint64_t capacity) {
  uint64_t meta = align_up(sizeof(Header));
  if (capacity < meta + ALIGN) return -1;
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return -1;
  if (ftruncate(fd, (off_t)capacity) != 0) {
    close(fd);
    unlink(path);
    return -1;
  }
  void* m = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (m == MAP_FAILED) {
    close(fd);
    unlink(path);
    return -1;
  }
  Header* h = (Header*)m;
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  h->heap_start = meta;
  h->bump = meta;
  h->used_bytes = 0;
  h->n_free = 0;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  // A crashed worker must not wedge every other process on the mutex.
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &attr);
  pthread_mutexattr_destroy(&attr);
  h->magic = MAGIC;  // last: marks fully initialized
  msync(m, sizeof(Header), MS_SYNC);
  munmap(m, capacity);
  close(fd);
  return 0;
}

void* arena_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* m = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                 MAP_SHARED, fd, 0);
  if (m == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = (Header*)m;
  if (h->magic != MAGIC) {
    munmap(m, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  Handle* out = new Handle{h, (uint64_t)st.st_size, fd};
  return out;
}

void arena_close(void* hp) {
  Handle* h = (Handle*)hp;
  if (!h) return;
  munmap(h->hdr, h->mapped);
  close(h->fd);
  delete h;
}

// Returns 0 when the arena is usable; nonzero when poisoned.  A lock owner
// dying mid-mutation may have left the freelist/table half-updated —
// continuing would hand the same bytes to two objects, so the arena is
// POISONED: every op fails cleanly and callers fall back to the file
// backend (existing objects reconstruct via lineage).
static int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mu);
    h->poisoned = 1;
  }
  return h->poisoned ? -3 : 0;
}

int64_t arena_alloc(void* hp, const char* id, uint64_t size) {
  Handle* h = (Handle*)hp;
  uint32_t id_len = (uint32_t)strnlen(id, ID_MAX);
  if (lock_robust(h->hdr) != 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -3;  // poisoned
  }
  Slot* s = find_slot(h->hdr, id, id_len, true);
  if (s == nullptr) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -1;  // table full
  }
  if (s->state == SLOT_PENDING || s->state == SLOT_SEALED ||
      s->state == SLOT_DOOMED) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -2;  // exists
  }
  int64_t off = alloc_bytes(h->hdr, size);
  if (off < 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -1;  // heap full
  }
  s->hash = fnv1a(id, id_len);
  s->id_len = id_len;
  memcpy(s->id, id, id_len);
  s->offset = (uint64_t)off;
  s->size = size;
  s->pins = 0;
  s->state = SLOT_PENDING;
  h->hdr->used_bytes += size;
  pthread_mutex_unlock(&h->hdr->mu);
  return off;
}

int arena_seal(void* hp, const char* id) {
  Handle* h = (Handle*)hp;
  uint32_t id_len = (uint32_t)strnlen(id, ID_MAX);
  if (lock_robust(h->hdr) != 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -3;
  }
  Slot* s = find_slot(h->hdr, id, id_len, false);
  if (s == nullptr || (s->state != SLOT_PENDING && s->state != SLOT_SEALED)) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -1;
  }
  s->state = SLOT_SEALED;
  pthread_mutex_unlock(&h->hdr->mu);
  return 0;
}

// Sealed-object lookup + PIN: the caller holds a zero-copy view, so the
// bytes must not be reused until arena_release.  Offset returned; size via
// out-param.
int64_t arena_acquire(void* hp, const char* id, uint64_t* size_out) {
  Handle* h = (Handle*)hp;
  uint32_t id_len = (uint32_t)strnlen(id, ID_MAX);
  if (lock_robust(h->hdr) != 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -3;
  }
  Slot* s = find_slot(h->hdr, id, id_len, false);
  if (s == nullptr || s->state != SLOT_SEALED) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -1;
  }
  s->pins++;
  if (size_out) *size_out = s->size;
  int64_t off = (int64_t)s->offset;
  pthread_mutex_unlock(&h->hdr->mu);
  return off;
}

int arena_release(void* hp, const char* id) {
  Handle* h = (Handle*)hp;
  uint32_t id_len = (uint32_t)strnlen(id, ID_MAX);
  if (lock_robust(h->hdr) != 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -3;
  }
  Slot* s = find_slot(h->hdr, id, id_len, false);
  if (s == nullptr || (s->state != SLOT_SEALED && s->state != SLOT_DOOMED) ||
      s->pins == 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -1;
  }
  s->pins--;
  if (s->pins == 0 && s->state == SLOT_DOOMED) {
    free_slot_bytes(h->hdr, s);
  }
  pthread_mutex_unlock(&h->hdr->mu);
  return 0;
}

// Unpinned existence/metadata check (state API, contains()).
int64_t arena_lookup(void* hp, const char* id, uint64_t* size_out) {
  Handle* h = (Handle*)hp;
  uint32_t id_len = (uint32_t)strnlen(id, ID_MAX);
  if (lock_robust(h->hdr) != 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -3;
  }
  Slot* s = find_slot(h->hdr, id, id_len, false);
  if (s == nullptr || s->state != SLOT_SEALED) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -1;
  }
  if (size_out) *size_out = s->size;
  int64_t off = (int64_t)s->offset;
  pthread_mutex_unlock(&h->hdr->mu);
  return off;
}

int arena_delete(void* hp, const char* id) {
  Handle* h = (Handle*)hp;
  uint32_t id_len = (uint32_t)strnlen(id, ID_MAX);
  if (lock_robust(h->hdr) != 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -3;
  }
  Slot* s = find_slot(h->hdr, id, id_len, false);
  if (s == nullptr || s->state == SLOT_FREE || s->state == SLOT_TOMBSTONE) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -1;
  }
  if (s->state == SLOT_DOOMED) {
    pthread_mutex_unlock(&h->hdr->mu);
    return 0;  // already deleted, awaiting last release
  }
  if (s->pins > 0) {
    s->state = SLOT_DOOMED;  // invisible to lookups; freed at last release
    pthread_mutex_unlock(&h->hdr->mu);
    return 0;
  }
  free_slot_bytes(h->hdr, s);
  pthread_mutex_unlock(&h->hdr->mu);
  return 0;
}

// Slot state for diagnostics/recovery: 0 free/absent, 1 pending, 2 sealed,
// 3 tombstone, 4 doomed, -3 poisoned.
int arena_state(void* hp, const char* id) {
  Handle* h = (Handle*)hp;
  uint32_t id_len = (uint32_t)strnlen(id, ID_MAX);
  if (lock_robust(h->hdr) != 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return -3;
  }
  Slot* s = find_slot(h->hdr, id, id_len, false);
  int st = (s == nullptr) ? SLOT_FREE : (int)s->state;
  pthread_mutex_unlock(&h->hdr->mu);
  return st;
}

uint64_t arena_used(void* hp) {
  Handle* h = (Handle*)hp;
  if (lock_robust(h->hdr) != 0) {
    pthread_mutex_unlock(&h->hdr->mu);
    return 0;
  }
  uint64_t u = h->hdr->used_bytes;
  pthread_mutex_unlock(&h->hdr->mu);
  return u;
}

uint64_t arena_capacity(void* hp) {
  Handle* h = (Handle*)hp;
  return h->hdr->capacity - h->hdr->heap_start;
}

}  // extern "C"
