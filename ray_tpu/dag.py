"""Lazy task DAGs: fn.bind(...) builds a graph, execute() runs it.

ray: python/ray/dag/ (DAGNode, .bind()/.execute()) — the base the
reference's Serve graphs and Workflow build on.  A DAGNode records a
remote function + args (which may be other DAGNodes); execute() walks the
graph ONCE per node (diamonds share results) and wires ObjectRefs so the
runtime's dependency tracking does the scheduling — no driver-side joins
between stages.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple


class DAGNode:
    """One lazy invocation of a remote function."""

    def __init__(self, fn, args: Tuple, kwargs: Dict):
        from ray_tpu.remote_function import RemoteFunction

        if not isinstance(fn, RemoteFunction):
            raise TypeError("DAGNode target must be a @ray_tpu.remote function")
        self._fn = fn
        self._args = args
        self._kwargs = kwargs

    # -- introspection ----------------------------------------------------
    @staticmethod
    def _scan(value, found: List["DAGNode"]) -> None:
        """Collect DAGNodes nested in containers (ray's DAG scans args the
        same way) — a node hidden in a list must be executed, not pickled."""
        if isinstance(value, DAGNode):
            found.append(value)
        elif isinstance(value, (list, tuple, set)):
            for v in value:
                DAGNode._scan(v, found)
        elif isinstance(value, dict):
            for v in value.values():
                DAGNode._scan(v, found)

    def _children(self) -> List["DAGNode"]:
        out: List[DAGNode] = []
        for a in list(self._args) + list(self._kwargs.values()):
            self._scan(a, out)
        return out

    def topological_order(self) -> List["DAGNode"]:
        """Children before parents; each node once (diamond-safe)."""
        seen: Dict[int, DAGNode] = {}
        order: List[DAGNode] = []

        def visit(node: "DAGNode", stack: set):
            if id(node) in seen:
                return
            if id(node) in stack:
                raise ValueError("cycle in DAG")
            stack.add(id(node))
            for c in node._children():
                visit(c, stack)
            stack.remove(id(node))
            seen[id(node)] = node
            order.append(node)

        visit(self, set())
        return order

    # -- execution --------------------------------------------------------
    def execute(self):
        """Submit the whole graph; returns the root's ObjectRef.  Shared
        subgraphs run once; inter-node edges are ObjectRefs, so stages
        pipeline through the runtime's dependency tracking."""
        results: Dict[int, Any] = {}

        def subst(value):
            if isinstance(value, DAGNode):
                return results[id(value)]
            if isinstance(value, list):
                return [subst(v) for v in value]
            if isinstance(value, tuple):
                return tuple(subst(v) for v in value)
            if isinstance(value, set):
                return {subst(v) for v in value}
            if isinstance(value, dict):
                return {k: subst(v) for k, v in value.items()}
            return value

        for node in self.topological_order():
            args = [subst(a) for a in node._args]
            kwargs = {k: subst(v) for k, v in node._kwargs.items()}
            results[id(node)] = node._fn.remote(*args, **kwargs)
        return results[id(self)]

    def __repr__(self):
        return f"DAGNode({getattr(self._fn, '_name', '?')}, deps={len(self._children())})"
