"""@ray_tpu.remote functions (ray: python/ray/remote_function.py:35)."""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private import ids
from ray_tpu._private.client import build_args_blob, client, current_session
from ray_tpu._private.task_spec import TaskSpec

_DEFAULT_TASK_MAX_RETRIES = 3  # ray default (remote_function.py:254)


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._opts = dict(options or {})
        self._fn_id: Optional[str] = None
        self._exported_session: Optional[str] = None
        self.__name__ = getattr(fn, "__name__", "remote_fn")

    def options(self, **opts) -> "RemoteFunction":
        return RemoteFunction(self._fn, {**self._opts, **opts})

    def _ensure_exported(self) -> str:
        session = current_session()
        if self._fn_id is None or self._exported_session != session:
            blob = cloudpickle.dumps(self._fn)
            self._fn_id = "fn-" + hashlib.sha1(blob).hexdigest()[:16]
            client.export_function(self._fn_id, blob)
            self._exported_session = session
        return self._fn_id

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__} cannot be called directly; "
            f"use {self.__name__}.remote()"
        )

    def bind(self, *args, **kwargs):
        """Lazy DAG node (ray: dag .bind()); run via .execute() or
        workflow.run()."""
        from ray_tpu.dag import DAGNode

        return DAGNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        o = self._opts
        renv = o.get("runtime_env")
        if renv:
            # Validate BEFORE exporting: a rejected submission must not pay
            # the cloudpickle + KV export of a function that never runs.
            from ray_tpu._private.runtime_env import validate_runtime_env

            validate_runtime_env(renv)
        fn_id = self._ensure_exported()
        session = current_session()
        if (
            renv
            and (renv.get("working_dir") or renv.get("py_modules"))
            and renv.get("_resolved") != session
        ):
            # Package + upload ONCE per session per options instance — an
            # os.walk per submit would sit on the hot path, but a cached
            # resolution from a PREVIOUS session points at pkg:// blobs the
            # new session's KV never saw, so the marker is the session name.
            from ray_tpu._private.runtime_env import resolve_runtime_env

            # Re-resolving for a NEW session must start from the original
            # local paths (a prior resolution replaced them with pkg://
            # URIs, which resolve_runtime_env passes through untouched).
            raw = {k: v for k, v in renv.items() if k not in ("_resolved", "_orig")}
            raw.update(renv.get("_orig") or {})
            resolved = resolve_runtime_env(
                raw, lambda u, d: client.kv_put(u, d), session
            )
            resolved["_orig"] = {
                k: raw[k]
                for k in ("working_dir", "py_modules")
                if raw.get(k) and not str(raw[k]).startswith("pkg://")
            }
            resolved["_resolved"] = session
            o["runtime_env"] = resolved
        resources = dict(o.get("resources") or {})
        resources["CPU"] = float(o.get("num_cpus", 1))
        if o.get("num_tpus"):
            resources["TPU"] = float(o["num_tpus"])
        if o.get("num_gpus"):
            resources["GPU"] = float(o["num_gpus"])
        blob, contained, deps = build_args_blob(args, kwargs)
        num_returns = o.get("num_returns", 1)
        spec = TaskSpec(
            task_id=ids.task_id(),
            name=o.get("name", self.__name__),
            fn_id=fn_id,
            args_blob=blob,
            contained_refs=contained,
            deps=deps,
            num_returns=num_returns,
            resources=resources,
            max_retries=o.get("max_retries", _DEFAULT_TASK_MAX_RETRIES),
            retry_exceptions=bool(o.get("retry_exceptions", False)),
            scheduling_strategy=o.get("scheduling_strategy"),
            runtime_env=o.get("runtime_env"),
        )
        refs = client.submit(spec)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs


def remote(*args, **kwargs):
    """@remote decorator for functions and classes
    (ray: python/ray/_private/worker.py:2629 `ray.remote`)."""
    from ray_tpu.actor import ActorClass
    import inspect

    if len(args) == 1 and not kwargs and callable(args[0]):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target, {})
        return RemoteFunction(target)

    opts = kwargs

    def decorator(target):
        if inspect.isclass(target):
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)

    return decorator
