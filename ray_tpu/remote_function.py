"""@ray_tpu.remote functions (ray: python/ray/remote_function.py:35)."""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private import ids
from ray_tpu._private.client import build_args_blob, client, current_session
from ray_tpu._private.task_spec import TaskSpec

_DEFAULT_TASK_MAX_RETRIES = 3  # ray default (remote_function.py:254)


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._opts = dict(options or {})
        self._fn_id: Optional[str] = None
        self._exported_session: Optional[str] = None
        self.__name__ = getattr(fn, "__name__", "remote_fn")

    def options(self, **opts) -> "RemoteFunction":
        return RemoteFunction(self._fn, {**self._opts, **opts})

    def _ensure_exported(self) -> str:
        session = current_session()
        if self._fn_id is None or self._exported_session != session:
            blob = cloudpickle.dumps(self._fn)
            self._fn_id = "fn-" + hashlib.sha1(blob).hexdigest()[:16]
            client.export_function(self._fn_id, blob)
            self._exported_session = session
        return self._fn_id

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__} cannot be called directly; "
            f"use {self.__name__}.remote()"
        )

    def bind(self, *args, **kwargs):
        """Lazy DAG node (ray: dag .bind()); run via .execute() or
        workflow.run()."""
        from ray_tpu.dag import DAGNode

        return DAGNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        fn_id = self._ensure_exported()
        o = self._opts
        resources = dict(o.get("resources") or {})
        resources["CPU"] = float(o.get("num_cpus", 1))
        if o.get("num_tpus"):
            resources["TPU"] = float(o["num_tpus"])
        if o.get("num_gpus"):
            resources["GPU"] = float(o["num_gpus"])
        blob, contained, deps = build_args_blob(args, kwargs)
        num_returns = o.get("num_returns", 1)
        spec = TaskSpec(
            task_id=ids.task_id(),
            name=o.get("name", self.__name__),
            fn_id=fn_id,
            args_blob=blob,
            contained_refs=contained,
            deps=deps,
            num_returns=num_returns,
            resources=resources,
            max_retries=o.get("max_retries", _DEFAULT_TASK_MAX_RETRIES),
            retry_exceptions=bool(o.get("retry_exceptions", False)),
            scheduling_strategy=o.get("scheduling_strategy"),
            runtime_env=o.get("runtime_env"),
        )
        refs = client.submit(spec)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs


def remote(*args, **kwargs):
    """@remote decorator for functions and classes
    (ray: python/ray/_private/worker.py:2629 `ray.remote`)."""
    from ray_tpu.actor import ActorClass
    import inspect

    if len(args) == 1 and not kwargs and callable(args[0]):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target, {})
        return RemoteFunction(target)

    opts = kwargs

    def decorator(target):
        if inspect.isclass(target):
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)

    return decorator
