"""Dashboard-lite: HTTP endpoints over the state API + a timeline export.

ray: dashboard/ (DashboardHead at head.py:70 + REST modules) reduced to
the load-bearing surface: JSON endpoints for nodes/tasks/actors/objects/
workers/metrics and a Chrome-trace timeline (the reference's
`ray timeline`, python/ray/_private/profiling.py).  Serves with the stdlib
threaded HTTP server — no frontend build, curl/jq-friendly.

    GET /api/nodes | /api/tasks | /api/actors | /api/objects
    GET /api/workers | /api/placement_groups | /api/metrics | /api/summary
    GET /api/timeline        (chrome://tracing format)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


def timeline(last=None, since=None) -> list:
    """Chrome-trace events from the runtime's task-event sink
    (ray: `ray timeline` exports the same catapult format).  `last` /
    `since` bound the export to a trailing window / an absolute start
    (CLI --last/--since; default window via RAY_TPU_TIMELINE_LAST_S)."""
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    with rt.lock:
        events = list(rt.task_events)
    out = []
    for e in events:
        dur_us = int(max(e.get("duration", 0.0), 0.0) * 1e6)
        end_us = int(e["end_time"] * 1e6)
        out.append(
            {
                "name": e["name"],
                "cat": "task",
                "ph": "X",  # complete event
                "ts": end_us - dur_us,
                "dur": max(dur_us, 1),
                "pid": e.get("node_id") or "head",
                "tid": e.get("worker_id") or "?",
                "args": {
                    "task_id": e["task_id"],
                    "state": e["state"],
                    "attempt": e["attempt"],
                    "parent_task_id": e.get("parent_task_id"),
                },
            }
        )
    # Trace spans (util/tracing.py, when enabled) ride the same timeline:
    # submit/run spans interleave with task rows in the catapult view.
    from ray_tpu.util.state import list_spans
    from ray_tpu.util.tracing import spans_to_chrome_trace

    out.extend(spans_to_chrome_trace(list_spans()))
    # Object lifecycle events (create/seal/transfer/spill/restore/free)
    # merge as instant events on a per-node "objects" row, so byte
    # movement lines up with the task rows that caused it.
    for ev in list(getattr(rt, "object_events", ())):
        out.append(
            {
                "name": f"obj:{ev['event']}",
                "cat": "object",
                "ph": "i",
                "s": "p",
                "ts": int(ev["t"] * 1e6),
                "pid": ev.get("node") or "head",
                "tid": "objects",
                "args": {
                    "object_id": ev["oid"],
                    "bytes": ev.get("bytes"),
                },
            }
        )
    from ray_tpu._private import config as _config
    from ray_tpu.util.tracing import window_chrome_events

    if last is None and since is None:
        default_last = _config.get("timeline_last_s")
        last = default_last if default_last > 0 else None
    return window_chrome_events(out, last=last, since=since)


def _events_endpoint(query=None):
    """Structured cluster events with ?severity=&source=&limit= filters."""
    from ray_tpu.util import state as state_api

    q = query or {}
    try:
        limit = int(q.get("limit", [100])[0])
    except ValueError:
        limit = 100
    return state_api.list_cluster_events(
        limit=limit,
        severity=(q.get("severity") or [None])[0],
        source=(q.get("source") or [None])[0],
    )


def _telemetry_endpoint(query=None):
    """Pushed-metrics plane: ?series=<name> returns that aggregate's ring
    time series; without it, the per-process + aggregate summary."""
    from ray_tpu.util import state as state_api

    q = query or {}
    series = (q.get("series") or [None])[0]
    if series is not None:
        return state_api.telemetry_series(series)
    return state_api.telemetry_summary()


def _memory_endpoint(query=None):
    """Object-ledger join (util/state.memory_summary): ?group_by=node|
    owner|callsite, ?leaks=1 trims to the suspects, ?top=N, ?events=1
    appends the lifecycle ring."""
    from ray_tpu.util import state as state_api

    q = query or {}
    try:
        top = int((q.get("top") or [20])[0])
    except ValueError:
        top = 20
    out = state_api.memory_summary(
        group_by=(q.get("group_by") or [None])[0],
        top=top,
        include_events=(q.get("events") or ["0"])[0] not in ("0", ""),
    )
    if (q.get("leaks") or ["0"])[0] not in ("0", ""):
        out = {
            "leak_suspects": out["leak_suspects"],
            "leak_suspect_bytes": out["leak_suspect_bytes"],
            "leaks": out["leaks"],
        }
    return out


def _timeline_endpoint(query=None):
    """Windowed timeline: ?last=SECONDS / ?since=EPOCH bound the export
    by the event/span rings instead of dumping everything."""
    q = query or {}

    def _num(name):
        try:
            v = (q.get(name) or [None])[0]
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None

    return timeline(last=_num("last"), since=_num("since"))


def _profile_endpoint(query=None):
    """Cluster flamegraph (profiler.py).  ?seconds=N runs a sampling
    window inline (start → sleep → stop — each HTTP request gets its own
    thread, so blocking here is fine); without it, reports whatever the
    sink already holds (e.g. an always-hot RAY_TPU_PROF_HZ run).
    ?node= / ?pid= filter; ?hz= tunes the rate."""
    import time as _time

    from ray_tpu.util import state as state_api

    q = query or {}

    def _one(name, cast=str):
        v = (q.get(name) or [None])[0]
        if v is None:
            return None
        try:
            return cast(v)
        except (TypeError, ValueError):
            return None

    seconds = _one("seconds", float)
    if seconds:
        state_api.profile_start(hz=_one("hz", float))
        _time.sleep(min(max(seconds, 0.1), 120.0))
        state_api.profile_stop()
        _time.sleep(0.7)  # one ticker beat: final worker pushes land
    return state_api.profile_report(node=_one("node"), pid=_one("pid", int))


def _task_summary_endpoint(query=None):
    """Stage-attributed task summary (?slow=N bounds the slow list)."""
    from ray_tpu.util import state as state_api

    q = query or {}
    try:
        slow = int((q.get("slow") or [10])[0])
    except (TypeError, ValueError):
        slow = 10
    return state_api.task_summary(slow=slow)


def _logs_endpoint(worker=None, tail: int = 0, query=None):
    """Per-worker captured output (ray: dashboard log index + `ray logs`).
    Without ?worker=, lists workers that have log lines."""
    from ray_tpu._private.runtime import get_runtime

    if query:
        worker = query.get("worker", [worker])[0]
        tail = int(query.get("tail", [tail])[0])
    rt = get_runtime()
    if worker is None:
        return {"workers": sorted(rt.worker_logs)}
    return {"worker": worker, "lines": rt.get_logs(worker, tail or None)}


class Dashboard:
    """Embeddable dashboard server (one per driver)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from ray_tpu.util import state as state_api

        routes = {
            "/api/nodes": state_api.list_nodes,
            "/api/tasks": state_api.list_tasks,
            "/api/actors": state_api.list_actors,
            "/api/objects": state_api.list_objects,
            "/api/workers": state_api.list_workers,
            "/api/placement_groups": state_api.list_placement_groups,
            "/api/metrics": state_api.cluster_metrics,
            "/api/summary": state_api.summarize_tasks,
            "/api/timeline": _timeline_endpoint,
            "/api/logs": _logs_endpoint,
            "/api/events": _events_endpoint,
            "/api/telemetry": _telemetry_endpoint,
            "/api/memory": _memory_endpoint,
            "/api/profile": _profile_endpoint,
            "/api/task_summary": _task_summary_endpoint,
        }

        def _prometheus() -> str:
            # Prometheus text exposition of the CLUSTER aggregate (ray:
            # metrics_agent.py:375 → prometheus_exporter): every pushed
            # per-process registry merged by the telemetry sink (counters
            # and histogram buckets summed), plus runtime gauges.  The
            # head's own registry is folded in fresh, so a local-only
            # runtime serves exactly what prometheus_text used to.
            from ray_tpu._private.runtime import get_runtime
            from ray_tpu._private import telemetry as _telemetry

            rt = get_runtime()
            rt.telemetry.ingest("head", rt.head_telemetry_snapshot())
            return _telemetry.prometheus_cluster_text(
                rt.telemetry, extra_gauges=state_api.cluster_metrics()
            )

        # Non-JSON routes share the same dispatch: (handler, content_type);
        # a None content_type means JSON-serialize the handler's result.
        content_types = {
            "/metrics": "text/plain; version=0.0.4",
            "/": "text/html; charset=utf-8",
        }
        routes["/metrics"] = _prometheus
        routes["/"] = lambda: _INDEX_HTML

        class Handler(BaseHTTPRequestHandler):
            disable_nagle_algorithm = True  # no Nagle/delayed-ACK stalls

            def log_message(self, *a):
                pass

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                fn = routes.get(parsed.path)
                ctype = content_types.get(parsed.path)
                if fn is None:
                    body = json.dumps(
                        {"error": "unknown route", "routes": sorted(routes)}
                    ).encode()
                    code = 404
                else:
                    try:
                        # Query-aware endpoints declare a `query` kwarg;
                        # the rest are called bare — ONE response tail.
                        import inspect

                        if "query" in inspect.signature(fn).parameters:
                            out = fn(query=parse_qs(parsed.query))
                        else:
                            out = fn()
                        body = (
                            out.encode() if ctype
                            else json.dumps(out, default=str).encode()
                        )
                        code = 200
                    except Exception as e:  # noqa: BLE001 — HTTP boundary
                        ctype = None  # errors are always the JSON shape
                        body = json.dumps({"error": repr(e)}).encode()
                        code = 500
                self.send_response(code)
                self.send_header("Content-Type", ctype or "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="raytpu-dash"
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._server.shutdown()


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port)
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.shutdown()
        _dashboard = None


# Web UI-lite: one static page over the JSON endpoints (ray: dashboard/
# client React app reduced to a dependency-free auto-refreshing view —
# no frontend build, works wherever the head runs).
_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa;color:#222}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin:1.2rem 0 .4rem}
 table{border-collapse:collapse;font-size:.85rem;background:#fff}
 th,td{border:1px solid #ddd;padding:.25rem .6rem;text-align:left}
 th{background:#f0f0f0} .num{text-align:right}
 #err{color:#b00020} code{background:#eee;padding:0 .3rem}
</style></head><body>
<h1>ray_tpu dashboard <small id="ts"></small></h1>
<div id="err"></div>
<h2>Cluster metrics</h2><table id="metrics"></table>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Task summary</h2><table id="summary"></table>
<p>Raw endpoints: <code>/api/nodes</code> <code>/api/tasks</code>
<code>/api/actors</code> <code>/api/objects</code> <code>/api/workers</code>
<code>/api/placement_groups</code> <code>/api/metrics</code>
<code>/api/summary</code> <code>/api/timeline</code> <code>/api/logs</code>
<code>/api/telemetry</code> <code>/api/memory</code>
<code>/api/profile</code> <code>/api/task_summary</code>
<code>/metrics</code> (Prometheus)</p>
<script>
function row(cells, tag){const tr=document.createElement('tr');
 for(const c of cells){const td=document.createElement(tag||'td');
  td.textContent=(typeof c==='number')?(Number.isInteger(c)?c:c.toFixed(2)):String(c);
  tr.appendChild(td);} return tr;}
function fill(id, header, rows){const t=document.getElementById(id);
 t.replaceChildren(row(header,'th')); for(const r of rows) t.appendChild(row(r));}
async function j(p){const r=await fetch(p); if(!r.ok) throw new Error(p+': '+r.status);
 return r.json();}
async function refresh(){
 try{
  const [m, nodes, actors, summary] = await Promise.all(
   [j('/api/metrics'), j('/api/nodes'), j('/api/actors'), j('/api/summary')]);
  fill('metrics', ['metric','value'], Object.entries(m));
  fill('nodes', ['node','alive','head','resources','available'], nodes.map(n =>
   [n.node_id, n.alive===false?'dead':'alive', n.is_head?'yes':'',
    JSON.stringify(n.resources||{}), JSON.stringify(n.available||{})]));
  fill('actors', ['actor','name','state','restarts'], actors.map(a =>
   [a.actor_id, a.name||'', a.state, a.num_restarts||0]));
  fill('summary', ['state','count'], Object.entries(summary));
  document.getElementById('ts').textContent=new Date().toLocaleTimeString();
  document.getElementById('err').textContent='';
 }catch(e){document.getElementById('err').textContent=String(e);}
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""
