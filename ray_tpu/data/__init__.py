"""ray_tpu.data — distributed datasets on the actor runtime.

ray: python/ray/data/ (Dataset at dataset.py:163, read_api.py).  Blocks are
object-store entries (row lists or columnar NumpyBlock); stages run as one
task per block with the object store as the inter-stage buffer; all-to-all
ops (repartition/shuffle/sort/groupby) are two-phase task graphs.
"""

from ray_tpu.data.block import ArrowBlock, Block, BlockAccessor, NumpyBlock
from ray_tpu.data.dataset import Dataset, DatasetPipeline
from ray_tpu.data.datasource import (
    CSVDatasource,
    Datasource,
    FileBasedDatasource,
    JSONDatasource,
    ParquetDatasource,
    ReadTask,
    TextDatasource,
    read_datasource,
    write_datasource,
)
from ray_tpu.data.read_api import (
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)

__all__ = [
    "ArrowBlock",
    "CSVDatasource",
    "Datasource",
    "FileBasedDatasource",
    "JSONDatasource",
    "ParquetDatasource",
    "ReadTask",
    "TextDatasource",
    "read_datasource",
    "write_datasource",
    "Block",
    "BlockAccessor",
    "Dataset",
    "DatasetPipeline",
    "NumpyBlock",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_csv",
    "read_json",
    "read_parquet",
    "read_text",
]
