"""Dataset: lazy, distributed, block-based data pipelines.

ray: python/ray/data/dataset.py:163 (Dataset; map_batches :373, repartition
:969, random_shuffle :1008, split :1144, iter_batches :2875) with the
execution model of _internal/plan.py + streaming_executor.py:34:

  * transforms are LAZY — each one-to-one stage (map/flat_map/filter/
    map_batches) only appends an op to the dataset's pending chain;
    nothing runs until a consumer asks;
  * at execution the whole pending chain FUSES into ONE task per block
    (ray: _internal/planner's MapOperator fusion) — a .map().filter()
    .map_batches() pipeline over N blocks launches exactly N tasks;
  * all-to-all stages (repartition/shuffle/sort/groupby) are barrier
    points built as two-phase task graphs (partition map + reduce); for
    shuffle/sort/groupby the pending map chain fuses INTO the partition
    map phase — one task per input block, no intermediate block between
    map chain and shuffle (ray: _internal/push_based_shuffle.py).
    repartition/split(equal=True)/union need global row counts first, so
    they materialize the fused chain before slicing (a barrier, like the
    reference's count-based repartition);
  * consumption streams: iter_batches/iter_rows submit fused block tasks
    through a bounded in-flight window (backpressure — the driver holds at
    most `prefetch_blocks` unconsumed blocks), overlapping production with
    training-side consumption (ray: streaming_executor backpressure).

TPU-relevant: iter_batches yields numpy-dict batches sized for the training
step, and split() hands each SPMD host-worker an equal set of blocks
(ray: Dataset.split's locality-aware analogue).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    NumpyBlock,
    batch_to_rows,
    block_len,
    block_rows,
    block_slice,
    concat_blocks,
    rows_to_numpy_batch,
)


# -- stage tasks (plain remote functions) -----------------------------------


def _batch_output_to_block(out) -> Block:
    """A map_batches fn's output → block; dict-of-arrays stays columnar,
    pyarrow Tables stay Arrow."""
    if isinstance(out, dict):
        return NumpyBlock(out)
    try:
        import pyarrow as pa

        if isinstance(out, pa.Table):
            from ray_tpu.data.block import ArrowBlock

            return ArrowBlock(out)
    except ImportError:
        pass
    return batch_to_rows(out)


def _apply_op(block: Block, op: tuple) -> Block:
    fn_kind, fn, batch_format, batch_size = op
    if fn_kind == "rows":
        return [fn(r) for r in block]
    if fn_kind == "flat":
        out = []
        for r in block:
            out.extend(fn(r))
        return out
    if fn_kind == "filter":
        return [r for r in block if fn(r)]
    if fn_kind == "batches":
        bs = batch_size or block_len(block) or 1
        outs = []
        for i in range(0, block_len(block), bs):
            acc = BlockAccessor(block_slice(block, i, i + bs))
            outs.append(_batch_output_to_block(fn(acc.to_batch(batch_format))))
        return concat_blocks(outs)
    if fn_kind == "block":
        return fn(block)
    raise ValueError(fn_kind)


@ray_tpu.remote
def _fused_map_block(block: Block, ops: List[tuple]) -> Block:
    """The fused stage executor: the WHOLE pending one-to-one chain runs
    in one task, block stays in this worker's memory between ops — no
    inter-stage object-store round trips (ray: fused MapOperator)."""
    for op in ops:
        block = _apply_op(block, op)
    return block


def _stable_hash(key) -> int:
    """Process-independent key hash: builtin hash() of str/bytes is salted
    per interpreter, which would scatter one key across partitions when
    map tasks run in different worker processes."""
    import pickle as _pickle
    import zlib

    try:
        data = _pickle.dumps(key, protocol=4)
    except Exception:
        data = repr(key).encode()
    return zlib.crc32(data)


@ray_tpu.remote
def _block_len(block: Block) -> int:
    return block_len(block)


@ray_tpu.remote
def _write_block(block: Block, path: str, fmt: str) -> Tuple[str, int]:
    """One output file per block (ray: dataset.py:2327 write_parquet /
    :2454 write_csv / write_json — file-per-block layout).  Arrow/columnar
    blocks write without a row detour."""
    n = block_len(block)
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(BlockAccessor(block).to_batch("pyarrow"), path)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(BlockAccessor(block).to_batch("pyarrow"), path)
    elif fmt == "json":
        import json as _json

        with open(path, "w") as f:
            for r in block_rows(block):
                f.write(_json.dumps(r if isinstance(r, dict) else {"value": r}))
                f.write("\n")
    else:
        raise ValueError(f"unknown write format {fmt!r}")
    return path, n


@ray_tpu.remote
def _slice_block(block: Block, start: int, end: int) -> Block:
    return block_slice(block, start, end)


@ray_tpu.remote
def _merge_shards(*shards: Block) -> Block:
    return concat_blocks(list(shards))


@ray_tpu.remote
def _partition_block_grouped(
    block: Block, ops: List[tuple], n: int, group_bounds: List[int], key_fn, seed
):
    """Map stage of the PUSH-BASED shuffle (ray:
    _internal/push_based_shuffle.py): fused upstream chain, split into n
    partitions, then PACK the partitions into merger groups — one output
    object per MERGER instead of one per partition, so M maps produce
    M x P intermediates (P = merge factor), not M x N."""
    for op in ops:
        block = _apply_op(block, op)
    n_groups = len(group_bounds) - 1
    shards: List[List[Any]] = [[] for _ in range(n)]
    if key_fn is None:
        rng = random.Random(seed)
        for r in block_rows(block):
            shards[rng.randrange(n)].append(r)
    else:
        for r in block_rows(block):
            shards[_stable_hash(key_fn(r)) % n].append(r)
    packs = [
        shards[group_bounds[g] : group_bounds[g + 1]] for g in range(n_groups)
    ]
    return packs if n_groups > 1 else packs[0]


@ray_tpu.remote
def _merge_group_round(*packs):
    """Merge stage of the push-based shuffle: combine ONE round's map
    outputs for one merger group.  A round's merge depends only on that
    round's maps, so it executes WHILE later rounds' maps run (the
    pipelining that makes the shuffle push-based), each round's packed
    intermediates free as soon as they merge, and — unlike an
    accumulator chained across rounds — every row moves through the
    store exactly twice (map -> merge -> finalize), not once per round
    (ray: push_based_shuffle merge rounds)."""
    out: List[List[Any]] = [[] for _ in range(len(packs[0]))]
    for pack in packs:
        for i, shard in enumerate(pack):
            out[i].extend(block_rows(shard))
    return out


def _concat_rounds(round_merges):
    n = len(round_merges[0])
    out: List[List[Any]] = [[] for _ in range(n)]
    for rm in round_merges:
        for i, rows in enumerate(rm):
            out[i].extend(rows)
    return out


@ray_tpu.remote
def _finalize_shuffle_group(seed, *round_merges):
    """Reduce stage: concat each partition's rows across rounds, permute;
    num_returns = partitions in the group."""
    outs = _concat_rounds(round_merges)
    for i, rows in enumerate(outs):
        random.Random(seed + i).shuffle(rows)
    return outs if len(outs) > 1 else outs[0]


@ray_tpu.remote
def _split_group(*round_merges):
    """Reduce stage for keyed partitions (groupby): concat across rounds,
    emit each partition as its own block; num_returns = group size."""
    outs = _concat_rounds(round_merges)
    return outs if len(outs) > 1 else outs[0]


@ray_tpu.remote
def _sort_block(block: Block, ops: List[tuple], key, descending: bool) -> Block:
    for op in ops:
        block = _apply_op(block, op)
    return sorted(block_rows(block), key=key, reverse=descending)


@ray_tpu.remote
def _merge_sorted(key, descending: bool, *blocks: Block) -> Block:
    import heapq

    if key is None:
        key = lambda x: x
    merged = heapq.merge(*blocks, key=key, reverse=descending)
    return list(merged)


class Dataset:
    """Base block refs + a pending (unsubmitted) one-to-one op chain."""

    def __init__(self, block_refs: List[Any], _ops: Optional[List[tuple]] = None):
        self._base_refs = list(block_refs)
        self._ops: List[tuple] = list(_ops or [])
        self._executed: Optional[List[Any]] = None  # memoized fused refs
        # Per-block memo of already-submitted fused tasks: repeated /
        # partial consumption (multi-epoch iter_batches, take then
        # take_all) reuses each block's result instead of re-running the
        # chain — also keeps nondeterministic fns consistent across reads.
        self._submitted: Dict[int, Any] = {}

    # -- constructors (see read_api.py) -----------------------------------

    # -- plan execution ----------------------------------------------------

    @property
    def _block_refs(self) -> List[Any]:
        """Executed block refs (kept as a property: lots of internal and
        library code consumes `ds._block_refs`)."""
        return self._execute()

    def _submit_block(self, i: int, ops: List[tuple]) -> Any:
        ref = self._submitted.get(i)
        if ref is None:
            ref = _fused_map_block.remote(self._base_refs[i], ops)
            self._submitted[i] = ref
        return ref

    def _execute(self) -> List[Any]:
        """Submit the fused chain — ONE task per block — and memoize."""
        if self._executed is None:
            if not self._ops:
                self._executed = list(self._base_refs)
            else:
                ops = list(self._ops)
                self._executed = [
                    self._submit_block(i, ops) for i in range(len(self._base_refs))
                ]
        return self._executed

    def _stream_refs(self, window: int) -> Iterator[Any]:
        """Streaming execution with backpressure: at most `window` fused
        block tasks are submitted-but-unconsumed at any moment, so a huge
        dataset never floods the store ahead of the consumer
        (ray: streaming_executor.py:34 bounded-resource semantics)."""
        if self._executed is not None or not self._ops:
            yield from self._execute()
            return
        from collections import deque as _deque

        ops = list(self._ops)
        inflight: "_deque[Any]" = _deque()
        for i in range(len(self._base_refs)):
            if len(inflight) >= window:
                yield inflight.popleft()
            inflight.append(self._submit_block(i, ops))
        while inflight:
            yield inflight.popleft()
        if len(self._submitted) == len(self._base_refs):
            self._executed = [
                self._submitted[i] for i in range(len(self._base_refs))
            ]

    # -- transforms (one-to-one, LAZY: recorded, fused at execution) -------
    def _map_stage(self, fn_kind: str, fn: Callable, batch_format="numpy", batch_size=None) -> "Dataset":
        return Dataset(
            self._executed if self._executed is not None else self._base_refs,
            _ops=(
                ([] if self._executed is not None else self._ops)
                + [(fn_kind, fn, batch_format, batch_size)]
            ),
        )

    def map(self, fn: Callable) -> "Dataset":
        return self._map_stage("rows", fn)

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._map_stage("flat", fn)

    def filter(self, fn: Callable) -> "Dataset":
        return self._map_stage("filter", fn)

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
    ) -> "Dataset":
        return self._map_stage("batches", fn, batch_format, batch_size)

    # -- all-to-all --------------------------------------------------------
    def _contiguous_slice_refs(
        self, bounds: List[int], lengths: List[int]
    ) -> List[List[Any]]:
        """Map global row ranges [bounds[i], bounds[i+1]) onto per-input-block
        slice refs, preserving row order (ray's repartition at dataset.py:969
        is order-preserving; the map phase sends each output only the
        contiguous slice it owns)."""
        offsets = [0]
        for ln in lengths:
            offsets.append(offsets[-1] + ln)
        out: List[List[Any]] = []
        for i in range(len(bounds) - 1):
            lo, hi = bounds[i], bounds[i + 1]
            slices = []
            for j, b in enumerate(self._block_refs):
                blo, bhi = offsets[j], offsets[j + 1]
                s, e = max(lo, blo), min(hi, bhi)
                if s < e:
                    if s == blo and e == bhi:
                        slices.append(b)  # whole block, no copy task
                    else:
                        slices.append(_slice_block.remote(b, s - blo, e - blo))
            out.append(slices)
        return out

    def repartition(self, num_blocks: int) -> "Dataset":
        """Order-preserving equal-range repartition (ray: dataset.py:969)."""
        lengths = ray_tpu.get([_block_len.remote(b) for b in self._block_refs])
        total = sum(lengths)
        bounds = [i * total // num_blocks for i in range(num_blocks + 1)]
        groups = self._contiguous_slice_refs(bounds, lengths)
        new_refs = [_merge_shards.remote(*g) if g else ray_tpu.put([]) for g in groups]
        return Dataset(new_refs)

    def _fusable_inputs(self) -> Tuple[List[Any], List[tuple]]:
        """(input refs, pending op chain) for fusing into an all-to-all
        map phase without a separate materialization."""
        if self._executed is not None:
            return self._executed, []
        return self._base_refs, list(self._ops)

    # Push-based shuffle knobs (ray: push_based_shuffle.py computes a
    # merge factor from cluster shape; fixed here — P mergers, and
    # ROUND_SIZE map tasks fold into the accumulators per round so merge
    # work pipelines with still-running maps).
    _SHUFFLE_MERGERS = 8
    _SHUFFLE_ROUND_SIZE = 8

    def _push_partition(
        self, n: int, key_fn, base_seed: Optional[int]
    ) -> Tuple[List[Any], List[int]]:
        """Shared push-based partition machinery (shuffle AND groupby):
        round-chained map + merge over P merger groups.  Returns the P
        accumulator refs and the group bounds."""
        refs, ops = self._fusable_inputs()
        P = min(self._SHUFFLE_MERGERS, n)
        bounds = [p * n // P for p in range(P + 1)]
        rounds: List[List[Any]] = [[] for _ in range(P)]  # per-group merges
        for r0 in range(0, len(refs), self._SHUFFLE_ROUND_SIZE):
            rrefs = refs[r0 : r0 + self._SHUFFLE_ROUND_SIZE]
            packs = [
                _partition_block_grouped.options(num_returns=P).remote(
                    b, ops, n, bounds, key_fn,
                    None if base_seed is None else base_seed + r0 + j,
                )
                for j, b in enumerate(rrefs)
            ]
            for p in range(P):
                cols = [
                    (packs[j][p] if P > 1 else packs[j])
                    for j in range(len(packs))
                ]
                rounds[p].append(_merge_group_round.remote(*cols))
        return rounds, bounds

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """ray: dataset.py:1008; PUSH-BASED two-stage shuffle (ray:
        _internal/push_based_shuffle.py).  The pending map chain fuses
        into the partition stage; maps emit one packed object per merger
        (M x P intermediates, not M x N); mergers fold map outputs in
        ROUNDS chained through an accumulator, so merging overlaps the
        next round's maps and each round's intermediates free as they
        fold; a final per-group reduce permutes and emits the output
        partitions."""
        if not self._base_refs:
            return Dataset([])
        n = len(self._fusable_inputs()[0])  # outputs mirror input blocks
        base = seed if seed is not None else random.randrange(2**31)
        rounds, bounds = self._push_partition(n, None, base)
        new_refs: List[Any] = []
        for p in range(len(rounds)):
            g = bounds[p + 1] - bounds[p]  # >= 1: P <= n
            out = _finalize_shuffle_group.options(num_returns=g).remote(
                base + 7919 + p, *rounds[p]
            )
            new_refs.extend(out if g > 1 else [out])
        return Dataset(new_refs)

    def sort(self, key: Optional[Callable] = None, descending: bool = False) -> "Dataset":
        refs, ops = self._fusable_inputs()
        sorted_refs = [_sort_block.remote(b, ops, key, descending) for b in refs]
        return Dataset([_merge_sorted.remote(key, descending, *sorted_refs)])

    def groupby_aggregate(
        self, key_fn: Callable, agg_fn: Callable[[Any, List[Any]], Any], num_partitions: int = 8
    ) -> "Dataset":
        """Hash-partition by key, then aggregate per partition (simplified
        GroupedData — ray: python/ray/data/grouped_data.py).  Rides the
        same push-based round-merged partition machinery as shuffle."""
        n = num_partitions
        if not self._base_refs:
            return Dataset([])
        rounds, bounds = self._push_partition(n, key_fn, None)
        merged: List[Any] = []
        for p in range(len(rounds)):
            g = bounds[p + 1] - bounds[p]  # >= 1: P <= n
            out = _split_group.options(num_returns=g).remote(*rounds[p])
            merged.extend(out if g > 1 else [out])

        def agg(block: Block) -> Block:
            groups: Dict[Any, List[Any]] = {}
            for r in block:
                groups.setdefault(key_fn(r), []).append(r)
            return [agg_fn(k, v) for k, v in groups.items()]

        return Dataset(merged)._map_stage("block", agg)

    def union(self, *others: "Dataset") -> "Dataset":
        """Execution barrier: operands' fused chains are submitted here
        (their op chains differ, so they cannot share one pending chain)."""
        refs = list(self._block_refs)
        for o in others:
            refs.extend(o._block_refs)
        return Dataset(refs)

    # -- consumption -------------------------------------------------------
    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """ray: dataset.py:1144 — per-train-worker shards.

        equal=True produces EXACTLY equal row counts (truncating the
        remainder), deterministically and order-preserving — unequal SPMD
        shards would make ranks run different step counts and hang compiled
        collectives."""
        if equal:
            lengths = ray_tpu.get([_block_len.remote(b) for b in self._block_refs])
            total = sum(lengths)
            per = total // n
            bounds = [i * per for i in range(n + 1)]  # drops total - n*per rows
            groups = self._contiguous_slice_refs(bounds, lengths)
            return [
                Dataset([_merge_shards.remote(*g)] if g else [ray_tpu.put([])])
                for g in groups
            ]
        refs = self._block_refs
        out = [refs[i::n] for i in range(n)]
        return [Dataset(r) for r in out]

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        # Streamed with a small window: taking 20 rows of a huge lazy
        # pipeline runs a handful of block tasks, not all of them.
        for b in self._stream_refs(window=2):
            rows = block_rows(ray_tpu.get(b))
            out.extend(rows[: limit - len(out)])
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for b in self._block_refs:
            out.extend(block_rows(ray_tpu.get(b)))
        return out

    def count(self) -> int:
        return sum(ray_tpu.get([_block_len.remote(b) for b in self._block_refs]))

    def schema(self):
        for b in self._block_refs:
            blk = ray_tpu.get(b)
            if block_len(blk):
                return BlockAccessor(blk).schema()
        return None

    def num_blocks(self) -> int:
        return len(self._base_refs)

    def materialize(self) -> "Dataset":
        refs = self._execute()
        ray_tpu.wait(refs, num_returns=len(refs), timeout=None)
        return self

    def iter_rows(self) -> Iterator[Any]:
        for b in self._stream_refs(window=4):
            yield from block_rows(ray_tpu.get(b))

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_blocks: int = 4,
    ) -> Iterator[Any]:
        """Streaming consumption: fused block tasks are submitted through a
        bounded window of `prefetch_blocks` (backpressure — production
        overlaps consumption without flooding the store), carry-over
        stitches batch boundaries across blocks (ray: dataset.py:2875 /
        streaming_executor.py:34).  Columnar blocks slice without row
        materialization — the batches handed to device_put are the stored
        arrays.  Works identically inside train-worker actors: pass a split
        Dataset to the worker and iterate there (block fetch is a local shm
        mmap, no driver round-trip)."""
        carry: List[Block] = []
        carry_len = 0
        for b in self._stream_refs(window=max(prefetch_blocks, 1)):
            blk = ray_tpu.get(b)
            if block_len(blk) == 0:
                continue
            carry.append(blk)
            carry_len += block_len(blk)
            if carry_len >= batch_size:
                merged = concat_blocks(carry)
                off = 0
                while carry_len - off >= batch_size:
                    chunk = block_slice(merged, off, off + batch_size)
                    off += batch_size
                    yield BlockAccessor(chunk).to_batch(batch_format)
                carry = [block_slice(merged, off, carry_len)] if off < carry_len else []
                carry_len -= off
        if carry_len and not drop_last:
            yield BlockAccessor(concat_blocks(carry)).to_batch(batch_format)

    def to_pandas(self):
        return BlockAccessor(self.take_all()).to_batch("pandas")

    # -- write APIs (ray: dataset.py:2327 write_parquet, :2454 write_csv,
    # write_json) ----------------------------------------------------------

    def _write(self, path: str, fmt: str, ext: str) -> List[str]:
        """File-per-block parallel write; returns written paths.  Empty
        blocks are skipped (the reference also writes only non-empty
        blocks), but an entirely-empty dataset still writes one empty
        file so the directory round-trips."""
        import os as _os

        _os.makedirs(path, exist_ok=True)
        refs = self._block_refs
        tasks = [
            _write_block.remote(b, _os.path.join(path, f"part-{i:05d}.{ext}"), fmt)
            for i, b in enumerate(refs)
        ]
        results = ray_tpu.get(tasks, timeout=600)
        written = [p for p, n in results if n > 0]
        if not written and results:
            written = [results[0][0]]
        # Remove files for empty blocks (written then found empty).
        for p, n in results:
            if n == 0 and p not in written:
                try:
                    _os.unlink(p)
                except OSError:
                    pass
        return written

    def write_datasource(self, datasource) -> List[Any]:
        """Parallel per-block writes through a pluggable Datasource
        (ray: Dataset.write_datasource)."""
        from ray_tpu.data.datasource import write_datasource

        return write_datasource(self, datasource)

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet", "parquet")

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv", "csv")

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "json", "json")

    # -- pipelining (ray: python/ray/data/dataset_pipeline.py:65) ----------

    def window(self, *, blocks_per_window: int = 2) -> "DatasetPipeline":
        """Split into windows executed as consumed (plus one window of
        prefetch — see DatasetPipeline.iter_datasets): pinned memory is
        bounded by two windows regardless of dataset size
        (ray: Dataset.window)."""
        base = self._executed if self._executed is not None else self._base_refs
        ops = [] if self._executed is not None else list(self._ops)
        windows = [
            Dataset(base[i : i + blocks_per_window], _ops=ops)
            for i in range(0, len(base), blocks_per_window)
        ] or [Dataset([], _ops=[])]
        return DatasetPipeline(windows, epochs=1)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        """Epoch iteration: the dataset replayed `times` times (None =
        unbounded, ray: Dataset.repeat).  Replays reuse each block's
        memoized fused result."""
        return DatasetPipeline([self], epochs=times)

    def iter_torch_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        prefetch_blocks: int = 4,
        dtypes=None,
        device: Optional[str] = None,
    ) -> Iterator[Any]:
        """Batches as torch tensors (ray: dataset.py:3080 to_torch /
        iter_torch_batches) — same streaming window as iter_batches, with
        the numpy->tensor conversion zero-copy where dtypes allow."""
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size,
            batch_format="numpy",
            drop_last=drop_last,
            prefetch_blocks=prefetch_blocks,
        ):
            def conv(arr, col=None):
                t = torch.as_tensor(arr)
                # dtypes: one dtype for every column, or a per-column dict
                # (both forms of the referenced Ray API).
                dt = dtypes.get(col) if isinstance(dtypes, dict) else dtypes
                if dt is not None:
                    t = t.to(dt)
                if device is not None:
                    t = t.to(device)
                return t

            if isinstance(batch, dict):
                yield {k: conv(v, k) for k, v in batch.items()}
            else:
                yield conv(batch)

    def stats(self) -> str:
        return self.__repr__()

    def __repr__(self):
        # repr must not trigger execution (a lazy pipeline printed in a
        # debugger should stay lazy).
        extra = f", pending_ops={len(self._ops)}" if self._ops else ""
        return f"Dataset(num_blocks={len(self._base_refs)}{extra})"


class DatasetPipeline:
    """Windowed/repeated execution over Datasets
    (ray: python/ray/data/dataset_pipeline.py:65).

    A pipeline is a sequence of windows (each a Dataset) replayed for
    `epochs` epochs (None = unbounded).  Only the window currently being
    consumed executes — window N+1's tasks submit while N's batches drain,
    so memory is bounded by one window regardless of dataset size.
    Transforms apply lazily per window.
    """

    def __init__(self, windows: List[Dataset], epochs: Optional[int] = 1):
        self._windows = list(windows)
        self._epochs = epochs

    # -- transforms (applied to every window, lazily) ----------------------

    def _per_window(self, method: str, *args, **kwargs) -> "DatasetPipeline":
        return DatasetPipeline(
            [getattr(w, method)(*args, **kwargs) for w in self._windows],
            epochs=self._epochs,
        )

    def map(self, fn) -> "DatasetPipeline":
        return self._per_window("map", fn)

    def filter(self, fn) -> "DatasetPipeline":
        return self._per_window("filter", fn)

    def flat_map(self, fn) -> "DatasetPipeline":
        return self._per_window("flat_map", fn)

    def map_batches(self, fn, **kwargs) -> "DatasetPipeline":
        return self._per_window("map_batches", fn, **kwargs)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        if self._epochs is None:
            return self  # already unbounded: repeating cannot extend it
        total = None if times is None else times * self._epochs
        return DatasetPipeline(self._windows, epochs=total)

    # -- consumption -------------------------------------------------------

    @staticmethod
    def _fresh(w: Dataset) -> Dataset:
        """A window clone with an empty execution memo: the consumed
        clone's fused output blocks release as soon as iteration drops it,
        so the pipeline pins at most the in-flight windows — memoizing on
        the shared window objects would keep EVERY window's outputs alive
        for the pipeline's lifetime."""
        return Dataset(w._base_refs, _ops=w._ops)

    def iter_epochs(self) -> Iterator["DatasetPipeline"]:
        """One single-epoch pipeline per epoch (ray: DatasetPipeline
        .iter_epochs) — each epoch replays every window in order."""
        n = self._epochs
        i = 0
        while n is None or i < n:
            yield DatasetPipeline(self._windows, epochs=1)
            i += 1

    def iter_datasets(self) -> Iterator[Dataset]:
        """Windows in epoch order, with ONE window of prefetch: window
        N+1's fused tasks are submitted when window N is handed out, so
        its blocks materialize while N's batches drain (the pipelining
        ray's streaming windows provide), while total pinned memory stays
        bounded by two windows."""
        nxt: Optional[Dataset] = None
        for epoch in self.iter_epochs():
            wins = epoch._windows
            for i, w in enumerate(wins):
                cur = nxt if nxt is not None else self._fresh(w)
                if i + 1 < len(wins):
                    nxt = self._fresh(wins[i + 1])
                    nxt._execute()  # submit ≤ window_size fused tasks now
                else:
                    nxt = None  # epoch boundary: no cross-epoch prefetch
                yield cur

    def iter_rows(self) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_rows()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        """Window boundaries are batch boundaries (each window's final
        short batch is not stitched into the next window — the reference's
        pipeline has the same per-window batching)."""
        for ds in self.iter_datasets():
            yield from ds.iter_batches(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_torch_batches(**kwargs)

    def num_windows(self) -> int:
        return len(self._windows)

    def count(self) -> int:
        """Rows per epoch (executes every window)."""
        return sum(w.count() for w in self._windows)

    def __repr__(self):
        e = "inf" if self._epochs is None else self._epochs
        return f"DatasetPipeline(windows={len(self._windows)}, epochs={e})"
