"""Dataset constructors (ray: python/ray/data/read_api.py).

Readers create one read task per file/partition; blocks land in the object
store owned by the driver.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.data.block import batch_to_rows
from ray_tpu.data.dataset import Dataset


def _to_blocks(items: List[Any], parallelism: int) -> List[Any]:
    # NB: module-level `range()` below shadows the builtin in this module.
    n = max(1, min(parallelism, len(items) or 1))
    size = (len(items) + n - 1) // n if items else 0
    blocks = (
        [items[i * size : (i + 1) * size] for i in builtins.range(n)] if items else [[]]
    )
    return [ray_tpu.put(b) for b in blocks if b or len(blocks) == 1]


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return Dataset(_to_blocks(list(items), parallelism))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001 — API parity
    return from_items(list(builtins.range(n)), parallelism=parallelism)


def from_numpy(arr, *, parallelism: int = 8) -> Dataset:
    """Columnar from the start: shards of {"value": arr} NumpyBlocks."""
    import numpy as np

    from ray_tpu.data.block import NumpyBlock

    arr = np.asarray(arr)
    n = max(1, min(parallelism, len(arr) or 1))
    size = (len(arr) + n - 1) // n if len(arr) else 0
    slices = [
        arr[i * size : (i + 1) * size] for i in builtins.range(n)
    ] if size else []
    blocks = [NumpyBlock({"value": s}) for s in slices if len(s)] or [
        NumpyBlock({"value": arr})
    ]
    return Dataset([ray_tpu.put(b) for b in blocks])


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    return from_items(df.to_dict("records"), parallelism=parallelism)


def from_arrow(table, *, parallelism: int = 8) -> Dataset:
    return from_items(table.to_pylist(), parallelism=parallelism)


@ray_tpu.remote
def _read_parquet_file(path: str, columns):
    """Parquet → columnar NumpyBlock (stays columnar through map_batches /
    iter_batches; ray: datasource/parquet_datasource.py reads Arrow blocks)."""
    import pyarrow.parquet as pq

    from ray_tpu.data.block import NumpyBlock

    table = pq.read_table(path, columns=columns)
    return NumpyBlock({name: table[name].to_numpy() for name in table.column_names})


@ray_tpu.remote
def _read_csv_file(path: str) -> List[Dict]:
    import pyarrow.csv as pacsv

    return pacsv.read_csv(path).to_pylist()


@ray_tpu.remote
def _read_json_file(path: str) -> List[Dict]:
    import json

    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def _expand(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "*"))))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    return Dataset([_read_parquet_file.remote(p, columns) for p in _expand(paths)])


def read_csv(paths) -> Dataset:
    return Dataset([_read_csv_file.remote(p) for p in _expand(paths)])


def read_json(paths) -> Dataset:
    return Dataset([_read_json_file.remote(p) for p in _expand(paths)])


def read_text(paths) -> Dataset:
    @ray_tpu.remote
    def _read(path: str) -> List[str]:
        with open(path) as f:
            return [ln.rstrip("\n") for ln in f]

    return Dataset([_read.remote(p) for p in _expand(paths)])
