"""Dataset constructors (ray: python/ray/data/read_api.py).

Readers create one read task per file/partition; blocks land in the object
store owned by the driver.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.data.block import batch_to_rows
from ray_tpu.data.dataset import Dataset


def _shard_bounds(n_rows: int, parallelism: int) -> List[tuple]:
    """Ceil-div row ranges for `parallelism` shards, dropping empties; one
    (0, 0) shard for an empty input so every constructor yields ≥1 block.
    (One helper — from_items/from_numpy/from_arrow sharded identically.)"""
    if n_rows == 0:
        return [(0, 0)]
    n = max(1, min(parallelism, n_rows))
    size = (n_rows + n - 1) // n
    return [
        (i * size, min((i + 1) * size, n_rows))
        for i in builtins.range(n)
        if i * size < n_rows
    ]


def _to_blocks(items: List[Any], parallelism: int) -> List[Any]:
    return [ray_tpu.put(items[s:e]) for s, e in _shard_bounds(len(items), parallelism)]


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return Dataset(_to_blocks(list(items), parallelism))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001 — API parity
    return from_items(list(builtins.range(n)), parallelism=parallelism)


def from_numpy(arr, *, parallelism: int = 8) -> Dataset:
    """Columnar from the start: shards of {"value": arr} NumpyBlocks."""
    import numpy as np

    from ray_tpu.data.block import NumpyBlock

    arr = np.asarray(arr)
    return Dataset(
        [
            ray_tpu.put(NumpyBlock({"value": arr[s:e]}))
            for s, e in _shard_bounds(len(arr), parallelism)
        ]
    )


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    return from_items(df.to_dict("records"), parallelism=parallelism)


def from_arrow(table, *, parallelism: int = 8) -> Dataset:
    """Arrow-native: shards are zero-copy Table.slice views."""
    from ray_tpu.data.block import ArrowBlock

    return Dataset(
        [
            ray_tpu.put(ArrowBlock(table.slice(s, e - s)))
            for s, e in _shard_bounds(table.num_rows, parallelism)
        ]
    )


def _expand(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "*"))))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


# Built-in file readers ride the SAME pluggable path a user datasource
# does (ray: read_parquet -> ParquetDatasource -> read_datasource).


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    from ray_tpu.data.datasource import ParquetDatasource, read_datasource

    return read_datasource(ParquetDatasource(paths, columns))


def read_csv(paths) -> Dataset:
    from ray_tpu.data.datasource import CSVDatasource, read_datasource

    return read_datasource(CSVDatasource(paths))


def read_json(paths) -> Dataset:
    from ray_tpu.data.datasource import JSONDatasource, read_datasource

    return read_datasource(JSONDatasource(paths))


def read_text(paths) -> Dataset:
    from ray_tpu.data.datasource import TextDatasource, read_datasource

    return read_datasource(TextDatasource(paths))
