"""Dataset constructors (ray: python/ray/data/read_api.py).

Readers create one read task per file/partition; blocks land in the object
store owned by the driver.
"""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.data.block import batch_to_rows
from ray_tpu.data.dataset import Dataset


def _shard_bounds(n_rows: int, parallelism: int) -> List[tuple]:
    """Ceil-div row ranges for `parallelism` shards, dropping empties; one
    (0, 0) shard for an empty input so every constructor yields ≥1 block.
    (One helper — from_items/from_numpy/from_arrow sharded identically.)"""
    if n_rows == 0:
        return [(0, 0)]
    n = max(1, min(parallelism, n_rows))
    size = (n_rows + n - 1) // n
    return [
        (i * size, min((i + 1) * size, n_rows))
        for i in builtins.range(n)
        if i * size < n_rows
    ]


def _to_blocks(items: List[Any], parallelism: int) -> List[Any]:
    return [ray_tpu.put(items[s:e]) for s, e in _shard_bounds(len(items), parallelism)]


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    return Dataset(_to_blocks(list(items), parallelism))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001 — API parity
    return from_items(list(builtins.range(n)), parallelism=parallelism)


def from_numpy(arr, *, parallelism: int = 8) -> Dataset:
    """Columnar from the start: shards of {"value": arr} NumpyBlocks."""
    import numpy as np

    from ray_tpu.data.block import NumpyBlock

    arr = np.asarray(arr)
    return Dataset(
        [
            ray_tpu.put(NumpyBlock({"value": arr[s:e]}))
            for s, e in _shard_bounds(len(arr), parallelism)
        ]
    )


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    return from_items(df.to_dict("records"), parallelism=parallelism)


def from_arrow(table, *, parallelism: int = 8) -> Dataset:
    """Arrow-native: shards are zero-copy Table.slice views."""
    from ray_tpu.data.block import ArrowBlock

    return Dataset(
        [
            ray_tpu.put(ArrowBlock(table.slice(s, e - s)))
            for s, e in _shard_bounds(table.num_rows, parallelism)
        ]
    )


@ray_tpu.remote
def _read_parquet_file(path: str, columns):
    """Parquet → ArrowBlock: the table stays Arrow end-to-end (slice /
    map_batches(batch_format="pyarrow") / write_parquet without a row or
    numpy detour; ray: datasource/parquet_datasource.py reads Arrow
    blocks and block.py treats pyarrow.Table as the native block)."""
    import pyarrow.parquet as pq

    from ray_tpu.data.block import ArrowBlock

    return ArrowBlock(pq.read_table(path, columns=columns))


@ray_tpu.remote
def _read_csv_file(path: str) -> List[Dict]:
    import pyarrow.csv as pacsv

    return pacsv.read_csv(path).to_pylist()


@ray_tpu.remote
def _read_json_file(path: str) -> List[Dict]:
    import json

    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def _expand(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(_glob.glob(os.path.join(p, "*"))))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    return Dataset([_read_parquet_file.remote(p, columns) for p in _expand(paths)])


def read_csv(paths) -> Dataset:
    return Dataset([_read_csv_file.remote(p) for p in _expand(paths)])


def read_json(paths) -> Dataset:
    return Dataset([_read_json_file.remote(p) for p in _expand(paths)])


def read_text(paths) -> Dataset:
    @ray_tpu.remote
    def _read(path: str) -> List[str]:
        with open(path) as f:
            return [ln.rstrip("\n") for ln in f]

    return Dataset([_read.remote(p) for p in _expand(paths)])
