"""Blocks: the unit of distributed data (ray: python/ray/data/block.py).

Two physical block forms, one logical interface:

- row blocks: a list of rows (any Python objects; commonly dicts) — the
  universal fallback for heterogeneous data;
- NumpyBlock: a dict of equal-length numpy column arrays — the TPU-relevant
  tabular fast path.  Columnar blocks move through the object store as
  pickle-5 out-of-band buffers (zero-copy via the shm store), slice without
  row materialization, and hand `iter_batches` ready dict-of-array batches
  for `device_put`.  map_batches(batch_format="numpy") keeps data columnar
  end-to-end; converting to rows happens only when an op needs rows
  (map/filter/sort/groupby).

BlockAccessor converts between the forms at the edges; the execution engine
(dataset.py) is form-agnostic through the helpers below.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Union


class NumpyBlock:
    """Columnar block: dict of equal-length numpy arrays."""

    __slots__ = ("columns",)

    def __init__(self, columns: Dict[str, Any]):
        import numpy as np

        self.columns = {k: np.asarray(v) for k, v in columns.items()}
        lens = {len(v) for v in self.columns.values()}
        if len(lens) > 1:
            raise ValueError(f"ragged columns: { {k: len(v) for k, v in self.columns.items()} }")

    def __len__(self) -> int:
        for v in self.columns.values():
            return len(v)
        return 0

    def slice(self, start: int, end: int) -> "NumpyBlock":
        return NumpyBlock({k: v[start:end] for k, v in self.columns.items()})

    def __iter__(self):
        # Row iteration (slow path) — only taken by row-oriented ops.
        return iter(batch_to_rows(self.columns))

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return NumpyBlock({k: v[idx] for k, v in self.columns.items()})
        return {k: _unwrap(v[idx]) for k, v in self.columns.items()}

    def __reduce__(self):
        return (NumpyBlock, (self.columns,))


class ArrowBlock:
    """Arrow-table-backed block (ray: the reference's default block format
    is pyarrow.Table — block.py BlockAccessor.for_block dispatch).

    Zero-copy slicing via Table.slice, columnar hand-off to numpy/pandas
    batches, and parquet/csv writes without a row detour.  Pickles via
    Arrow IPC (buffers travel out-of-band through the shm store)."""

    __slots__ = ("table",)

    def __init__(self, table):
        self.table = table

    def __len__(self) -> int:
        return self.table.num_rows

    def slice(self, start: int, end: int) -> "ArrowBlock":
        start = max(0, start)
        return ArrowBlock(self.table.slice(start, max(end - start, 0)))

    def __iter__(self):
        return iter(self.table.to_pylist())

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            lo, hi, _ = idx.indices(len(self))
            return self.slice(lo, hi)
        # Scalar take per column — NOT to_pydict(), which would convert the
        # whole table to Python per row access.
        return {
            name: self.table[name][idx].as_py()
            for name in self.table.column_names
        }

    def __reduce__(self):
        import pyarrow as pa

        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, self.table.schema) as w:
            w.write_table(self.table)
        return (_arrow_from_ipc, (sink.getvalue(),))


def _arrow_from_ipc(buf):
    import pyarrow as pa

    with pa.ipc.open_stream(buf) as r:
        return ArrowBlock(r.read_all())


Block = Union[List[Any], NumpyBlock, ArrowBlock]


def block_len(block: Block) -> int:
    return len(block)


def block_slice(block: Block, start: int, end: int) -> Block:
    if isinstance(block, (NumpyBlock, ArrowBlock)):
        return block.slice(start, end)
    return block[start:end]


def block_rows(block: Block) -> List[Any]:
    if isinstance(block, NumpyBlock):
        return batch_to_rows(block.columns)
    if isinstance(block, ArrowBlock):
        return block.table.to_pylist()
    return block


def concat_blocks(blocks: List[Block]) -> Block:
    """Concatenate, staying columnar when every input is columnar with the
    same schema."""
    import numpy as np

    blocks = [b for b in blocks if len(b)]
    if not blocks:
        return []
    if len(blocks) == 1:
        return blocks[0]  # zero-copy: np.concatenate([x]) would copy
    if all(isinstance(b, NumpyBlock) for b in blocks) and len(
        {tuple(sorted(b.columns)) for b in blocks}
    ) == 1:
        return NumpyBlock(
            {
                k: np.concatenate([b.columns[k] for b in blocks])
                for k in blocks[0].columns
            }
        )
    if all(isinstance(b, ArrowBlock) for b in blocks) and len(
        {tuple(b.table.column_names) for b in blocks}
    ) == 1:
        import pyarrow as pa

        return ArrowBlock(pa.concat_tables([b.table for b in blocks]))
    out: List[Any] = []
    for b in blocks:
        out.extend(block_rows(b))
    return out


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    def num_rows(self) -> int:
        return len(self.block)

    def to_rows(self) -> List[Any]:
        return block_rows(self.block)

    def to_batch(self, batch_format: str = "numpy"):
        if isinstance(self.block, NumpyBlock):
            if batch_format in ("numpy", "dict"):
                return dict(self.block.columns)
            if batch_format == "pandas":
                import pandas as pd

                return pd.DataFrame(self.block.columns)
            if batch_format == "pyarrow":
                import pyarrow as pa

                return pa.table(dict(self.block.columns))
            raise ValueError(f"unknown batch_format {batch_format!r}")
        if isinstance(self.block, ArrowBlock):
            t = self.block.table
            if batch_format == "pyarrow":
                return t
            if batch_format in ("numpy", "dict"):
                return {
                    name: t[name].to_numpy(zero_copy_only=False)
                    for name in t.column_names
                }
            if batch_format == "pandas":
                return t.to_pandas()
            raise ValueError(f"unknown batch_format {batch_format!r}")
        rows = self.block
        if batch_format in ("numpy", "dict"):
            return rows_to_numpy_batch(rows)
        if batch_format == "pandas":
            import pandas as pd

            if rows and isinstance(rows[0], dict):
                return pd.DataFrame(rows)
            return pd.DataFrame({"value": rows})
        if batch_format == "pyarrow":
            import pyarrow as pa

            if rows and isinstance(rows[0], dict):
                return pa.Table.from_pylist(rows)
            return pa.table({"value": rows})
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def schema(self):
        if isinstance(self.block, NumpyBlock):
            return {k: str(v.dtype) for k, v in self.block.columns.items()}
        if isinstance(self.block, ArrowBlock):
            t = self.block.table
            return {f.name: str(f.type) for f in t.schema}
        if not self.block:
            return None
        row = self.block[0]
        if isinstance(row, dict):
            return {k: type(v).__name__ for k, v in row.items()}
        return type(row).__name__


def rows_to_numpy_batch(rows: List[Any]) -> Dict[str, Any]:
    import numpy as np

    if rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return {"value": np.asarray(rows)}


def batch_to_rows(batch: Any) -> List[Any]:
    """Invert to_batch for any supported batch format."""
    import numpy as np

    if isinstance(batch, dict):
        keys = list(batch.keys())
        if not keys:
            return []
        n = len(batch[keys[0]])
        if keys == ["value"]:
            return [_unwrap(batch["value"][i]) for i in range(n)]
        return [{k: _unwrap(batch[k][i]) for k in keys} for i in range(n)]
    if isinstance(batch, list):
        return batch
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return batch.to_dict("records")
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return batch.to_pylist()
    except ImportError:
        pass
    raise TypeError(f"unsupported batch type {type(batch)}")


def _unwrap(x):
    import numpy as np

    if isinstance(x, np.generic):
        return x.item()
    return x
