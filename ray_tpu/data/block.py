"""Blocks: the unit of distributed data (ray: python/ray/data/block.py).

A block is a list of rows (any Python objects; commonly dicts for tabular
data) stored as one object in the object store.  BlockAccessor converts
between row and batch ("numpy" dict-of-arrays / "pandas" / "pyarrow")
formats at the edges; internally everything moves as row lists, which keeps
the execution engine format-agnostic.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

Block = List[Any]


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    def num_rows(self) -> int:
        return len(self.block)

    def to_rows(self) -> List[Any]:
        return self.block

    def to_batch(self, batch_format: str = "numpy"):
        rows = self.block
        if batch_format in ("numpy", "dict"):
            return rows_to_numpy_batch(rows)
        if batch_format == "pandas":
            import pandas as pd

            if rows and isinstance(rows[0], dict):
                return pd.DataFrame(rows)
            return pd.DataFrame({"value": rows})
        if batch_format == "pyarrow":
            import pyarrow as pa

            if rows and isinstance(rows[0], dict):
                return pa.Table.from_pylist(rows)
            return pa.table({"value": rows})
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def schema(self):
        if not self.block:
            return None
        row = self.block[0]
        if isinstance(row, dict):
            return {k: type(v).__name__ for k, v in row.items()}
        return type(row).__name__


def rows_to_numpy_batch(rows: List[Any]) -> Dict[str, Any]:
    import numpy as np

    if rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return {"value": np.asarray(rows)}


def batch_to_rows(batch: Any) -> List[Any]:
    """Invert to_batch for any supported batch format."""
    import numpy as np

    if isinstance(batch, dict):
        keys = list(batch.keys())
        if not keys:
            return []
        n = len(batch[keys[0]])
        if keys == ["value"]:
            return [batch["value"][i] for i in range(n)]
        return [{k: _unwrap(batch[k][i]) for k in keys} for i in range(n)]
    if isinstance(batch, list):
        return batch
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return batch.to_dict("records")
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return batch.to_pylist()
    except ImportError:
        pass
    raise TypeError(f"unsupported batch type {type(batch)}")


def _unwrap(x):
    import numpy as np

    if isinstance(x, np.generic):
        return x.item()
    return x
