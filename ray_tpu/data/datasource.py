"""Datasource: the pluggable read/write surface.

ray: python/ray/data/datasource/datasource.py — a Datasource yields
ReadTasks (serializable zero-arg callables, one per block/partition) that
execute as distributed tasks; custom sources (databases, object stores,
proprietary formats) plug into `read_datasource()` without touching the
engine.  Writes mirror it: `Dataset.write_datasource()` runs
`datasource.write_block(block, index)` once per block, in parallel.

The built-in file readers (read_parquet/csv/json/text) are expressed as
FileBasedDatasource subclasses, so they exercise the same plugin path a
user source does.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ReadTask:
    """One unit of read parallelism: a serializable callable returning a
    Block (ray: datasource.py ReadTask).  `metadata` is free-form (row
    counts, input files) surfaced for debugging."""

    def __init__(self, read_fn: Callable[[], Any], metadata: Optional[dict] = None):
        self._fn = read_fn
        self.metadata = metadata or {}

    def __call__(self):
        return self._fn()


class Datasource:
    """Interface: override get_read_tasks (and optionally write_block)."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def write_block(self, block, index: int) -> Any:
        """One block -> one output partition (return value surfaced to the
        caller, e.g. a path).  Optional: read-only sources skip it."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement writes"
        )


class FileBasedDatasource(Datasource):
    """One ReadTask per file; subclasses implement _read_file(path).
    ray: datasource/file_based_datasource.py."""

    def __init__(self, paths):
        from ray_tpu.data.read_api import _expand

        self.paths = _expand(paths)

    def _read_file(self, path: str):
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        read = self._read_file
        return [
            ReadTask(
                (lambda p=p: read(p)),
                metadata={"input_files": [p]},
            )
            for p in self.paths
        ]


class ParquetDatasource(FileBasedDatasource):
    def __init__(self, paths, columns: Optional[List[str]] = None):
        super().__init__(paths)
        self.columns = columns

    def _read_file(self, path: str):
        import pyarrow.parquet as pq

        from ray_tpu.data.block import ArrowBlock

        return ArrowBlock(pq.read_table(path, columns=self.columns))


class CSVDatasource(FileBasedDatasource):
    def _read_file(self, path: str):
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path).to_pylist()


class JSONDatasource(FileBasedDatasource):
    def _read_file(self, path: str):
        import json

        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return rows


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path: str):
        with open(path) as f:
            return [ln.rstrip("\n") for ln in f]


@ray_tpu.remote
def _run_read_task(task: ReadTask):
    return task()


@ray_tpu.remote
def _run_write_block(datasource: Datasource, block, index: int):
    return datasource.write_block(block, index)


def read_datasource(datasource: Datasource, *, parallelism: int = 8):
    """Execute a datasource's read plan as distributed tasks
    (ray: read_api.py read_datasource)."""
    from ray_tpu.data.dataset import Dataset

    tasks = datasource.get_read_tasks(parallelism)
    if not tasks:
        return Dataset([ray_tpu.put([])])
    return Dataset([_run_read_task.remote(t) for t in tasks])


def write_datasource(dataset, datasource: Datasource) -> List[Any]:
    """One write_block task per block, in parallel; returns the per-block
    results (ray: Dataset.write_datasource)."""
    return ray_tpu.get(
        [
            _run_write_block.remote(datasource, b, i)
            for i, b in enumerate(dataset._block_refs)
        ]
    )
