"""Trial: one hyperparameter configuration's lifecycle.

ray: python/ray/tune/experiment/trial.py:190 (Trial) — reduced to the fields
the runner/schedulers/persistence actually use.  Status FSM:
PENDING -> RUNNING -> {TERMINATED, ERROR, PAUSED} ; PAUSED -> PENDING
(PBT exploit restarts a paused trial with a mutated config + donor
checkpoint).
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    config: Dict[str, Any]
    trial_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:8]
    )
    status: str = PENDING
    last_result: Optional[Dict[str, Any]] = None
    metrics_history: list = dataclasses.field(default_factory=list)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    num_failures: int = 0
    # iteration counter maintained by the runner (1 per report)
    training_iteration: int = 0
    # scheduler bookkeeping survives checkpoint/restore via __dict__ pickling
    stopped_early: bool = False
    # history/iteration high-water marks at the last checkpointed report —
    # a failure retry truncates back to these so resumed runs don't
    # duplicate steps in metrics_history
    ckpt_history_len: int = 0
    ckpt_training_iteration: int = 0

    def metric_value(self, metric: str) -> Optional[float]:
        if self.last_result is None:
            return None
        v = self.last_result.get(metric)
        return None if v is None else float(v)

    @property
    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, it={self.training_iteration})"
