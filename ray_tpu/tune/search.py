"""Search spaces + searchers.

ray: python/ray/tune/search/ — sample.py (Domain/grid_search/choice/uniform/
loguniform/randint), basic_variant.py (BasicVariantGenerator: grid
cross-product x num_samples random draws).  Optuna/hyperopt adapters are out
of scope (external deps); the Searcher ABC gives the same plug-in seam.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


# -- domains ----------------------------------------------------------------


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        assert low > 0 and high > low
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


class Randint(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class SampleFrom(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


# -- variant generation -----------------------------------------------------


def _split_spec(spec: Dict) -> tuple:
    """Walk a (possibly nested) param space; return (grid_paths, sample_paths)."""
    grids: List[tuple] = []
    samples: List[tuple] = []

    def walk(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (k,))
        elif isinstance(node, GridSearch):
            grids.append((path, node))
        elif isinstance(node, Domain):
            samples.append((path, node))

    walk(spec, ())
    return grids, samples


def _set_path(d: Dict, path: tuple, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _copy_spec(node):
    if isinstance(node, dict):
        return {k: _copy_spec(v) for k, v in node.items()}
    return node


class Searcher:
    """ray: python/ray/tune/search/searcher.py — the plug-in seam."""

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]):
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict):
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[Dict], error: bool):
        pass

    def save_state(self) -> Dict:
        return {}

    def restore_state(self, state: Dict):
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product x num_samples random draws
    (ray: tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict, num_samples: int = 1, seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = list(self._generate())
        self._next = 0

    def _generate(self) -> Iterator[Dict]:
        grids, samples = _split_spec(self.param_space)
        grid_axes = [
            [(path, v) for v in gs.values] for path, gs in grids
        ] or [[]]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grid_axes) if grids else [()]:
                cfg = _copy_spec(self.param_space)
                for path, value in combo:
                    _set_path(cfg, path, value)
                for path, dom in samples:
                    _set_path(cfg, path, dom.sample(self.rng))
                # strip any leftover Domain objects (fixed values pass through)
                yield cfg

    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg

    def save_state(self) -> Dict:
        return {"next": self._next, "rng": self.rng.getstate()}

    def restore_state(self, state: Dict):
        self._next = state["next"]
        self.rng.setstate(state["rng"])


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator — the model-based search algorithm
    behind optuna/hyperopt (ray: tune/search/optuna/optuna_search.py and
    search/hyperopt/ adapt external implementations; here the estimator is
    native, so model-based search needs no extra dependency).

    After `n_initial` random trials, observations split into good/bad by
    the `gamma` quantile; each dimension gets a Parzen (kernel-density)
    estimator per group, candidates are drawn from the good-group density
    and ranked by the density ratio l(x)/g(x) (dimensions treated
    independently, as in the original TPE formulation).
    """

    def __init__(
        self,
        param_space: Dict,
        num_samples: int = 32,
        n_initial: int = 8,
        gamma: float = 0.25,
        n_candidates: int = 24,
        seed: Optional[int] = None,
    ):
        self.param_space = param_space
        self.num_samples = num_samples
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self.metric: Optional[str] = None
        self.mode: str = "max"
        self._suggested = 0
        self._obs: List[tuple] = []  # (flat_values dict, score: higher=better)
        grids, samples = _split_spec(param_space)
        # Grid values participate as categorical dims (TPE has no notion
        # of exhaustive sweeps).
        self._dims: Dict[tuple, Domain] = {p: d for p, d in samples}
        for p, gs in grids:
            self._dims[p] = Categorical(gs.values)

    # -- per-dimension density machinery --------------------------------
    def _to_internal(self, dom: Domain, v):
        if isinstance(dom, LogUniform):
            return math.log(v)
        return v

    def _from_internal(self, dom: Domain, v):
        if isinstance(dom, LogUniform):
            return math.exp(v)
        if isinstance(dom, Randint):
            return int(min(dom.high - 1, max(dom.low, round(v))))
        return v

    def _bounds(self, dom: Domain):
        if isinstance(dom, LogUniform):
            return math.log(dom.low), math.log(dom.high)
        if isinstance(dom, (Uniform, Randint)):
            return dom.low, dom.high
        return None

    def _kde_logpdf(self, xs: List[float], lo: float, hi: float, x: float) -> float:
        """Parzen estimator: Gaussian kernels at each observation, plus a
        uniform prior kernel over the bounds (hyperopt's regularization)."""
        width = max(hi - lo, 1e-12)
        bw = max(width / max(math.sqrt(len(xs)), 1.0), 1e-3 * width)
        total = 1.0 / width  # the prior kernel
        for c in xs:
            z = (x - c) / bw
            total += math.exp(-0.5 * z * z) / (bw * math.sqrt(2 * math.pi))
        return math.log(total / (len(xs) + 1))

    def _cat_logp(self, vals: List, categories: List, v) -> float:
        n = len(vals)
        k = len(categories)
        count = sum(1 for x in vals if x == v)
        return math.log((count + 1.0) / (n + k))

    # -- Searcher interface ----------------------------------------------
    def set_search_properties(self, metric, mode):
        self.metric, self.mode = metric, mode or "max"

    def _random_config(self) -> Dict:
        cfg = _copy_spec(self.param_space)
        for path, dom in self._dims.items():
            _set_path(cfg, path, dom.sample(self.rng))
        return cfg

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        if len(self._obs) < self.n_initial:
            return self._random_config()

        ranked = sorted(self._obs, key=lambda o: o[1], reverse=True)
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        good, bad = ranked[:n_good], ranked[n_good:] or ranked[-1:]

        cfg = _copy_spec(self.param_space)
        for path, dom in self._dims.items():
            gvals = [o[0][path] for o in good if path in o[0]]
            bvals = [o[0][path] for o in bad if path in o[0]]
            if isinstance(dom, Categorical):
                cands = [self.rng.choice(dom.categories) for _ in range(self.n_candidates)]
                best = max(
                    cands,
                    key=lambda v: self._cat_logp(gvals, dom.categories, v)
                    - self._cat_logp(bvals, dom.categories, v),
                )
                _set_path(cfg, path, best)
                continue
            bounds = self._bounds(dom)
            if bounds is None or not gvals:
                _set_path(cfg, path, dom.sample(self.rng))
                continue
            lo, hi = bounds
            g_int = [self._to_internal(dom, v) for v in gvals]
            b_int = [self._to_internal(dom, v) for v in bvals]
            width = max(hi - lo, 1e-12)
            bw = max(width / max(math.sqrt(len(g_int)), 1.0), 1e-3 * width)
            cands = []
            for _ in range(self.n_candidates):
                center = self.rng.choice(g_int)
                x = min(hi, max(lo, self.rng.gauss(center, bw)))
                cands.append(x)
            best = max(
                cands,
                key=lambda x: self._kde_logpdf(g_int, lo, hi, x)
                - self._kde_logpdf(b_int, lo, hi, x),
            )
            _set_path(cfg, path, self._from_internal(dom, best))
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[Dict], error: bool):
        if error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        flat = {}
        # Record the dims actually suggested (walk the result's config).
        cfg = result.get("config") or {}
        for path in self._dims:
            node = cfg
            ok = True
            for k in path:
                if not isinstance(node, dict) or k not in node:
                    ok = False
                    break
                node = node[k]
            if ok:
                flat[path] = node
        if flat:
            self._obs.append((flat, score))

    def save_state(self) -> Dict:
        return {
            "suggested": self._suggested,
            "obs": list(self._obs),
            "rng": self.rng.getstate(),
        }

    def restore_state(self, state: Dict):
        self._suggested = state["suggested"]
        self._obs = list(state["obs"])
        self.rng.setstate(state["rng"])
