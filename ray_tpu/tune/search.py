"""Search spaces + searchers.

ray: python/ray/tune/search/ — sample.py (Domain/grid_search/choice/uniform/
loguniform/randint), basic_variant.py (BasicVariantGenerator: grid
cross-product x num_samples random draws).  Optuna/hyperopt adapters are out
of scope (external deps); the Searcher ABC gives the same plug-in seam.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


# -- domains ----------------------------------------------------------------


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        assert low > 0 and high > low
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


class Randint(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class SampleFrom(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> Randint:
    return Randint(low, high)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


# -- variant generation -----------------------------------------------------


def _split_spec(spec: Dict) -> tuple:
    """Walk a (possibly nested) param space; return (grid_paths, sample_paths)."""
    grids: List[tuple] = []
    samples: List[tuple] = []

    def walk(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (k,))
        elif isinstance(node, GridSearch):
            grids.append((path, node))
        elif isinstance(node, Domain):
            samples.append((path, node))

    walk(spec, ())
    return grids, samples


def _set_path(d: Dict, path: tuple, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _copy_spec(node):
    if isinstance(node, dict):
        return {k: _copy_spec(v) for k, v in node.items()}
    return node


class Searcher:
    """ray: python/ray/tune/search/searcher.py — the plug-in seam."""

    def set_search_properties(self, metric: Optional[str], mode: Optional[str]):
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict):
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[Dict], error: bool):
        pass

    def save_state(self) -> Dict:
        return {}

    def restore_state(self, state: Dict):
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product x num_samples random draws
    (ray: tune/search/basic_variant.py)."""

    def __init__(self, param_space: Dict, num_samples: int = 1, seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = list(self._generate())
        self._next = 0

    def _generate(self) -> Iterator[Dict]:
        grids, samples = _split_spec(self.param_space)
        grid_axes = [
            [(path, v) for v in gs.values] for path, gs in grids
        ] or [[]]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grid_axes) if grids else [()]:
                cfg = _copy_spec(self.param_space)
                for path, value in combo:
                    _set_path(cfg, path, value)
                for path, dom in samples:
                    _set_path(cfg, path, dom.sample(self.rng))
                # strip any leftover Domain objects (fixed values pass through)
                yield cfg

    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg

    def save_state(self) -> Dict:
        return {"next": self._next, "rng": self.rng.getstate()}

    def restore_state(self, state: Dict):
        self._next = state["next"]
        self.rng.setstate(state["rng"])
