"""TrialRunner: drives trials as actors over the ray_tpu runtime.

ray: python/ray/tune/execution/trial_runner.py:583 (step loop) +
execution/ray_trial_executor.py:195 (trial actor lifecycle).  One actor per
live trial (max_concurrency=2: the trainable blocks one slot, poll() answers
in the other — the same pattern as train worker actors).  Schedulers return
CONTINUE/STOP/RESTART per report; RESTART (PBT exploit) relaunches the actor
with the mutated config + donor checkpoint.

Experiment state (trials, searcher, scheduler) is checkpointed to
<experiment_dir>/experiment_state.pkl after every transition, enabling
Tuner.restore after driver death (ray: tune/execution/experiment_state.py).
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.tune.schedulers import CONTINUE, RESTART, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import Searcher
from ray_tpu.tune.trial import ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial


@ray_tpu.remote(max_concurrency=2)
class _TrialActor:
    """Executes one trial's trainable; buffers tune session reports."""

    def __init__(self, trial_id: str):
        self.trial_id = trial_id
        self.session = None

    def run(self, trainable: Callable, config: Dict, resume_ckpt):
        from ray_tpu.train.session import init_session

        self.session = init_session(
            rank=0,
            world_size=1,
            resume_checkpoint=resume_ckpt,
            experiment_name=self.trial_id,
        )
        try:
            import inspect

            sig = inspect.signature(trainable)
            if len(sig.parameters) == 0:
                trainable()
            else:
                trainable(config)
            self.session.done = True
            return {"ok": True}
        except BaseException:
            self.session.done = True
            raise

    def poll(self) -> Dict[str, Any]:
        if self.session is None:
            return {"reports": [], "done": False}
        return {"reports": self.session.drain(), "done": self.session.done}


class TrialRunner:
    def __init__(
        self,
        trainable: Callable,
        searcher: Searcher,
        scheduler: Optional[TrialScheduler],
        *,
        metric: str,
        mode: str = "max",
        max_concurrent: int = 4,
        resources_per_trial: Optional[Dict[str, float]] = None,
        max_failures: int = 0,
        stop: Optional[Dict[str, float]] = None,
        experiment_dir: str,
        trials: Optional[List[Trial]] = None,
        poll_interval: float = 0.05,
    ):
        self.trainable = trainable
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.metric = metric
        self.mode = mode
        self.max_concurrent = max_concurrent
        self.resources = dict(resources_per_trial or {"CPU": 1.0})
        self.max_failures = max_failures
        self.stop = stop or {}
        self.experiment_dir = experiment_dir
        self.poll_interval = poll_interval
        self.trials: List[Trial] = trials or []
        self._actors: Dict[str, Any] = {}  # trial_id -> actor handle
        self._run_refs: Dict[str, Any] = {}  # trial_id -> run() ref
        # Refs of intentionally killed runs (STOP/RESTART).  Keyed by REF,
        # not trial id: a RESTART relaunches the same trial id immediately,
        # and a trial-id key would leak onto the new run and swallow its
        # real failures (hanging the whole experiment).
        self._killed_refs: List[Any] = []
        self._searcher_done = False
        self.searcher.set_search_properties(metric, mode)
        self.scheduler.set_search_properties(metric, mode)
        os.makedirs(experiment_dir, exist_ok=True)

    # -- main loop ---------------------------------------------------------
    def run(self) -> List[Trial]:
        self._fill_from_searcher()
        while not self._all_finished():
            self._start_pending()
            time.sleep(self.poll_interval)
            self._process_running()
            # Refill AFTER completions so model-based searchers (TPE) see
            # the finished trials' scores before suggesting the next batch
            # — draining suggest() upfront would degrade them to their
            # random warmup for the whole experiment
            # (ray: SearchGenerator queries the searcher incrementally).
            self._fill_from_searcher()
        self.checkpoint_experiment()
        return self.trials

    def _all_finished(self) -> bool:
        return (
            all(t.is_finished for t in self.trials)
            and not self._run_refs
            and self._searcher_done
        )

    def _fill_from_searcher(self):
        """Top the live/pending pool up to max_concurrent from the
        searcher; the rest of the budget stays with the searcher until
        capacity frees."""
        if self._searcher_done:
            return
        while (
            sum(1 for t in self.trials if not t.is_finished)
            < self.max_concurrent
        ):
            t = Trial(config={})
            cfg = self.searcher.suggest(t.trial_id)
            if cfg is None:
                self._searcher_done = True
                break
            t.config = cfg
            self.trials.append(t)

    def _live_count(self) -> int:
        return sum(1 for t in self.trials if t.status == RUNNING)

    def _start_pending(self):
        for t in self.trials:
            if t.status != PENDING:
                continue
            if self._live_count() >= self.max_concurrent:
                break
            self._launch(t)

    def _launch(self, t: Trial):
        res = dict(self.resources)
        opts: Dict[str, Any] = {"num_cpus": res.pop("CPU", 1.0)}
        if res:
            opts["resources"] = res
        actor = _TrialActor.options(**opts).remote(t.trial_id)
        ref = actor.run.remote(self.trainable, dict(t.config), t.checkpoint)
        self._actors[t.trial_id] = actor
        self._run_refs[t.trial_id] = ref
        t.status = RUNNING
        self.checkpoint_experiment()

    def _process_running(self):
        self._drain_killed_refs()
        running = [t for t in self.trials if t.status == RUNNING]
        if not running:
            return
        # Drain reports: fire every poll first so the RPCs run concurrently,
        # then gather — one slow/dying actor must not serialize the round.
        poll_refs = {}
        for t in running:
            try:
                poll_refs[t.trial_id] = self._actors[t.trial_id].poll.remote()
            except Exception:
                poll_refs[t.trial_id] = None
        polls = {}
        for tid, ref in poll_refs.items():
            try:
                polls[tid] = ray_tpu.get(ref, timeout=30) if ref is not None else None
            except Exception:
                polls[tid] = None  # actor died; completion check below
        for t in running:
            p = polls.get(t.trial_id)
            if p:
                for rep in p["reports"]:
                    decision = self._handle_report(t, rep)
                    if decision != CONTINUE:
                        break
        # completion / crash via run refs
        done_pairs = [(tid, ref) for tid, ref in self._run_refs.items()]
        for tid, ref in done_pairs:
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if not ready:
                continue
            t = self._trial(tid)
            self._run_refs.pop(tid, None)
            try:
                ray_tpu.get(ref, timeout=1)
                self._final_drain(t)
                if t.status == RUNNING:
                    self._finish(t, TERMINATED)
                self.searcher.on_trial_complete(tid, t.last_result, error=False)
                self.scheduler.on_trial_complete(t, t.last_result)
            except Exception as e:
                t.num_failures += 1
                if self.max_failures < 0 or t.num_failures <= self.max_failures:
                    self._cleanup_actor(tid)
                    # Drop the failed attempt's post-checkpoint reports so
                    # the resumed run doesn't duplicate steps in
                    # metrics_history (same contract as
                    # DataParallelTrainer's history truncation).
                    del t.metrics_history[t.ckpt_history_len :]
                    t.training_iteration = t.ckpt_training_iteration
                    t.last_result = (
                        t.metrics_history[-1] if t.metrics_history else None
                    )
                    t.status = PENDING  # retry from last checkpoint
                else:
                    t.error = repr(e)
                    self._finish(t, ERROR)
                    self.searcher.on_trial_complete(tid, t.last_result, error=True)
            self.checkpoint_experiment()

    def _drain_killed_refs(self):
        """Consume run refs of intentionally killed actors (their
        ActorDiedError is expected and must not be classified as a trial
        failure)."""
        still = []
        for ref in self._killed_refs:
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if not ready:
                still.append(ref)
                continue
            try:
                ray_tpu.get(ref, timeout=1)
            except Exception:
                pass
        self._killed_refs = still

    def _final_drain(self, t: Trial):
        """A trainable may return between polls: drain reports buffered after
        the last poll round so last_result/checkpoint are never lost."""
        actor = self._actors.get(t.trial_id)
        if actor is None:
            return
        try:
            p = ray_tpu.get(actor.poll.remote(), timeout=30)
        except Exception:
            return
        for rep in p["reports"]:
            self._handle_report(t, rep, final=True)

    def _handle_report(self, t: Trial, rep: Dict, final: bool = False) -> str:
        t.training_iteration += 1
        result = dict(rep["metrics"])
        result.setdefault("training_iteration", t.training_iteration)
        result["trial_id"] = t.trial_id
        result["config"] = dict(t.config)
        t.last_result = result
        t.metrics_history.append(result)
        if rep.get("checkpoint") is not None:
            t.checkpoint = rep["checkpoint"]
            t.ckpt_history_len = len(t.metrics_history)
            t.ckpt_training_iteration = t.training_iteration
        self.searcher.on_trial_result(t.trial_id, result)
        if final:
            # Trainable already returned: record only.  The scheduler is NOT
            # consulted — a PBT RESTART decision here would mutate
            # config/checkpoint (exploit from a donor) and then be discarded,
            # leaving the finished trial reporting a donor's checkpoint.
            return CONTINUE
        decision = self.scheduler.on_trial_result(t, result)
        if decision == CONTINUE and self._should_stop(result):
            decision = STOP
        if decision == STOP:
            self._kill(t.trial_id)
            t.stopped_early = True
            self._finish(t, TERMINATED)
            # Early-stopped trials complete too: searchers that learn from
            # outcomes (search.py plug-in seam) must see every completion.
            self.searcher.on_trial_complete(t.trial_id, t.last_result, error=False)
            self.scheduler.on_trial_complete(t, t.last_result)
        elif decision == RESTART:
            # PBT exploit: scheduler already mutated t.config/t.checkpoint
            self._kill(t.trial_id)
            t.status = PENDING
        return decision

    def _should_stop(self, result: Dict) -> bool:
        for key, threshold in self.stop.items():
            v = result.get(key)
            if v is None:
                continue
            if key == self.metric and self.mode == "min":
                if float(v) <= float(threshold):
                    return True
            elif float(v) >= float(threshold):
                return True
        return False

    # -- helpers -----------------------------------------------------------
    def _trial(self, tid: str) -> Trial:
        return next(t for t in self.trials if t.trial_id == tid)

    def _kill(self, tid: str):
        # Move the run ref out of the completion sweep's view: its eventual
        # ActorDiedError is expected, and a RESTART will reuse the trial id
        # for a fresh run ref immediately.
        ref = self._run_refs.pop(tid, None)
        if ref is not None:
            self._killed_refs.append(ref)
        self._cleanup_actor(tid)

    def _cleanup_actor(self, tid: str):
        actor = self._actors.pop(tid, None)
        if actor is not None:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        # leave any _run_refs entry: the completion sweep consumes + classifies it

    def _finish(self, t: Trial, status: str):
        t.status = status
        self._cleanup_actor(t.trial_id)

    # -- persistence -------------------------------------------------------
    def checkpoint_experiment(self):
        state = {
            "trials": [self._trial_state(t) for t in self.trials],
            "searcher": self.searcher.save_state(),
            "scheduler": self.scheduler.save_state(),
            "metric": self.metric,
            "mode": self.mode,
        }
        tmp = os.path.join(self.experiment_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, os.path.join(self.experiment_dir, "experiment_state.pkl"))

    def _trial_state(self, t: Trial) -> Dict:
        ckpt_path = None
        if t.checkpoint is not None:
            ckpt_path = os.path.join(self.experiment_dir, t.trial_id, "checkpoint")
            if t.checkpoint._dir is None or (
                os.path.abspath(t.checkpoint._dir) != os.path.abspath(ckpt_path)
            ):
                t.checkpoint.to_directory(ckpt_path)
                t.checkpoint = Checkpoint.from_directory(ckpt_path)
        return {
            "trial_id": t.trial_id,
            "config": t.config,
            "status": t.status,
            "last_result": t.last_result,
            "metrics_history": t.metrics_history,
            "error": t.error,
            "num_failures": t.num_failures,
            "training_iteration": t.training_iteration,
            "stopped_early": t.stopped_early,
            "checkpoint_path": ckpt_path,
        }

    @staticmethod
    def load_experiment(experiment_dir: str) -> Dict:
        with open(os.path.join(experiment_dir, "experiment_state.pkl"), "rb") as f:
            return pickle.load(f)

    @staticmethod
    def trials_from_state(state: Dict, *, restart_errored: bool = False) -> List[Trial]:
        trials = []
        for ts in state["trials"]:
            t = Trial(config=ts["config"], trial_id=ts["trial_id"])
            t.status = ts["status"]
            t.last_result = ts["last_result"]
            t.metrics_history = ts["metrics_history"] or []
            t.error = ts["error"]
            t.num_failures = ts["num_failures"]
            t.training_iteration = ts["training_iteration"]
            t.stopped_early = ts["stopped_early"]
            if ts["checkpoint_path"] and os.path.isdir(ts["checkpoint_path"]):
                t.checkpoint = Checkpoint.from_directory(ts["checkpoint_path"])
            if t.status in (RUNNING, PAUSED):
                t.status = PENDING  # was live when the driver died: resume
            if t.status == ERROR and restart_errored:
                t.status = PENDING
                t.error = None
                t.num_failures = 0
            trials.append(t)
        return trials
