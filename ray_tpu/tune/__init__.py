"""ray_tpu.tune: hyperparameter search over the actor runtime.

ray: python/ray/tune/ (tuner.py:47 Tuner, execution/trial_runner.py:583,
schedulers/async_hyperband.py, schedulers/pbt.py, search/basic_variant.py).

The trial session re-uses the train session plumbing: `tune.report()` inside
a trial function is the same facade as `train.session.report()`, so a
DataParallelTrainer running inside a trial actor streams its rank-0 reports
up to the tune scheduler automatically.
"""

from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner, run

# user-facing in-trial facade (ray: ray.air.session / ray.tune.report)
from ray_tpu.train.session import (
    get_checkpoint,
    report,
)

ASHAScheduler = AsyncHyperBandScheduler  # reference alias

__all__ = [
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "ResultGrid",
    "Searcher",
    "TPESearcher",
    "Trial",
    "TrialScheduler",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "run",
    "sample_from",
    "uniform",
]
