"""Tuner: the user-facing entry point.

ray: python/ray/tune/tuner.py:47 (Tuner, fit :327) + tune/result_grid.py.
Accepts a function trainable or a DataParallelTrainer (the trainer runs
inside the trial actor and spawns its own SPMD worker group — nested actor
creation, the TPU analogue of the reference wrapping trainers in trainables
at base_trainer.py:538).
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air.config import RunConfig
from ray_tpu.air.result import Result
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trial import ERROR, TERMINATED, Trial
from ray_tpu.tune.trial_runner import TrialRunner


@dataclasses.dataclass
class TuneConfig:
    """ray: python/ray/tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    resources_per_trial: Optional[Dict[str, float]] = None
    seed: Optional[int] = None


class ResultGrid:
    """ray: python/ray/tune/result_grid.py."""

    def __init__(self, trials: List[Trial], metric: str, mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        return self._to_result(self._trials[i])

    def _to_result(self, t: Trial) -> Result:
        err = RuntimeError(t.error) if t.error else None
        return Result(
            metrics=t.last_result,
            checkpoint=t.checkpoint,
            error=err,
            metrics_history=t.metrics_history,
        )

    @property
    def trials(self) -> List[Trial]:
        return self._trials

    @property
    def errors(self) -> List[Result]:
        return [self._to_result(t) for t in self._trials if t.status == ERROR]

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [
            t for t in self._trials if t.last_result and t.last_result.get(metric) is not None
        ]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        best = (max if mode == "max" else min)(
            scored, key=lambda t: float(t.last_result[metric])
        )
        return self._to_result(best)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([t.last_result or {} for t in self._trials])


def _as_trainable(trainable) -> Callable:
    """Function trainables pass through; trainers wrap into one."""
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

    if isinstance(trainable, DataParallelTrainer):
        trainer = trainable

        def trainer_trainable(config: Dict[str, Any]):
            import copy

            t = copy.copy(trainer)
            tlc = dict(t.train_loop_config or {})
            overrides = config.get("train_loop_config")
            if overrides:
                tlc.update(overrides)
            else:
                # flat param spaces map straight into the train loop config
                tlc.update({k: v for k, v in config.items() if k != "scaling_config"})
            t.train_loop_config = tlc
            if "scaling_config" in config:
                t.scaling_config = config["scaling_config"]
            from ray_tpu.train.session import get_checkpoint

            t.resume_from_checkpoint = get_checkpoint() or t.resume_from_checkpoint
            result = t.fit()
            if result.error is not None:
                raise result.error

        return trainer_trainable
    if callable(trainable):
        return trainable
    raise TypeError(f"trainable must be callable or a trainer, got {type(trainable)}")


class Tuner:
    def __init__(
        self,
        trainable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
        _trials: Optional[List[Trial]] = None,
    ):
        self._orig_trainable = trainable
        self._trainable = _as_trainable(trainable)
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restored_trials = _trials

    def _experiment_dir(self) -> str:
        rc = self._run_config
        base = rc.storage_path or os.path.join(tempfile.gettempdir(), "ray_tpu_results")
        name = rc.name or "tune_experiment"
        return os.path.join(base, name)

    def fit(self) -> ResultGrid:
        import ray_tpu

        ray_tpu._auto_init()
        tc = self._tune_config
        metric = tc.metric or "_metric"
        searcher = tc.search_alg or BasicVariantGenerator(
            self._param_space, num_samples=tc.num_samples, seed=tc.seed
        )
        if self._restored_trials is not None:
            # searcher already exhausted in the original run
            searcher = _ExhaustedSearcher()
        max_concurrent = tc.max_concurrent_trials
        if max_concurrent is None:
            try:
                cpus = ray_tpu.cluster_resources().get("CPU", 4.0)
            except Exception:
                cpus = 4.0
            per = (tc.resources_per_trial or {"CPU": 1.0}).get("CPU", 1.0) or 1.0
            # A trainer trainable spawns nested worker actors from inside
            # the trial; their CPUs must count against per-trial demand or
            # the trial actors alone saturate the cluster and the nested
            # workers deadlock in the scheduler queue.
            from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

            if isinstance(self._orig_trainable, DataParallelTrainer):
                sc = self._orig_trainable.scaling_config
                if sc is not None:
                    per += sc.num_workers * sc.worker_resources().get("CPU", 1.0)
            max_concurrent = max(1, int(cpus // per))
        failure_cfg = self._run_config.failure_config
        runner = TrialRunner(
            self._trainable,
            searcher,
            tc.scheduler,
            metric=metric,
            mode=tc.mode,
            max_concurrent=max_concurrent,
            resources_per_trial=tc.resources_per_trial,
            max_failures=failure_cfg.max_failures if failure_cfg else 0,
            stop=getattr(self._run_config, "stop", None),
            experiment_dir=self._experiment_dir(),
            trials=self._restored_trials,
        )
        trials = runner.run()
        return ResultGrid(trials, metric, tc.mode)

    @classmethod
    def restore(
        cls,
        path: str,
        trainable,
        *,
        restart_errored: bool = False,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ) -> "Tuner":
        """Rebuild a Tuner from <experiment_dir> after driver death
        (ray: tuner.py Tuner.restore)."""
        state = TrialRunner.load_experiment(path)
        trials = TrialRunner.trials_from_state(state, restart_errored=restart_errored)
        tc = tune_config or TuneConfig()
        tc.metric = tc.metric or state.get("metric")
        tc.mode = state.get("mode", tc.mode)
        rc = run_config or RunConfig()
        rc.storage_path = rc.storage_path or os.path.dirname(path)
        rc.name = rc.name or os.path.basename(path)
        return cls(
            trainable,
            param_space=param_space,
            tune_config=tc,
            run_config=rc,
            _trials=trials,
        )


class _ExhaustedSearcher(Searcher):
    def suggest(self, trial_id: str):
        return None


def run(
    trainable,
    *,
    config: Optional[Dict[str, Any]] = None,
    metric: Optional[str] = None,
    mode: str = "max",
    num_samples: int = 1,
    scheduler: Optional[TrialScheduler] = None,
    stop: Optional[Dict[str, float]] = None,
    **kwargs,
) -> ResultGrid:
    """Legacy convenience API (ray: python/ray/tune/tune.py tune.run)."""
    rc = RunConfig()
    if stop is not None:
        rc.stop = stop
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples, scheduler=scheduler,
            **{k: v for k, v in kwargs.items() if k in TuneConfig.__dataclass_fields__},
        ),
        run_config=rc,
    )
    return tuner.fit()
