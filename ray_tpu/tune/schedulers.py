"""Trial schedulers: FIFO, ASHA early stopping, Population Based Training.

ray: python/ray/tune/schedulers/trial_scheduler.py (decision protocol),
async_hyperband.py (AsyncHyperBandScheduler/ASHA), pbt.py
(PopulationBasedTraining).  Differences by design: our function trainables
cannot pause in place, so PBT's exploit is expressed as a RESTART decision —
the runner kills the trial actor and relaunches it with the mutated config
and the donor's checkpoint (the reference does the same for function
trainables via checkpoint+restore).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
RESTART = "RESTART"  # PBT exploit: relaunch with trial.config/trial.checkpoint


class TrialScheduler:
    def set_search_properties(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        v = float(v)
        return v if self.mode == "max" else -v

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, result: Optional[Dict]):
        pass

    def save_state(self) -> Dict:
        return dict(self.__dict__)

    def restore_state(self, state: Dict):
        self.__dict__.update(state)


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (ray: trial_scheduler.py FIFOScheduler)."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (ray: tune/schedulers/async_hyperband.py).

    Rungs at grace_period * reduction_factor^k.  When a trial reports at or
    past a rung it hasn't been judged at, its metric is recorded; if it falls
    outside the top 1/reduction_factor of everything recorded at that rung,
    it is stopped.  Asynchronous: no waiting for a full bracket.
    """

    def __init__(
        self,
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        reduction_factor: float = 3,
        max_t: int = 100,
        brackets: int = 1,
    ):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung milestones (ascending), excluding max_t itself
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(int(t))
            t *= reduction_factor
        # rung -> {trial_id: score}
        self.rungs: Dict[int, Dict[str, float]] = {m: {} for m in self.milestones}

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP  # budget exhausted (normal completion, not demotion)
        decision = CONTINUE
        for m in self.milestones:
            if t < m:
                break
            rung = self.rungs[m]
            if trial.trial_id in rung:
                continue
            rung[trial.trial_id] = score
            if len(rung) > 1:
                cutoff_idx = max(0, int(len(rung) / self.rf) - 1)
                cutoff = sorted(rung.values(), reverse=True)[cutoff_idx]
                if score < cutoff:
                    decision = STOP
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running best is below the median of completed
    averages at the same step (ray: tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration", grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None or t < self.grace_period:
            return CONTINUE
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(score)
        others = [sum(v) / len(v) for k, v in self._avgs.items() if k != trial.trial_id]
        if len(others) < self.min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(hist)
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (ray: tune/schedulers/pbt.py).

    Every perturbation_interval steps a trial's score is recorded.  Trials in
    the bottom quantile exploit a random top-quantile donor: copy its latest
    checkpoint, mutate the donor's hyperparameters (x0.8 / x1.2 for numeric,
    resample for categorical), and RESTART.
    """

    def __init__(
        self,
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}
        # trial_id -> (config, checkpoint) of latest exploitable state
        self._states: Dict[str, tuple] = {}
        self.num_perturbations = 0

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return CONTINUE
        self._scores[trial.trial_id] = score
        self._states[trial.trial_id] = (dict(trial.config), trial.checkpoint)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        if n < 2:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id not in bottom:
            return CONTINUE
        donor_id = self.rng.choice([tid for tid in top if tid != trial.trial_id] or top)
        donor_cfg, donor_ckpt = self._states.get(donor_id, (None, None))
        if donor_cfg is None:
            return CONTINUE
        # exploit + explore: mutate the donor's config in place on the trial
        new_cfg = dict(trial.config)
        new_cfg.update(donor_cfg)
        new_cfg = self._explore(new_cfg)
        trial.config = new_cfg
        if donor_ckpt is not None:
            trial.checkpoint = donor_ckpt
        self.num_perturbations += 1
        return RESTART

    def _explore(self, cfg: Dict) -> Dict:
        """Perturb an exploited config (hook: PB2 replaces this with a
        GP-bandit choice for its bounded params)."""
        for key, spec in self.mutations.items():
            cfg[key] = self._mutate(cfg.get(key), spec)
        return cfg

    def _mutate(self, current, spec):
        from ray_tpu.tune.search import Domain

        if isinstance(spec, list):
            if current not in spec or self.rng.random() < self.resample_prob:
                return self.rng.choice(spec)
            i = spec.index(current)
            j = min(len(spec) - 1, max(0, i + self.rng.choice([-1, 1])))
            return spec[j]
        if isinstance(spec, Domain):
            return spec.sample(self.rng)
        if callable(spec):
            return spec()
        if isinstance(current, (int, float)):
            factor = self.rng.choice([0.8, 1.2])
            out = current * factor
            return int(out) if isinstance(current, int) else out
        return current

    def save_state(self) -> Dict:
        d = dict(self.__dict__)
        d["rng"] = self.rng.getstate()
        return d

    def restore_state(self, state: Dict):
        rng_state = state.pop("rng", None)
        self.__dict__.update(state)
        self.rng = random.Random()
        if rng_state is not None:
            self.rng.setstate(rng_state)


class PB2(PopulationBasedTraining):
    """PBT with GP-bandit exploration (ray: tune/schedulers/pb2.py).

    Exploit is inherited from PBT (bottom-quantile trials copy a top
    donor's checkpoint); EXPLORE replaces random mutation for bounded
    continuous hyperparams with a Gaussian-process UCB choice fit to
    (hyperparams -> score improvement) history — sample-efficient tuning
    when perturbation budgets are small.  Unbounded/categorical params
    still mutate the PBT way.
    """

    def __init__(
        self,
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_bounds: Optional[Dict[str, tuple]] = None,
        quantile_fraction: float = 0.25,
        ucb_kappa: float = 1.5,
        n_candidates: int = 256,
        seed: Optional[int] = None,
        **kw,
    ):
        super().__init__(
            time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            quantile_fraction=quantile_fraction,
            seed=seed,
            **kw,
        )
        self.bounds = dict(hyperparam_bounds or {})
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        # (hyperparam vector, score delta over the interval) observations
        self._gp_data: list = []
        self._prev_score: Dict[str, float] = {}

    def on_trial_result(self, trial: Trial, result: Dict) -> str:
        score = self._score(result)
        t = result.get(self.time_attr)
        if score is not None and t is not None and self.bounds:
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                x = [float(trial.config.get(k, 0.0)) for k in sorted(self.bounds)]
                self._gp_data.append((x, score - prev))
                self._gp_data = self._gp_data[-256:]
            self._prev_score[trial.trial_id] = score
        decision = super().on_trial_result(trial, result)
        if decision == RESTART:
            # The exploit copies a donor checkpoint: the next report's
            # score jump is the COPY, not the new hyperparams' doing —
            # recording that delta would teach the GP a fiction.
            self._prev_score.pop(trial.trial_id, None)
        return decision

    def _explore(self, cfg: Dict) -> Dict:
        cfg = super()._explore(cfg)  # PBT mutation for non-bounded keys
        return self._explore_config(cfg)

    def _gp_choose(self) -> Optional[Dict[str, float]]:
        if len(self._gp_data) < 4:
            return None
        try:
            import numpy as np
            from sklearn.gaussian_process import GaussianProcessRegressor
            from sklearn.gaussian_process.kernels import Matern
        except Exception:
            return None
        keys = sorted(self.bounds)
        X = np.array([x for x, _ in self._gp_data])
        y = np.array([d for _, d in self._gp_data])
        y = (y - y.mean()) / (y.std() + 1e-9)
        lo = np.array([self.bounds[k][0] for k in keys], dtype=float)
        hi = np.array([self.bounds[k][1] for k in keys], dtype=float)
        span = hi - lo
        gp = GaussianProcessRegressor(
            # The GP sees [0,1]-normalized inputs, so the length scale is
            # in NORMALIZED units — span-scaled values would flatten (or
            # shatter) the kernel and degrade UCB to a random pick.
            kernel=Matern(nu=2.5, length_scale=0.25),
            alpha=1e-3,
            normalize_y=False,
        )
        try:
            gp.fit((X - lo) / span, y)
        except Exception:
            return None
        rngs = np.random.default_rng(self.rng.randrange(1 << 31))
        cand = rngs.uniform(size=(self.n_candidates, len(keys)))
        mu, sigma = gp.predict(cand, return_std=True)
        best = cand[int(np.argmax(mu + self.kappa * sigma))]
        chosen = lo + best * span
        return dict(zip(keys, chosen.tolist()))

    def _explore_config(self, cfg: Dict) -> Dict:
        gp_pick = self._gp_choose()
        for key in self.bounds:
            if gp_pick is not None:
                cfg[key] = gp_pick[key]
            else:
                lo, hi = self.bounds[key]
                cfg[key] = self.rng.uniform(lo, hi)
        return cfg

    # save/restore: PBT serializes __dict__ wholesale, which already
    # covers _gp_data/_prev_score/bounds — no override needed.
