"""Exception hierarchy.

Mirrors the reference's user-facing errors (ray: python/ray/exceptions.py):
TaskError wraps the remote traceback; WorkerCrashedError / ActorDiedError /
ObjectLostError / GetTimeoutError / TaskCancelledError keep the same meaning.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised an exception remotely; re-raised at ray_tpu.get().

    Analogue of ray.exceptions.RayTaskError: carries the remote traceback as
    text and the original cause when it is picklable.
    """

    def __init__(self, task_name: str, remote_tb: str, cause: BaseException | None = None):
        self.task_name = task_name
        self.remote_tb = remote_tb
        self.cause = cause
        super().__init__(f"task {task_name} failed:\n{remote_tb}")

    def __reduce__(self):
        return (TaskError, (self.task_name, self.remote_tb, self.cause))

    @classmethod
    def from_exception(cls, task_name: str, exc: BaseException) -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        try:
            import cloudpickle

            cloudpickle.dumps(exc)
            cause = exc
        except Exception:
            cause = None
        return cls(task_name, tb, cause)


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    pass


class ActorUnavailableError(RayTpuError):
    pass


class ObjectLostError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """The node's memory monitor killed this task's worker under memory
    pressure (ray: ray.exceptions.OutOfMemoryError via memory_monitor.h:52).
    Retriable with its own budget (task_oom_retries) before surfacing."""


class ObjectStoreFullError(RayTpuError):
    """The shm store is at capacity and nothing can be evicted or spilled
    (ray: plasma CreateRequestQueue backpressure → ObjectStoreFullError)."""


class OwnerDiedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class PlacementGroupUnavailableError(RayTpuError):
    pass
