"""Headline benchmark: flagship-model training throughput on real TPU.

Prints ONE JSON line: tokens/sec/chip on a Llama-family decoder train step
(fwd+bwd+adam, bf16 compute), plus achieved MFU.  vs_baseline is achieved
MFU / 0.45 — the north-star target from BASELINE.json ("Llama-7B DDP at
>=45% MFU"); the reference itself has no TPU numbers to compare against
(SURVEY.md §6: GPU-only).

The long-context sweep re-measures the same model shape at seq 2048,
4096, 8192 and 16384 (constant tokens/step — batch halves as sequence
doubles), the regime where the flash-attention backward and remat
policy earn their keep.  The 16k point switches to full per-layer
recompute (remat_policy=None) because the qkv_attn stash overflows
single-chip HBM there — its extra recompute flops are NOT credited, so
compare points via `mfu_attn_incl` (adds 12*L*d*seq flops/token for
the score/value matmuls, fwd+bwd), not the 6ND parameter-MFU.

Model is scaled to fit one chip's HBM (the driver runs single-chip); the
multi-chip path — including ring attention over a seq-sharded mesh — is
exercised by __graft_entry__.dryrun_multichip and tests/test_ops_attention.
"""

from __future__ import annotations

import json
import time


# per-chip dense bf16 peak; longest-prefix keys first ("TPU v5p" must win
# over "TPU v5" under the startswith lookup below)
PEAK_BF16_FLOPS = {
    "TPU v6 lite": 918e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 197e12,
    "TPU v4": 275e12,
}


def _measure(cfg, mesh, batch_size: int, seq: int, steps: int, peak: float):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import LMTrainContext

    ctx = LMTrainContext(cfg, mesh=mesh, strategy="dp")
    state = ctx.init_state(seed=0)
    key = jax.random.PRNGKey(1)
    toks = jax.random.randint(key, (batch_size, seq + 1), 0, cfg.vocab_size)
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}

    # warmup / compile. float() forces a host fetch — block_until_ready alone
    # does not synchronize on the axon TPU platform.
    for _ in range(2):
        state, metrics = ctx.train_step(state, batch)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = ctx.train_step(state, batch)
    # steps chain through donated state, so fetching the last loss implies
    # all prior steps completed.
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_s = steps * batch_size * seq / dt
    n_params = cfg.num_params()
    # 6ND fwd+bwd (+remat recompute ≈ 8ND counted conservatively as 6ND)
    param_flops_per_tok = 6 * n_params
    # score/value matmuls: 4*L*d*seq fwd per token, x3 for fwd+bwd
    attn_flops_per_tok = 12 * cfg.n_layers * cfg.d_model * seq
    del state
    return {
        "tokens_per_s": round(tokens_per_s, 1),
        "mfu": round(param_flops_per_tok * tokens_per_s / peak, 4),
        "mfu_attn_incl": round(
            (param_flops_per_tok + attn_flops_per_tok) * tokens_per_s / peak, 4
        ),
    }


def main():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import TransformerConfig
    from ray_tpu.parallel import MeshSpec, build_mesh

    t_start = time.perf_counter()
    dev = jax.devices()[0]
    peak = next(
        (v for k, v in PEAK_BF16_FLOPS.items() if dev.device_kind.startswith(k)),
        197e12,
    )
    mesh = build_mesh(MeshSpec(data=1), devices=[dev])

    # ~940M params: the widest llama-family shape that fits v5e HBM (16G)
    # with bf16 params + f32 adam moments.  d_model=2048 maps onto the MXU
    # far better than deeper/narrower configs (measured: d1536/L24 -> 0.46
    # MFU, d2048/L16 -> 0.51 on v5e).  remat saves post-rope q/k/v + the
    # flash-attention output, recomputing only the cheap matmuls in bwd.
    # bs16 x seq1024 beats bs8 x seq2048 at equal tokens/step (0.578 vs
    # 0.518 measured): half the quadratic attention work per token, which
    # the 6ND accounting doesn't credit (mfu_attn_incl does).
    def make_cfg(seq_len: int) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=32000,
            d_model=2048,
            n_layers=16,
            n_heads=16,
            n_kv_heads=16,
            d_ff=5504,
            max_seq_len=seq_len,
            param_dtype=jnp.bfloat16,
            remat=True,
            # 16k: the qkv_attn stash (~5 GB) overflows v5e HBM — switch
            # to full per-layer recompute (remat_policy=None), the
            # blockwise/remat long-seq mode (SURVEY §5.7); shorter points
            # keep the faster policy.
            remat_policy=None if seq_len >= 16384 else "qkv_attn",
        )

    head = _measure(make_cfg(1024), mesh, 16, 1024, steps=10, peak=peak)

    # Long-context sweep to 16k: constant 16k tokens/step (batch halves as
    # sequence doubles) — SURVEY §5.7, the axis the reference doesn't
    # have.  The flash kernel streams K/V blocks, so HBM stays flat and
    # no ring/offload switch is needed single-chip through 16k (the
    # seq-sharded ring path is exercised by dryrun_multichip).  Guarded
    # by wall-clock (the driver caps the bench run): skip remaining
    # points if compiles already ate the budget.
    sweep = {}
    for bs, seq in ((8, 2048), (4, 4096), (2, 8192), (1, 16384)):
        if time.perf_counter() - t_start > 420:
            sweep[str(seq)] = {"skipped": "bench time budget"}
            continue
        try:
            sweep[str(seq)] = _measure(
                make_cfg(seq), mesh, bs, seq, steps=6, peak=peak
            )
        except Exception as e:  # noqa: BLE001 — a sweep point must not
            # take down the headline number
            sweep[str(seq)] = {"error": f"{type(e).__name__}: {e}"}

    n_params = make_cfg(1024).num_params()
    print(
        json.dumps(
            {
                "metric": "train_tokens_per_sec_per_chip",
                "value": head["tokens_per_s"],
                "unit": "tokens/s",
                "vs_baseline": round(head["mfu"] / 0.45, 4),
                "mfu": head["mfu"],
                "n_params": n_params,
                "device": dev.device_kind,
                "seq_sweep": sweep,
            }
        )
    )


if __name__ == "__main__":
    main()
