"""Headline benchmark: flagship-model training throughput on real TPU.

Prints ONE JSON line: tokens/sec/chip on a Llama-family decoder train step
(fwd+bwd+adam, bf16 compute), plus achieved MFU.  vs_baseline is achieved
MFU / 0.45 — the north-star target from BASELINE.json ("Llama-7B DDP at
>=45% MFU"); the reference itself has no TPU numbers to compare against
(SURVEY.md §6: GPU-only).

Model is scaled to fit one chip's HBM (the driver runs single-chip); the
multi-chip path is exercised by __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import json
import time


# per-chip dense bf16 peak; longest-prefix keys first ("TPU v5p" must win
# over "TPU v5" under the startswith lookup below)
PEAK_BF16_FLOPS = {
    "TPU v6 lite": 918e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 197e12,
    "TPU v4": 275e12,
}


def main():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import LMTrainContext, TransformerConfig
    from ray_tpu.parallel import MeshSpec, build_mesh

    dev = jax.devices()[0]
    peak = next(
        (v for k, v in PEAK_BF16_FLOPS.items() if dev.device_kind.startswith(k)),
        197e12,
    )

    # ~940M params: the widest llama-family shape that fits v5e HBM (16G)
    # with bf16 params + f32 adam moments.  d_model=2048 maps onto the MXU
    # far better than deeper/narrower configs (measured: d1536/L24 -> 0.46
    # MFU, d2048/L16 -> 0.51 on v5e).  remat saves post-rope q/k/v + the
    # flash-attention output, recomputing only the cheap matmuls in bwd.
    # bs16 x seq1024 beats bs8 x seq2048 at equal tokens/step (0.578 vs
    # 0.518 measured): half the quadratic attention work per token, which
    # the 6ND accounting below doesn't credit.  remat=False and larger
    # batches OOM at this width.
    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=16,
        d_ff=5504,
        max_seq_len=1024,
        param_dtype=jnp.bfloat16,
        remat=True,
        remat_policy="qkv_attn",
    )
    batch_size, seq = 16, 1024

    mesh = build_mesh(MeshSpec(data=1), devices=[dev])
    ctx = LMTrainContext(cfg, mesh=mesh, strategy="dp")
    state = ctx.init_state(seed=0)

    key = jax.random.PRNGKey(1)
    toks = jax.random.randint(key, (batch_size, seq + 1), 0, cfg.vocab_size)
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}

    # warmup / compile. float() forces a host fetch — block_until_ready alone
    # does not synchronize on the axon TPU platform.
    for _ in range(2):
        state, metrics = ctx.train_step(state, batch)
    float(metrics["loss"])

    steps = 10
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = ctx.train_step(state, batch)
    # steps chain through donated state, so fetching the last loss implies
    # all prior steps completed.
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_s = steps * batch_size * seq / dt
    n_params = cfg.num_params()
    # 6ND fwd+bwd (+remat recompute ≈ 8ND counted conservatively as 6ND)
    model_flops = 6 * n_params * tokens_per_s
    mfu = model_flops / peak

    print(
        json.dumps(
            {
                "metric": "train_tokens_per_sec_per_chip",
                "value": round(tokens_per_s, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / 0.45, 4),
                "mfu": round(mfu, 4),
                "n_params": n_params,
                "device": dev.device_kind,
            }
        )
    )


if __name__ == "__main__":
    main()
