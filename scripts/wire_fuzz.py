#!/usr/bin/env python
"""Deterministic wire-protocol fuzzer + differential codec check.

The runtime twin of the wire-schema lint (the same split the concurrency
lint has with the lock watchdog): the static pass proves send/recv sites
agree with wire.SCHEMAS, this harness proves the DECODER's contract —

  * every byte string, however mangled, either decodes cleanly or raises
    wire.ProtocolError.  Never a hang, never an unhandled exception
    (UnpicklingError leaking out of a recv loop kills the loop, not the
    conn), never partial dispatch of a batch;
  * the v3 native codec and the pickle fallback are INTERCHANGEABLE for
    every kind the native table claims: encoding the same frame down
    both paths and decoding must yield equal objects with equal type
    trees, or the native encoder must decline (return None) so the
    frame rides pickle — the documented subclass-fallback contract.

All generation is seeded (`--seed`), so any failure is a repro command
line, and the corpus in tests/test_wire_fuzz.py pins every frame that
ever produced a non-ProtocolError outcome.

    python scripts/wire_fuzz.py [--seed 0] [--frames 5000] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import random
import sys
from typing import Any, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from ray_tpu._private import wire, wire_native  # noqa: E402
from ray_tpu._private.task_spec import TaskSpec  # noqa: E402


# --- frame generation -------------------------------------------------------

_FIELD_POOL: Tuple[Any, ...] = (
    None, True, False, 0, 1, -7, 2 ** 40, 1.5, "", "x", "worker-3",
    b"", b"\x00\xff", (), (1, "a"), [], [1, [2]], {}, {"k": 1},
    {"nested": {"a": [1.0, None]}},
)


def _typed_value(rng: random.Random, t: Optional[type]) -> Any:
    if t is None:
        return rng.choice(_FIELD_POOL)
    if t is str:
        return rng.choice(("", "a", "task-9", "node:1"))
    if t is int:
        return rng.choice((0, 1, 4096, -1))
    if t is float:
        return rng.choice((0.0, 1.5, -2.25))
    if t is bytes:
        return rng.choice((b"", b"body", b"\x80\x05"))
    if t is list:
        return rng.choice(([], [1], ["a", {"b": 2}]))
    if t is dict:
        return rng.choice(({}, {"k": 1}))
    if t is tuple:
        return rng.choice(((), (1,)))
    return rng.choice(_FIELD_POOL)


def make_valid_frame(rng: random.Random) -> tuple:
    """A schema-legal control tuple for a random kind."""
    kind = rng.choice(sorted(wire.SCHEMAS))
    lo, hi, types = wire.SCHEMAS[kind]
    top = lo + 3 if hi is None else min(hi, lo + 3)
    n = rng.randint(lo, max(lo, top))
    fields = []
    for i in range(n):
        t = types[i] if i < len(types) else None
        fields.append(_typed_value(rng, t))
    return (kind,) + tuple(fields)


def make_spec(rng: random.Random) -> TaskSpec:
    return TaskSpec(
        task_id=f"t{rng.randrange(1 << 16):x}",
        name="fuzz_fn",
        fn_id=f"f{rng.randrange(1 << 16):x}",
        args_blob=bytes(rng.getrandbits(8) for _ in range(rng.randrange(16))),
        num_returns=rng.randint(1, 3),
        resources={"CPU": 1.0},
    )


def _encode_valid(rng: random.Random) -> bytes:
    """One physical frame (single or batch) of schema-legal sub-frames."""
    choice = rng.random()
    if choice < 0.25:
        return wire.encode(make_valid_frame(rng))
    if choice < 0.5:
        # native-capable body (may still fall back to pickle)
        obj = rng.choice(
            [
                ("task", make_spec(rng), b"blob"),
                ("pcall", make_spec(rng)),
                ("reply", rng.randrange(1 << 20), True, {"v": [1, "x"]}),
                ("heartbeat",),
                make_valid_frame(rng),
            ]
        )
        return wire.encode_native(obj)
    bodies = [
        wire.encode_body(make_valid_frame(rng))
        for _ in range(rng.randint(1, 6))
    ]
    return wire.encode_batch(bodies)


def _encode_invalid(rng: random.Random) -> bytes:
    """Frames that must be rejected with ProtocolError (or, for a few
    shapes, happen to still parse — either outcome is in-contract; what
    matters is no OTHER exception escapes)."""
    kindpick = rng.randrange(10)
    if kindpick == 0:  # unknown kind (the refs_push bug class)
        return wire.encode(("no_such_kind_" + str(rng.randrange(100)), 1))
    if kindpick == 1:  # arity violation
        kind = rng.choice(sorted(wire.SCHEMAS))
        lo, hi, _types = wire.SCHEMAS[kind]
        n = rng.choice([max(0, lo - 1), (hi + 1) if hi is not None else lo + 99])
        return wire.encode((kind,) + ("x",) * n)
    if kindpick == 2:  # leading-type violation
        kind = rng.choice(
            [k for k, s in wire.SCHEMAS.items() if any(t for t in s[2])]
        )
        lo, _hi, types = wire.SCHEMAS[kind]
        fields: List[Any] = [
            _typed_value(rng, t) for t in types[:lo]
        ] + [None] * max(0, lo - len(types))
        # poison one typed position with the wrong type
        i = rng.randrange(len([t for t in types if t]) or 1)
        fields[i] = object.__new__(object) if rng.random() < 0.2 else (
            12345 if types[i] is not int else "not-an-int"
        )
        try:
            return wire.encode((kind,) + tuple(fields[:lo]))
        except Exception:
            return wire.encode((kind,) + ("x",) * lo)
    if kindpick == 3:  # truncation of a valid frame
        buf = _encode_valid(rng)
        return buf[: rng.randrange(len(buf))]
    if kindpick == 4:  # byte-flip mutation
        buf = bytearray(_encode_valid(rng))
        for _ in range(rng.randint(1, 4)):
            pos = rng.randrange(len(buf))
            buf[pos] ^= 1 << rng.randrange(8)
        return bytes(buf)
    if kindpick == 5:  # garbage with a valid single-frame header
        return wire._HEADER + bytes(
            rng.getrandbits(8) for _ in range(rng.randrange(64))
        )
    if kindpick == 6:  # garbage, no header
        return bytes(rng.getrandbits(8) for _ in range(rng.randrange(64)))
    if kindpick == 7:  # native-body corruption
        body = bytearray(wire_native.encode(("reply", 1, True, {"a": 1})))
        mode = rng.randrange(3)
        if mode == 0:
            body[0] = rng.choice([0x00, 0x7F, 0x79])  # unknown kind id
        elif mode == 1:
            body[1] = (body[1] + 1 + rng.randrange(200)) % 256  # marshal ver
        else:
            body = body[: 2 + rng.randrange(max(1, len(body) - 2))]  # torn
        return wire._HEADER + bytes(body)
    if kindpick == 8:  # batch structural corruption
        bodies = [wire.encode_body(make_valid_frame(rng)) for _ in range(3)]
        buf = bytearray(wire.encode_batch(bodies))
        mode = rng.randrange(3)
        if mode == 0:
            buf[4] = (buf[4] + 1 + rng.randrange(20)) % 256  # count
        elif mode == 1:
            buf[wire._BATCH_HEADER.size] ^= 0xFF  # first sub-length
        else:
            buf.extend(b"\x00" * rng.randint(1, 8))  # trailing bytes
        return bytes(buf)
    # pickled-body corruption: valid header, broken pickle stream
    payload = rng.choice(
        [
            b"\x80\x05garbage",
            b"\x80\x04cnot_a_module\nNoSuchClass\n.",
            pickle.dumps(make_valid_frame(rng))[: rng.randrange(4, 24)],
            b"",
        ]
    )
    return wire._HEADER + payload


class FuzzReport:
    def __init__(self) -> None:
        self.frames = 0
        self.decoded_ok = 0
        self.protocol_errors = 0
        # (hex frame, exception repr) for every OUT-OF-CONTRACT outcome
        self.failures: List[Tuple[str, str]] = []
        self.codec_checks = 0
        self.codec_divergences: List[str] = []

    @property
    def ok(self) -> bool:
        return not self.failures and not self.codec_divergences


def check_frame(buf: bytes, report: FuzzReport) -> None:
    """Contract: decode_frames returns a list or raises ProtocolError."""
    report.frames += 1
    try:
        objs = wire.decode_frames(buf)
        assert isinstance(objs, list)
        report.decoded_ok += 1
    except wire.ProtocolError:
        report.protocol_errors += 1
    except Exception as e:  # out of contract: corpus material
        report.failures.append((bytes(buf).hex(), repr(e)))


# --- differential codec check ----------------------------------------------


def _type_tree_equal(a: Any, b: Any) -> bool:
    """Equality INCLUDING exact container/scalar types at every level —
    catches a dict subclass silently flattening to dict."""
    if type(a) is not type(b):
        return False
    if isinstance(a, dict):
        if set(a) != set(b):
            return False
        return all(
            _type_tree_equal(k, k2) and _type_tree_equal(a[k], b[k2])
            for k, k2 in zip(sorted(a, key=repr), sorted(b, key=repr))
        )
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(
            _type_tree_equal(x, y) for x, y in zip(a, b)
        )
    if isinstance(a, TaskSpec):
        return a.__dict__ == b.__dict__
    return a == b


def differential_codec_cases(rng: random.Random) -> List[tuple]:
    """Representative frames for every kind in the native table."""
    spec = make_spec(rng)
    cases = [
        ("refop", "oid-1", "incr"),
        ("done", "t1", True, {"recv": 1.0}),
        ("done", "t1", True, b"value", {"recv": 1.0}),
        ("task", spec, b"args"),
        ("create_actor", spec, b"args"),
        ("pcall", spec),
        ("pdone", "t1", True, b"res"),
        ("task_events", [("t1", "RUNNING", 1.5)]),
        ("metrics_push", {"tasks_finished": 12.0}),
        ("refs_push", {"o1": {"count": 1}}),
        ("prof_push", {"stack;frame": 7}),
        ("spans", [("submit", 1.0, 2.0, {"t": "1"})]),
        ("shard_fwd", "conn-1", [b"b1", b"b2"]),
        ("shard_send", "conn-1", b"payload"),
        ("reply", 42, True, {"r": [1, "x", (2.5, None)]}),
        ("reply", 43, False, "error text"),
        ("heartbeat",),
        ("heartbeat", 3),
        ("direct_seal", "o1", 128, "node-1"),
        ("direct_lineage", {"o1": ("spec", b"blob")}),
        ("lease_return", "lease-1"),
    ]
    missing = set(wire_native.KIND_IDS) - {c[0] for c in cases}
    assert not missing, f"differential cases missing kinds: {missing}"
    return cases


class _DictSub(dict):
    pass


class _ListSub(list):
    pass


def run_codec_check(rng: random.Random, report: FuzzReport) -> None:
    for obj in differential_codec_cases(rng):
        report.codec_checks += 1
        pickled = pickle.loads(pickle.dumps(obj, protocol=5))
        native_body = wire_native.encode(obj)
        if native_body is not None:
            try:
                decoded = wire_native.decode(native_body)
            except Exception as e:
                report.codec_divergences.append(
                    f"{obj[0]}: native decode failed on own encode: {e!r}"
                )
                continue
            if not _type_tree_equal(decoded, pickled):
                report.codec_divergences.append(
                    f"{obj[0]}: native {decoded!r} != pickle {pickled!r}"
                )
            # the full wire path must agree too
            via_wire = wire.decode_frames(wire._HEADER + native_body)[0]
            if not _type_tree_equal(via_wire, pickled):
                report.codec_divergences.append(
                    f"{obj[0]}: wire-path native decode diverges"
                )
        elif not _type_tree_equal(pickled, obj):
            report.codec_divergences.append(
                f"{obj[0]}: pickle fallback does not round-trip"
            )
    # Subclass contract: container subclasses in user-reachable positions
    # must DECLINE native encoding (marshal would flatten or reject them);
    # the pickle fallback preserves the exact type.
    for payload in (_DictSub(a=1), _ListSub([1, 2]), {"k": _ListSub()}):
        report.codec_checks += 1
        frame = ("reply", 1, True, payload)
        if wire_native.encode(frame) is not None:
            report.codec_divergences.append(
                f"reply with {type(payload).__name__} payload took the "
                "native path — subclass fallback contract broken"
            )
            continue
        rt = pickle.loads(pickle.dumps(frame, protocol=5))
        if not _type_tree_equal(rt, frame):
            report.codec_divergences.append(
                f"pickle fallback flattened {type(payload).__name__}"
            )
    # A spec whose user-influenced field is a subclass must also decline.
    report.codec_checks += 1
    sub_spec = make_spec(rng)
    sub_spec.runtime_env = _DictSub(env_vars={})
    if wire_native.encode(("pcall", sub_spec)) is not None:
        report.codec_divergences.append(
            "pcall with dict-subclass runtime_env took the native path"
        )


# --- driver -----------------------------------------------------------------


def run_fuzz(
    seed: int, frames: int, valid_ratio: float = 0.3
) -> FuzzReport:
    rng = random.Random(seed)
    report = FuzzReport()
    run_codec_check(rng, report)
    for _ in range(frames):
        if rng.random() < valid_ratio:
            buf = _encode_valid(rng)
        else:
            buf = _encode_invalid(rng)
        check_frame(buf, report)
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--frames", type=int, default=5000)
    ap.add_argument(
        "--valid-ratio", type=float, default=0.3,
        help="fraction of generated frames that are schema-legal",
    )
    ap.add_argument("--json", action="store_true", dest="json_out")
    args = ap.parse_args(argv)

    report = run_fuzz(args.seed, args.frames, args.valid_ratio)
    if args.json_out:
        print(
            json.dumps(
                {
                    "ok": report.ok,
                    "frames": report.frames,
                    "decoded_ok": report.decoded_ok,
                    "protocol_errors": report.protocol_errors,
                    "failures": report.failures,
                    "codec_checks": report.codec_checks,
                    "codec_divergences": report.codec_divergences,
                },
                indent=2,
            )
        )
    else:
        print(
            f"frames={report.frames} decoded_ok={report.decoded_ok} "
            f"protocol_errors={report.protocol_errors} "
            f"codec_checks={report.codec_checks}"
        )
        for hexframe, exc in report.failures:
            print(f"  OUT-OF-CONTRACT: {exc} frame={hexframe}")
        for d in report.codec_divergences:
            print(f"  CODEC DIVERGENCE: {d}")
    if not report.ok:
        print(
            f"\nFAIL: {len(report.failures)} out-of-contract frame(s), "
            f"{len(report.codec_divergences)} codec divergence(s) "
            f"(seed={args.seed})"
        )
        return 1
    print(f"\nOK: contract held for {report.frames} frames (seed={args.seed})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
