"""Chaos soak: a schedule-driven, minutes-scale fault sweep with a seed.

ray: release/nightly_tests/setup_chaos.py runs Ray's long-running chaos
suites with a NodeKillerActor; the CI-scale tests/test_chaos.py here kills
at wall-clock random and cannot replay a failure.  This harness drives the
deterministic fault plane (ray_tpu/_private/faults.py) instead: every kill
and every delay comes from a named, seeded RAY_TPU_FAULT_SPEC clause, so a
failing run prints its seed and the exact spec to rerun.

The soak boots a SPLIT cluster (standalone head subprocess + one external
node daemon + one RELAY node under a scoped spec) and keeps five
workloads running while the spec fires:

  * task chains (produce -> fold, lineage + retries) — every round's
    results must be exactly right;
  * a NAMED restartable actor under max_task_retries — every reply must
    match;
  * an ANONYMOUS restartable actor whose worker is killed in the SAME
    window as a head kill (the overlap ISSUE 5's journaled GCS exists
    for) — the driver's handle must be re-resolved and serving again,
    and the ledger proves a restart happened;
  * serve HTTP traffic against a 2-replica deployment (replicas are
    killed in the head-kill window too) — every logical request must
    eventually succeed;
  * pipelined BROADCASTS (ISSUE 12): fresh multi-chunk objects land on
    several nodes per round through relay transfer plans while the
    relay node's daemon is crash-killed MID-RELAY — every sum must stay
    exact and nothing may leak.

The default schedule (seeded, per-process deterministic):
  * workers crash at their result-send hazard (wire.send of done/pdone
    frames, every N-th matching frame) — the juiciest window: did the
    result land before the death?;
  * the node daemon crashes at its t=18s (store loss -> lineage
    reconstruction) and is relaunched as a fresh node;
  * the head SIGKILLs itself mid-snapshot at its t=30s and is relaunched
    into the same session (restore + live-worker adoption);
  * a small probabilistic delay on every control frame keeps ordering
    races warm.

Afterwards the harness drains to a quiescent state (fault spec stripped
from relaunches), runs a clean verification round, and checks the ledger:
no lost results, no reply mismatches, per-task execution counts within
retry budgets, zero lost serve requests.  The report lands in
CHAOS_r01.json (or --out).

A SECOND scenario (--trainer, ISSUE 16) proves elastic SPMD end to end:
a MESH-gang DataParallelTrainer runs checkpointed steps across two
mesh_coord-labeled gang hosts while the harness SIGKILLs one gang daemon
mid-step.  The gang must re-mesh at N-1 within the RAY_TPU_REMESH_WAIT_S
window, resume from the latest checkpoint with bounded lost steps, scale
back to N when a replacement host (same coordinate) joins, and finish
with every step reported exactly once — with the per-stage recovery
breakdown (detect/teardown/replan/respawn/resume) in the remesh_seconds
histogram.  Report lands in CHAOS_r11.json.

Usage:
    python scripts/chaos_soak.py --duration 75 --seed 7 \
        [--spec '<fault spec>'] [--out CHAOS_r01.json] [--no-serve]
    python scripts/chaos_soak.py --trainer [--out CHAOS_r11.json]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402

# Per-process deterministic kill schedule + latency noise:
#   * match=^done (anchored) kills RELAYED executors — chain task workers
#     and the soak actor's worker — at their result-send hazard, but not
#     direct-path repliers (pdone does not match);
#   * the old replica-kill/head-bounce EXCLUSION is LIFTED: the journaled
#     GCS (ISSUE 5) persists ANONYMOUS actor records, so the schedule now
#     deliberately overlaps them — the AnonSoak worker and each serve
#     Replica crash at their t=29 (their clocks start at worker spawn,
#     so these land during/right after the head's own t=30 death): an
#     actor that dies while the head is down must be re-resolved from the
#     restored record and restarted on its budget;
#   * each head incarnation dies TWICE over: SIGKILL mid-journal-append
#     at its t=24 (torn-tail hazard — replay must recover the complete
#     prefix) and, if it gets there, mid-snapshot at its t=30;
#   * only the FIRST daemon (soak-d1) dies — its store loss must heal via
#     lineage before the head kills land;
#   * wire.flush clauses exercise the BATCH hazard window: a worker dies
#     mid-flush with a coalesced run of frames in flight (the receiver
#     sees a torn stream — EOF or a truncated batch decode_frames rejects
#     whole, never a partial dispatch), and a small probabilistic delay
#     stretches flush windows to keep batch/ordering races warm.
#   * the head runs with RAY_TPU_HEAD_IO_SHARDS=2 (ISSUE 8): one io
#     shard is crash-killed mid-forward at its t=12 (each incarnation —
#     a respawned shard under a still-armed spec dies again), so the
#     soak exercises BOTH fabric hazards: conns failing over to the
#     surviving shard and the head's shard respawn path, all while the
#     head itself bounces.  Zero lost results still required.
#   * ISSUE 12 (RELAY_SPEC below, scoped to the relay node):
#     transfer.chunk_relay crash-kills the relay daemon MID-RELAY of a
#     live broadcast (serving chunks of a pull still in flight on its
#     node) after its 8th relayed chunk — the downstream puller must fall
#     back to a sealed source (or re-plan via the owner) and the
#     broadcast workload must lose nothing; the 256KB soak chunk size
#     keeps every broadcast multi-chunk so the hazard window stays wide.
DEFAULT_SPEC = (
    "wire.send:crash@proc=worker,match=^done,after=40,every=53,times=2;"
    "wire.send:delay=0.002@prob=0.02;"
    "wire.flush:crash@proc=worker,match=^done,after=30,every=41,times=1;"
    "wire.flush:delay=0.002@prob=0.02;"
    "wire.send:crash@proc=daemon:soak-d1,at=18,times=1;"
    "wire.send:crash@proc=actor:AnonSoak,at=29,times=1;"
    "wire.send:crash@proc=actor:Replica,at=29,times=1;"
    "shard.forward:crash@proc=io_shard:1,at=12,times=1;"
    "gcs.journal_append:crash@proc=head,at=24,times=1;"
    "gcs.save:crash@proc=head,at=30,times=1"
)

# The RELAY node runs a SCOPED spec: just the mid-relay daemon kill (+ the
# ambient wire delay).  Its workers inherit this spec too — deliberately
# WITHOUT the worker/actor kill clauses: a relay node carrying the full
# schedule re-arms the per-process actor kills on every respawned worker
# it hosts, which turns post-storm placement onto that node into an
# infinite kill loop (observed: replicas/actors re-killed every ~30s
# through the whole drain).  The relay hazard this node exists for lives
# in the DAEMON process, so that is what the clause targets.
RELAY_SPEC = (
    "transfer.chunk_relay:crash@proc=daemon,after=8,times=1;"
    "wire.send:delay=0.002@prob=0.02"
)

TASK_RETRIES = 25
ACTOR_RETRIES = 25
CHAIN_WIDTH = 8
# Driver-level re-drives per logical operation.  A head kill erases the
# control-plane record of COMPLETED-but-unfetched results that lived only
# in the head process; the supported recovery envelope is snapshot
# re-drive (in-flight tasks) + surviving node copies + actor adoption.  A
# logical op that still cannot produce its (correct) answer after this
# many fresh submissions counts as LOST and fails the soak — and every
# re-drive is counted in the report, so the at-most-once windows are
# measured, not papered over.
REDRIVES = 3
# shm-sized payloads (>= max_direct_call_object_size): sealed segments
# live on tmpfs node stores and survive head bounces; inline results die
# with the head process.
ARR = 1 << 14


def _append(path: str, line: str) -> None:
    # O_APPEND single-line writes are atomic across the node's processes.
    with open(path, "a") as f:
        f.write(line + "\n")


@ray_tpu.remote(max_retries=TASK_RETRIES)
def produce(i, r, log_path):
    _append(log_path, f"produce:{r}:{i}")
    return np.full((ARR,), i, dtype=np.int64)


@ray_tpu.remote(max_retries=TASK_RETRIES)
def wave_work(i, delay, log_path):
    """Demand wave for the autoscale scenario: 1-CPU sleepers sized so the
    queue outlives the up-wait hysteresis and the fleet provably grows."""
    _append(log_path, f"wave:{i}")
    time.sleep(delay)
    return i


@ray_tpu.remote(max_retries=TASK_RETRIES)
def fold(a, j, r, log_path):
    _append(log_path, f"fold:{r}:{j}")
    return np.full((ARR,), int(a.sum()) + j, dtype=np.int64)


# Broadcast payload: ~4MB of int64 => 16 relay chunks at the soak's 256KB
# transfer chunk size, so a mid-relay kill has a wide window to land in.
BCAST_N = (4 << 20) // 8


@ray_tpu.remote(max_retries=TASK_RETRIES, scheduling_strategy="SPREAD")
def bcast_land(x, r, i, log_path):
    _append(log_path, f"bcast:{r}:{i}")
    return int(x.sum())


@ray_tpu.remote(max_restarts=100, max_task_retries=ACTOR_RETRIES)
class SoakActor:
    def __init__(self, log_path):
        self.log_path = log_path

    def echo(self, i):
        _append(self.log_path, f"actor:{i}")
        return i


@ray_tpu.remote(max_restarts=100, max_task_retries=ACTOR_RETRIES)
class AnonSoak:
    """ANONYMOUS restartable actor — the record class that used to die
    with the head.  Its spec clause kills the hosting worker at its t=29,
    overlapping the head's own deaths: recovery requires the restarted
    head to re-resolve the actor from persisted GCS state (journal) and
    restart it on its budget.  __init__ logs so the ledger can PROVE a
    restart happened (anoninit count >= 2)."""

    def __init__(self, log_path):
        self.log_path = log_path
        _append(log_path, "anoninit:0")

    def echo(self, i):
        _append(self.log_path, f"anon:{i}")
        return i


def _launch_daemon(head_json: str, node_id: str, num_cpus: int,
                   spec_override: Optional[str] = None,
                   resources: Optional[Dict[str, float]] = None,
                   labels: Optional[Dict[str, str]] = None):
    """spec_override scopes the fault plan THIS daemon (and every worker
    it spawns) runs under; empty string = no faults; None = inherit the
    ambient os.environ spec (the classic soak daemons).  labels carry the
    mesh_coord topology tags the elastic-trainer scenario's gang hosts
    need."""
    with open(head_json) as f:
        info = json.load(f)
    env = os.environ.copy()
    if spec_override is not None:
        if spec_override:
            env["RAY_TPU_FAULT_SPEC"] = spec_override
        else:
            env.pop("RAY_TPU_FAULT_SPEC", None)
    env.update(
        {
            "RAY_TPU_DRIVER_HOST": info["host"],
            "RAY_TPU_DRIVER_PORT": str(info["port"]),
            "RAY_TPU_AUTHKEY": info["authkey"],
            "RAY_TPU_NODE_CONFIG": json.dumps(
                {
                    "node_id": node_id,
                    "session": info["session"],
                    "num_cpus": num_cpus,
                    "resources": resources or {},
                    "labels": labels or {},
                }
            ),
            "PYTHONPATH": os.pathsep.join(dict.fromkeys([REPO_ROOT] + sys.path)),
        }
    )
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_daemon"],
        env=env,
        close_fds=True,
    )


class _Workload(threading.Thread):
    """Base: loops `step` until stop; remembers the first hard failure."""

    t0 = 0.0  # stamped by run_soak before start()

    def __init__(self, name, stop):
        super().__init__(daemon=True, name=name)
        self.stop_evt = stop
        self.failure: Optional[str] = None
        self.iterations = 0
        self.redrives = 0

    def note(self, msg):
        print(
            f"[soak t={time.monotonic() - self.t0:6.1f}s] [{self.name}] {msg}",
            flush=True,
        )

    def run(self):
        while not self.stop_evt.is_set():
            try:
                self.step()
                self.iterations += 1
            except Exception as e:  # noqa: BLE001 — a soak failure is data
                import traceback

                self.failure = (
                    f"(iteration {self.iterations}, "
                    f"t={time.monotonic() - self.t0:.1f}s) "
                    f"{type(e).__name__}: {e}"
                )
                self.note(self.failure + "\n" + traceback.format_exc())
                return

    def eventually(self, make_refs, check, timeout=60.0):
        """Submit-fresh-and-get with a bounded, COUNTED re-drive on the
        two outcomes a head kill can legitimately inflict on this client
        (a parked get that will never resolve, a loudly-lost object).
        Wrong VALUES never retry — they fail the soak immediately."""
        from ray_tpu.exceptions import GetTimeoutError, ObjectLostError

        last = None
        for attempt in range(1 + REDRIVES):
            if attempt:
                self.redrives += 1
                self.note(
                    f"re-drive {attempt}/{REDRIVES} of iteration "
                    f"{self.iterations} after {last!r}"
                )
            try:
                outs = ray_tpu.get(make_refs(), timeout=timeout)
            except (GetTimeoutError, ObjectLostError) as e:
                last = e
                continue
            check(outs)
            return
        raise AssertionError(
            f"logical op LOST after {REDRIVES} re-drives: {last!r}"
        )


class _ChainLoad(_Workload):
    def __init__(self, stop, log_path):
        super().__init__("soak-chains", stop)
        self.log_path = log_path

    def step(self):
        r = self.iterations

        def make_refs():
            return [
                fold.remote(
                    produce.remote(i, r, self.log_path), i, r, self.log_path
                )
                for i in range(CHAIN_WIDTH)
            ]

        def check(outs):
            for i, a in enumerate(outs):
                expect = i * ARR + i
                if a.shape != (ARR,) or int(a[0]) != expect or int(a.sum()) != expect * ARR:
                    raise AssertionError(
                        f"chain round {r} lane {i}: wrong result (CORRUPT)"
                    )

        self.eventually(make_refs, check)


class _ActorLoad(_Workload):
    def __init__(self, stop, log_path):
        super().__init__("soak-actor", stop)
        self.actor = SoakActor.options(name="soak_actor").remote(log_path)

    def step(self):
        i = self.iterations

        def check(outs):
            if outs != [i]:
                raise AssertionError(
                    f"actor echo({i}) returned {outs[0]} (CORRUPT reply)"
                )

        self.eventually(lambda: [self.actor.echo.remote(i)], check)
        # Shared-box pacing.  This also sets the actor-worker churn rate:
        # the kill clause fires on done-frame COUNTS, so an unpaced echo
        # hammer would recycle the actor's worker every ~1s and the
        # one-box cluster would spend itself respawning processes.
        time.sleep(0.1)


class _AnonLoad(_Workload):
    """Drives the ANONYMOUS actor through the overlapping replica-kill +
    head-kill window.  The driver keeps calling the SAME handle — after
    the overlap, the handle only works again if the restarted head
    re-resolved the anonymous record (pre-ISSUE-5 this was impossible:
    the record died with the head)."""

    def __init__(self, stop, log_path):
        super().__init__("soak-anon", stop)
        self.actor = AnonSoak.remote(log_path)

    def step(self):
        i = self.iterations

        def check(outs):
            if outs != [i]:
                raise AssertionError(
                    f"anon echo({i}) returned {outs[0]} (CORRUPT reply)"
                )

        self.eventually(lambda: [self.actor.echo.remote(i)], check)
        time.sleep(0.1)  # same shared-box pacing as the named actor load


class _BroadcastLoad(_Workload):
    """ISSUE 12: a live pipelined broadcast under the storm.  Each round
    puts a FRESH multi-chunk object (head store) and lands it on several
    nodes at once via SPREAD — the owner hands out relay transfer plans,
    in-flight pullers re-serve chunks, and the spec's
    transfer.chunk_relay clause crash-kills a daemon MID-RELAY.  Every
    round's sums must be exactly right (a torn or short relay would
    corrupt them), and the re-drive budget covers head/daemon deaths.
    The put rides inside make_refs so a re-drive after a head bounce
    re-seals fresh bytes instead of chasing a dead object id."""

    WIDTH = 3  # landing tasks per round (SPREAD across the node set)

    def __init__(self, stop, log_path):
        super().__init__("soak-bcast", stop)
        self.log_path = log_path

    def step(self):
        r = self.iterations
        fill = r % 251 + 1
        arr = np.full(BCAST_N, fill, dtype=np.int64)
        expect = fill * BCAST_N

        def make_refs():
            ref = ray_tpu.put(arr)
            return [
                bcast_land.remote(ref, r, i, self.log_path)
                for i in range(self.WIDTH)
            ]

        def check(outs):
            for i, got in enumerate(outs):
                if got != expect:
                    raise AssertionError(
                        f"broadcast round {r} lane {i}: {got} != {expect} "
                        "(CORRUPT relay)"
                    )

        self.eventually(make_refs, check)
        time.sleep(0.3)  # shared-box pacing; frees land between rounds


class _ServeLoad(_Workload):
    """One logical request per step; each retries (with address
    re-discovery — a restarted proxy binds a fresh port) until it succeeds
    or the per-request budget lapses (then it is LOST — the soak fails)."""

    def __init__(self, stop, addr, addr_fn):
        super().__init__("soak-serve", stop)
        self.addr = addr
        self.addr_fn = addr_fn
        self.ok = 0
        self.retried = 0
        self.lost = 0

    def step(self):
        import urllib.request

        deadline = time.monotonic() + 60
        attempt = 0
        while True:
            attempt += 1
            try:
                req = urllib.request.Request(
                    self.addr + "/soak", data=b"{}", method="POST"
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    body = json.loads(resp.read())
                assert body["result"] == {"ok": True}
                self.ok += 1
                if attempt > 1:
                    self.retried += 1
                # Light pacing: the soak shares one box with the whole
                # cluster; an unpaced HTTP hammer starves the processes
                # it is testing.
                time.sleep(0.05)
                return
            except Exception:
                if time.monotonic() > deadline:
                    self.lost += 1
                    raise AssertionError(
                        f"serve request lost after {attempt} attempts"
                    )
                time.sleep(1.0)
                try:
                    self.addr = self.addr_fn() or self.addr
                except Exception:
                    pass  # control plane mid-bounce: retry the old address


def _collect_flight(report: Dict, flight_dir: str) -> int:
    """Fold the flight-recorder dump headers into the report; returns the
    dump count."""
    from ray_tpu._private import telemetry

    dumps = telemetry.collect_dumps(flight_dir)
    by_reason: Dict[str, int] = {}
    for d in dumps:
        key = d.get("reason", "?")
        by_reason[key] = by_reason.get(key, 0) + 1
    report["flight_recorder"] = {
        "dir": flight_dir,
        "dumps": len(dumps),
        "by_reason": by_reason,
        "processes": sorted({d.get("proc", "?") for d in dumps}),
    }
    return len(dumps)


def _count_log(path: str) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if ln:
                    counts[ln] = counts.get(ln, 0) + 1
    except FileNotFoundError:
        pass
    return counts


def run_soak(
    duration: float = 75.0,
    seed: int = 7,
    spec: str = DEFAULT_SPEC,
    out: Optional[str] = None,
    use_serve: bool = True,
    num_cpus: int = 4,
    watch_locks: bool = True,
) -> Dict:
    from ray_tpu._private import faults, lock_watchdog
    from ray_tpu._private.head import launch_head_subprocess

    faults.configure(spec, seed)  # fail LOUDLY on a typo'd plan, up front
    faults.disable()  # the driver itself stays clean; children get the env

    workdir = tempfile.mkdtemp(prefix=f"chaos-soak-{seed}-")
    log_path = os.path.join(workdir, "executions.log")
    # Unique per run: session names key the shared /tmp log + store dirs,
    # and a reused name would interleave a previous soak's state.
    session = f"chaos{seed}x{os.getpid():x}"
    saved_env = {
        k: os.environ.get(k)
        for k in (
            "RAY_TPU_FAULT_SPEC",
            "RAY_TPU_FAULT_SEED",
            "RAY_TPU_RECONNECT_WINDOW_S",
            "RAY_TPU_LOCK_WATCHDOG",
            "RAY_TPU_LOCK_WATCHDOG_DIR",
            "RAY_TPU_LOCK_HOLD_S",
            "RAY_TPU_TRACE",
            "RAY_TPU_FLIGHT_DIR",
            "RAY_TPU_METRICS_PUSH_MS",
            "RAY_TPU_HEAD_IO_SHARDS",
            "RAY_TPU_PROF_HZ",
            "RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES",
            "RAY_TPU_RELAY_FANOUT",
        )
    }
    os.environ["RAY_TPU_FAULT_SPEC"] = spec
    os.environ["RAY_TPU_FAULT_SEED"] = str(seed)
    os.environ["RAY_TPU_RECONNECT_WINDOW_S"] = "45"
    # ISSUE 12: small transfer chunks keep every broadcast multi-chunk, so
    # mid-relay kill windows stay wide and relays genuinely pipeline.
    os.environ.setdefault("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", "262144")
    # relay_fanout=1 makes every multi-node pull a CHAIN (the 2nd puller
    # feeds off the 1st's in-flight board), so relays form with just two
    # daemon nodes — the shared 1-vCPU box can't afford the node count a
    # bushier tree would need to exercise the relay path.
    os.environ.setdefault("RAY_TPU_RELAY_FANOUT", "1")
    # ISSUE 8: the soak runs the SHARDED head fabric — every head
    # incarnation fans its conns across 2 io shards, and the spec kills
    # one shard mid-forward (its conns must fail over with zero lost
    # results while head kills overlap).
    os.environ.setdefault("RAY_TPU_HEAD_IO_SHARDS", "2")
    # FULL telemetry plane on across every process of the soak cluster
    # (ISSUE 6 acceptance: the soak passes with push + spans + flight
    # recorder enabled, and every fault-plane kill leaves a flight dump
    # behind — failures become diagnosable without a replay).
    flight_dir = os.path.join(workdir, "flight")
    os.makedirs(flight_dir, exist_ok=True)
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_FLIGHT_DIR"] = flight_dir
    os.environ.setdefault("RAY_TPU_METRICS_PUSH_MS", "1000")
    # ISSUE 10: the sampling profiler runs HOT through the whole soak in
    # every process (head, workers, daemon, io shards autostart via
    # telemetry.install) — head/shard kills must not wedge it, and every
    # crash dump carries the victim's last collapsed-stack snapshot.
    os.environ.setdefault("RAY_TPU_PROF_HZ", "25")
    watchdog_dir = os.path.join(workdir, "watchdog")
    if watch_locks:
        # Lock watchdog on across EVERY process of the soak cluster
        # (children inherit the env; the driver flips its already-imported
        # module gate directly).  Reports land in watchdog_dir per pid and
        # any report fails the soak — order inversions and long holds must
        # not ride along under chaos.  Hold threshold is looser than the
        # 1s default: a 4-CPU CI box under storm-level GIL contention
        # stretches legitimate dispatch holds.
        os.makedirs(watchdog_dir, exist_ok=True)
        os.environ["RAY_TPU_LOCK_WATCHDOG"] = "1"
        os.environ["RAY_TPU_LOCK_WATCHDOG_DIR"] = watchdog_dir
        os.environ.setdefault("RAY_TPU_LOCK_HOLD_S", "2.0")
        lock_watchdog._enable_for_tests(True)

    report: Dict = {
        "seed": seed,
        "spec": spec,
        "duration_s": duration,
        "kills": {"head": 0, "daemon": 0, "io_shard": 0},
        "lock_watchdog": {"enabled": watch_locks, "reports": []},
        "result": "FAIL",
    }
    head = daemon = None
    relay_daemons: Dict[str, subprocess.Popen] = {}
    serve_mod = None
    stop = threading.Event()
    loads = []
    try:
        head, head_json = launch_head_subprocess(
            workdir, num_cpus=num_cpus, session=session
        )
        daemon = _launch_daemon(head_json, "soak-d1", num_cpus)
        # One extra RELAY node (ISSUE 12): the broadcast workload's
        # SPREAD landings pull one head-store object onto both daemon
        # nodes at once; with relay_fanout=1 the second puller MUST
        # chain off the first's in-flight board — and the
        # transfer.chunk_relay clause kills whichever daemon is serving
        # a mid-flight relay.
        relay_daemons.update(
            {"soak-b1": _launch_daemon(head_json, "soak-b1", 2,
                                       spec_override=RELAY_SPEC)}
        )
        relay_gen = {"soak-b1": 1}
        report["kills"]["relay_daemon"] = 0

        def check_relay_daemons(draining: bool) -> None:
            for slot, proc in list(relay_daemons.items()):
                if proc.poll() is None:
                    continue
                report["kills"]["relay_daemon"] += 1
                relay_gen[slot] += 1
                nid = f"{slot}g{relay_gen[slot]}"
                note(
                    f"relay daemon {slot} died (kill "
                    f"#{report['kills']['relay_daemon']}); relaunching as {nid}"
                )
                relay_daemons[slot] = _launch_daemon(
                    head_json, nid, 2,
                    spec_override="" if draining else RELAY_SPEC,
                )

        ray_tpu.init(address=head_json)

        if use_serve:
            from ray_tpu import serve as serve_mod

            serve_mod.start(http_options={"host": "127.0.0.1", "port": 0})

            @serve_mod.deployment(
                name="soak",
                num_replicas=2,
                ray_actor_options={"max_restarts": 100},
            )
            def soak_dep(body=None):
                return {"ok": True}

            serve_mod.run(soak_dep.bind())
            addr = serve_mod.get_http_address()

        loads = [
            _ChainLoad(stop, log_path),
            _ActorLoad(stop, log_path),
            _AnonLoad(stop, log_path),
            _BroadcastLoad(stop, log_path),
        ]
        if use_serve:
            loads.append(_ServeLoad(stop, addr, serve_mod.get_http_address))

        # ---- supervise the schedule window: the SPEC does the killing;
        # the harness only resurrects control-plane processes.
        t0 = time.monotonic()
        _Workload.t0 = t0
        for w in loads:
            w.start()

        def note(msg):
            print(f"[soak t={time.monotonic() - t0:6.1f}s] {msg}", flush=True)

        daemon_n = 1
        while time.monotonic() - t0 < duration:
            time.sleep(0.5)
            draining = time.monotonic() - t0 > duration - 10
            if head.poll() is not None:
                report["kills"]["head"] += 1
                if draining:
                    # Quiescence: relaunches near/after the end come up
                    # with the fault plan stripped.
                    os.environ.pop("RAY_TPU_FAULT_SPEC", None)
                note(f"head died (kill #{report['kills']['head']}); relaunching")
                head, _ = launch_head_subprocess(
                    workdir, num_cpus=num_cpus, session=session
                )
                note("head relaunched")
            if daemon.poll() is not None:
                report["kills"]["daemon"] += 1
                daemon_n += 1
                if draining:
                    os.environ.pop("RAY_TPU_FAULT_SPEC", None)
                note(f"daemon died (kill #{report['kills']['daemon']}); "
                     f"relaunching as soak-d{daemon_n}")
                daemon = _launch_daemon(head_json, f"soak-d{daemon_n}", num_cpus)
            check_relay_daemons(draining)
            dead = [w for w in loads if w.failure]
            if dead:
                note(f"workload failure: {[(w.name, w.failure) for w in dead]}")
                break

        # ---- drain: stop the storm but KEEP SUPERVISING — surviving
        # processes still carry live clauses (each head incarnation crashes
        # at its own t=30), and a death with nobody resurrecting it would
        # strand the workloads' final operations.  Relaunches from here on
        # come up with the fault plan stripped.
        os.environ.pop("RAY_TPU_FAULT_SPEC", None)
        stop.set()
        drain_deadline = time.monotonic() + 300
        while (
            any(w.is_alive() for w in loads)
            and time.monotonic() < drain_deadline
        ):
            time.sleep(0.5)
            if head.poll() is not None:
                report["kills"]["head"] += 1
                note("head died during drain; relaunching clean")
                head, _ = launch_head_subprocess(
                    workdir, num_cpus=num_cpus, session=session
                )
            if daemon.poll() is not None:
                report["kills"]["daemon"] += 1
                daemon_n += 1
                note(f"daemon died during drain; relaunching as soak-d{daemon_n}")
                daemon = _launch_daemon(head_json, f"soak-d{daemon_n}", num_cpus)
            check_relay_daemons(True)
        for w in loads:
            w.join(timeout=10)
            if w.is_alive():
                raise AssertionError(f"[{w.name}] never drained (wedged op)")
        for w in loads:
            if w.failure:
                raise AssertionError(f"[{w.name}] {w.failure}")
        if head.poll() is not None:
            head, _ = launch_head_subprocess(
                workdir, num_cpus=num_cpus, session=session
            )
        # A clean round on the post-storm cluster: convergence, not luck.
        final = ray_tpu.get(
            [
                fold.remote(produce.remote(i, "final", log_path), i, "final",
                            log_path)
                for i in range(CHAIN_WIDTH)
            ],
            timeout=240,
        )
        for i, a in enumerate(final):
            assert int(a[0]) == i * ARR + i, (
                "post-storm cluster did not converge to correct results"
            )

        # ---- memory introspection: the object ledger must CONVERGE to
        # zero leak suspects after every kill the storm threw (worker
        # crashes mid-hold leave dead-holder suspects; the reclaim sweep
        # must clear them and free the bytes).  Polled: reclaim grace +
        # final refs_push ticks need a beat to land.
        from ray_tpu.util import state as state_api

        mem = None
        # Budget: worst-case orphan path is leak_age (10s) + orphan grace
        # (20s) + push/tick lag before a drain-era orphan is reclaimed.
        mem_deadline = time.monotonic() + 90
        while time.monotonic() < mem_deadline:
            try:
                mem = state_api.memory_summary(top=0)
            except Exception:
                time.sleep(1.0)
                continue
            if mem["leak_suspects"] == 0:
                break
            time.sleep(1.0)
        report["memory"] = {
            "leak_suspects": mem["leak_suspects"] if mem else None,
            "leak_suspect_bytes": mem["leak_suspect_bytes"] if mem else None,
            "objects": mem["objects"] if mem else None,
            "bytes_total": mem["bytes_total"] if mem else None,
            "nodes": mem["nodes"] if mem else None,
        }
        assert mem is not None, "memory_summary unreachable after the storm"
        assert mem["leak_suspects"] == 0, (
            f"object ledger did not converge: {mem['leak_suspects']} leak "
            f"suspects holding {mem['leak_suspect_bytes']} bytes after "
            f"drain: {[r['object_id'] for r in mem['leaks']][:10]}"
        )

        # ---- lease revocation (ISSUE 11): the match=^done crash clause
        # kills workers at their result-send hazard — each victim was an
        # executing LEASEHOLDER (head-side when its task relayed,
        # caller-side when direct), so the storm exercises the
        # crash-revocation path throughout.  The POST-storm incarnation's
        # counters start clean, so drive a small RELAYED burst (SPREAD is
        # direct-ineligible — it must take the head's queued path and
        # grant head-side leases) and then require convergence: every
        # lease revoked or idle-reaped with its resources back in the
        # pool.  A stranded lease would starve the cluster quietly.
        @ray_tpu.remote(max_retries=5, scheduling_strategy="SPREAD")
        def lease_probe(i):
            return i

        probe_out = ray_tpu.get(
            [lease_probe.remote(i) for i in range(16)], timeout=120
        )
        assert probe_out == list(range(16))
        lease_state = None
        lease_deadline = time.monotonic() + 60
        while time.monotonic() < lease_deadline:
            try:
                internal = state_api.telemetry_summary()["internal"]
            except Exception:
                time.sleep(1.0)
                continue
            lease_state = {
                "granted": internal.get("task_leases_granted"),
                "revoked": internal.get("task_leases_revoked"),
                "lease_dispatches": internal.get("lease_dispatches"),
                "live_at_quiesce": internal.get("head_task_leases"),
            }
            if lease_state["live_at_quiesce"] == 0.0:
                break
            time.sleep(1.0)
        report["task_leases"] = lease_state
        assert lease_state is not None, "telemetry unreachable at quiesce"
        assert lease_state["granted"], "storm never exercised a task lease"
        assert lease_state["live_at_quiesce"] == 0.0, (
            f"task leases stranded after the storm: {lease_state}"
        )

        # ---- the ledger: executions within retry budgets, kills fired.
        counts = _count_log(log_path)
        head_kills = report["kills"]["head"]
        # At-least-once bound: system retries per submission, times the
        # driver's counted re-drives, plus the snapshot re-drive a head
        # restart performs.
        budget = (TASK_RETRIES + 1) * (1 + REDRIVES) + head_kills
        over = {k: c for k, c in counts.items() if c > budget}
        assert not over, f"execution counts beyond retry budgets: {over}"
        dup_execs = sum(c - 1 for c in counts.values() if c > 1)
        chains = next(w for w in loads if w.name == "soak-chains")
        actor = next(w for w in loads if w.name == "soak-actor")
        anon = next(w for w in loads if w.name == "soak-anon")
        bcast = next(w for w in loads if w.name == "soak-bcast")
        anon_inits = counts.get("anoninit:0", 0)
        report.update(
            {
                "chain_rounds": chains.iterations,
                "chain_results_checked": chains.iterations * CHAIN_WIDTH,
                "chain_redrives": chains.redrives,
                "actor_calls": actor.iterations,
                "actor_redrives": actor.redrives,
                "anon_actor_calls": anon.iterations,
                "anon_actor_redrives": anon.redrives,
                "anon_actor_restarts": max(anon_inits - 1, 0),
                "broadcast_rounds": bcast.iterations,
                "broadcast_results_checked": bcast.iterations
                * _BroadcastLoad.WIDTH,
                "broadcast_redrives": bcast.redrives,
                "distinct_executions": len(counts),
                "duplicate_executions": dup_execs,
                "execution_budget": budget,
            }
        )
        if use_serve:
            sv = next(w for w in loads if w.name == "soak-serve")
            report["serve"] = {
                "ok": sv.ok, "retried": sv.retried, "lost": sv.lost,
            }
            assert sv.lost == 0, f"{sv.lost} serve requests lost"
        assert chains.iterations >= 3, "soak too short: <3 chain rounds ran"
        assert actor.iterations >= 10, "soak too short: <10 actor calls ran"
        assert head_kills >= 1, "schedule never killed the head"
        assert report["kills"]["daemon"] >= 1, "schedule never killed a daemon"
        assert dup_execs >= 1, (
            "no task was ever re-executed: worker kill clauses never fired"
        )
        # ISSUE 5 acceptance: the anonymous actor was killed (at=29, in
        # the head-kill window), RESTARTED from the restored record
        # (>= 2 inits), and its handle kept serving to the drained end
        # (anon workload finished with zero failures above).  Pre-journal,
        # this workload could not survive the overlap at all.
        assert anon_inits >= 2, (
            "anonymous actor never restarted — the AnonSoak kill clause "
            "never fired or the record did not survive the head bounce"
        )
        assert anon.iterations >= 10, "soak too short: <10 anon-actor calls ran"
        if watch_locks:
            wd = lock_watchdog.collect_dir_reports(watchdog_dir)
            wd.extend(f"driver: {r}" for r in lock_watchdog.reports())
            report["lock_watchdog"]["reports"] = wd
            assert not wd, f"lock watchdog reports under chaos: {wd}"
        # Flight recorder: every fault-plane crash dumped its ring.  The
        # schedule provably killed processes (asserted above), so dumps
        # MUST exist — a zero here means the recorder regressed.
        dumps = _collect_flight(report, flight_dir)
        assert dumps, (
            "fault-plane kills fired but produced no flight-recorder dumps"
        )
        # ISSUE 8 acceptance: the io-shard kill clause fired (its flight
        # dump is attached), and the soak still drained with zero lost
        # results — the shard's conns failed over and the head respawned
        # the shard while the storm ran.
        from ray_tpu._private import telemetry as _telemetry

        shard_dumps = [
            d
            for d in _telemetry.collect_dumps(flight_dir)
            if str(d.get("proc", "")).startswith("io_shard")
        ]
        report["kills"]["io_shard"] = len(shard_dumps)
        assert shard_dumps, (
            "shard.forward kill clause never fired — no io-shard flight "
            "dump found (is the sharded fabric actually on?)"
        )
        # ISSUE 12 acceptance: the broadcast workload ran through the
        # storm with every sum exact, AND the transfer.chunk_relay clause
        # provably crash-killed a daemon MID-RELAY of a live broadcast
        # (its flight dump names the point) — the downstream pullers fell
        # back to sealed sources / re-planned with zero lost results, and
        # the ledger's leak sweep (asserted above) covered the broadcast
        # objects too.
        relay_kill_dumps = [
            d
            for d in _telemetry.collect_dumps(flight_dir)
            if "transfer.chunk_relay" in str(d.get("reason", ""))
        ]
        report["relay_kills_mid_broadcast"] = len(relay_kill_dumps)
        assert bcast.iterations >= 3, "soak too short: <3 broadcast rounds ran"
        assert relay_kill_dumps, (
            "transfer.chunk_relay kill clause never fired — no daemon was "
            "mid-relay during the storm (is the pipelined broadcast "
            "actually on?)"
        )
        # ISSUE 10 acceptance: the profiler sampled through the chaos —
        # crash dumps carry collapsed-stack snapshots (prof_stacks > 0 in
        # the dump header), so a killed process records where its time
        # went, not just what it did.
        all_dumps = _telemetry.collect_dumps(flight_dir)
        prof_dumps = [d for d in all_dumps if d.get("prof_stacks", 0) > 0]
        report["profiler"] = {
            "hz": float(os.environ.get("RAY_TPU_PROF_HZ", "0")),
            "dumps_with_prof_snapshot": len(prof_dumps),
            "dumps_total": len(all_dumps),
        }
        assert prof_dumps, (
            "profiler ran hot through the soak but no flight dump carries "
            "a collapsed-stack snapshot (prof_stacks == 0 everywhere)"
        )
        report["result"] = "PASS"
        return report
    except BaseException:
        # Attach the flight-recorder dumps to the failing report: what
        # each killed/crashed process saw in its last seconds, without a
        # replay (the dump files stay under the kept session dir).
        try:
            _collect_flight(report, flight_dir)
        except Exception:
            pass
        print(
            "\n=== CHAOS SOAK FAILED — replay with:\n"
            f"    python scripts/chaos_soak.py --seed {seed} "
            f"--duration {duration} --spec '{spec}'\n"
            f"    (session dir kept at {workdir}; flight-recorder dumps "
            f"under {flight_dir})",
            file=sys.stderr,
            flush=True,
        )
        raise
    finally:
        stop.set()
        if serve_mod is not None:
            try:
                serve_mod.shutdown()
            except Exception:
                pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for proc in (daemon, head, *relay_daemons.values()):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if watch_locks:
            lock_watchdog._enable_for_tests(
                os.environ.get("RAY_TPU_LOCK_WATCHDOG") == "1"
            )
        if out and report.get("result"):
            with open(out, "w") as f:
                json.dump(report, f, indent=1, sort_keys=True)
                f.write("\n")


# ---------------------------------------------------------------------------
# Elastic-trainer scenario (ISSUE 16): gang re-mesh under a host SIGKILL.
# ---------------------------------------------------------------------------


def _elastic_train_fn(config):
    """Elastic SPMD soak loop: one checkpointed step at a time.  World
    size is whatever gang the driver respawned us into (2 -> 1 -> 2 over
    the scenario); every step reports WITH a checkpoint, so a re-mesh
    loses at most the in-flight step plus the undrained report window."""
    import time as _t

    from ray_tpu.train import session

    ckpt = session.get_checkpoint()
    start = int(ckpt["step"]) + 1 if ckpt else 0
    rank = session.get_world_rank()
    world = session.get_world_size()
    for s in range(start, int(config["steps"])):
        _append(config["log_path"], f"trainstep:{rank}/{world}:{s}")
        _t.sleep(float(config["step_s"]))
        session.report({"step": s, "world": world}, checkpoint={"step": s})


class _TrainerLoad(threading.Thread):
    """Runs fit() off the supervisor thread; remembers result/failure."""

    def __init__(self, steps: int, step_s: float, log_path: str):
        super().__init__(daemon=True, name="soak-trainer")
        self.steps = steps
        self.step_s = step_s
        self.log_path = log_path
        self.result = None
        self.failure: Optional[str] = None

    def run(self):
        try:
            from ray_tpu.air.config import (
                FailureConfig,
                RunConfig,
                ScalingConfig,
            )
            from ray_tpu.train.backend import BackendConfig
            from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

            trainer = DataParallelTrainer(
                _elastic_train_fn,
                train_loop_config={
                    "steps": self.steps,
                    "step_s": self.step_s,
                    "log_path": self.log_path,
                },
                # Plain backend: the elasticity under test is the gang +
                # worker-group machinery, not jax multiprocess (which the
                # CPU backend cannot run anyway).
                backend_config=BackendConfig(),
                scaling_config=ScalingConfig(
                    num_workers=2,
                    resources_per_worker={"CPU": 1.0, "gang": 1.0},
                    placement_strategy="MESH",
                ),
                run_config=RunConfig(failure_config=FailureConfig(max_failures=2)),
            )
            self.result = trainer.fit()
            if self.result.error is not None:
                self.failure = f"fit() returned error: {self.result.error}"
        except BaseException as e:  # noqa: BLE001 — a soak failure is data
            import traceback

            self.failure = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"


def _train_step_counts(log_path: str) -> Dict[tuple, int]:
    """{(rank, world, step): executions} from the trainstep ledger."""
    out: Dict[tuple, int] = {}
    for line, n in _count_log(log_path).items():
        if not line.startswith("trainstep:"):
            continue
        rw, step = line.split(":")[1:3]
        rank, world = rw.split("/")
        out[(int(rank), int(world), int(step))] = n
    return out


def _steps_at_world(counts: Dict[tuple, int], world: int) -> set:
    return {step for (_r, w, step) in counts if w == world}


def run_trainer_soak(
    seed: int = 11,
    out: Optional[str] = None,
    num_cpus: int = 2,
    watch_locks: bool = True,
    steps: int = 140,
    step_s: float = 0.2,
    wait_s: float = 4.0,
) -> Dict:
    """The elastic SPMD gang-re-mesh scenario (report: CHAOS_r11.json).

    Timeline: trainer runs on a 2-host MESH gang -> the harness SIGKILLs
    gang host B mid-step -> the head withdraws the gang, waits wait_s for
    a replacement, then re-plans a 1-host box -> the trainer resumes from
    the latest checkpoint at world size 1 -> the harness launches a
    replacement host at B's coordinate -> the sweep flags scale-up, the
    trainer re-meshes back to world size 2 and finishes every step."""
    from ray_tpu._private import lock_watchdog
    from ray_tpu._private.head import launch_head_subprocess
    from ray_tpu.util import tracing

    workdir = tempfile.mkdtemp(prefix=f"chaos-trainer-{seed}-")
    log_path = os.path.join(workdir, "executions.log")
    session = f"remesh{seed}x{os.getpid():x}"
    saved_env = {
        k: os.environ.get(k)
        for k in (
            "RAY_TPU_FAULT_SPEC",
            "RAY_TPU_REMESH_WAIT_S",
            "RAY_TPU_TRACE",
            "RAY_TPU_FLIGHT_DIR",
            "RAY_TPU_LOCK_WATCHDOG",
            "RAY_TPU_LOCK_WATCHDOG_DIR",
            "RAY_TPU_LOCK_HOLD_S",
            "RAY_TPU_METRICS_PUSH_MS",
        )
    }
    # No ambient fault storm: the chaos here is the host SIGKILL itself
    # (plus full telemetry/watchdog planes, which must stay clean).
    os.environ.pop("RAY_TPU_FAULT_SPEC", None)
    os.environ["RAY_TPU_REMESH_WAIT_S"] = str(wait_s)
    flight_dir = os.path.join(workdir, "flight")
    os.makedirs(flight_dir, exist_ok=True)
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_FLIGHT_DIR"] = flight_dir
    os.environ.setdefault("RAY_TPU_METRICS_PUSH_MS", "1000")
    tracing.enable_tracing()  # driver process: spans for the remesh stages
    watchdog_dir = os.path.join(workdir, "watchdog")
    if watch_locks:
        os.makedirs(watchdog_dir, exist_ok=True)
        os.environ["RAY_TPU_LOCK_WATCHDOG"] = "1"
        os.environ["RAY_TPU_LOCK_WATCHDOG_DIR"] = watchdog_dir
        os.environ.setdefault("RAY_TPU_LOCK_HOLD_S", "2.0")
        lock_watchdog._enable_for_tests(True)

    report: Dict = {
        "seed": seed,
        "scenario": "elastic-trainer",
        "steps": steps,
        "step_s": step_s,
        "remesh_wait_s": wait_s,
        "kills": {"gang_daemon": 0},
        "lock_watchdog": {"enabled": watch_locks, "reports": []},
        "result": "FAIL",
    }
    head = gang_a = gang_b = None
    import ray_tpu

    try:
        head, head_json = launch_head_subprocess(
            workdir, num_cpus=num_cpus, session=session
        )
        # Two gang hosts on a 1-D mesh (coordinates "0" and "1"); the
        # custom "gang" resource pins train workers onto them.
        gang_a = _launch_daemon(head_json, "gang-a", 2, spec_override="",
                                resources={"gang": 1.0},
                                labels={"mesh_coord": "0"})
        gang_b = _launch_daemon(head_json, "gang-b", 2, spec_override="",
                                resources={"gang": 1.0},
                                labels={"mesh_coord": "1"})
        ray_tpu.init(address=head_json)

        t0 = time.monotonic()

        def note(msg):
            print(f"[remesh t={time.monotonic() - t0:6.1f}s] {msg}",
                  flush=True)

        def wait_for(cond, what, deadline_s):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if trainer.failure:
                    raise AssertionError(f"trainer failed: {trainer.failure}")
                if cond():
                    return time.monotonic() - t0
                time.sleep(0.25)
            raise AssertionError(f"timed out after {deadline_s}s waiting "
                                 f"for {what}")

        trainer = _TrainerLoad(steps, step_s, log_path)
        trainer.start()

        # Phase 1: the full gang trains.
        wait_for(
            lambda: len(_steps_at_world(_train_step_counts(log_path), 2)) >= 10,
            "10 steps at world size 2", 120,
        )
        # Phase 2: SIGKILL gang host B mid-step (its PDEATHSIG-armed
        # train worker dies with it — a whole-host loss, not a clean
        # actor exit).
        note("SIGKILL gang-b daemon (host loss mid-step)")
        gang_b.kill()
        report["kills"]["gang_daemon"] += 1
        t_kill = time.monotonic() - t0
        # Phase 3: the gang must re-form at N-1 and RESUME training.
        steps_before_kill = _steps_at_world(_train_step_counts(log_path), 2)
        t_world1 = wait_for(
            lambda: len(_steps_at_world(_train_step_counts(log_path), 1)) >= 3,
            "training to resume at world size 1",
            wait_s + 60,
        )
        note(f"re-meshed at N-1, training resumed ({t_world1 - t_kill:.1f}s "
             "after the kill)")
        # Phase 4: a replacement host joins at B's coordinate; the sweep
        # flags scale-up and the trainer re-meshes back to full size.
        gang_b = _launch_daemon(head_json, "gang-b2", 2, spec_override="",
                                resources={"gang": 1.0},
                                labels={"mesh_coord": "1"})
        t_relaunch = time.monotonic() - t0
        note("replacement host gang-b2 launched at mesh_coord 1")
        t_world2 = wait_for(
            lambda: bool(
                _steps_at_world(_train_step_counts(log_path), 2)
                - steps_before_kill
            ),
            "training to scale back to world size 2", 90,
        )
        note(f"scaled back to N ({t_world2 - t_relaunch:.1f}s after the "
             "replacement joined)")
        # Phase 5: run to completion.
        trainer.join(timeout=steps * step_s + 240)
        assert not trainer.is_alive(), "trainer never finished (wedged)"
        assert trainer.failure is None, f"trainer failed: {trainer.failure}"
        result = trainer.result
        t_done = time.monotonic() - t0
        report["timeline"] = {
            "kill_at_s": round(t_kill, 2),
            "world1_resumed_at_s": round(t_world1, 2),
            "replacement_at_s": round(t_relaunch, 2),
            "world2_resumed_at_s": round(t_world2, 2),
            "done_at_s": round(t_done, 2),
            "shrink_recovery_s": round(t_world1 - t_kill, 2),
            "scale_up_recovery_s": round(t_world2 - t_relaunch, 2),
        }

        # ---- zero lost results: every step reported exactly once, in
        # order, across the whole elastic history.
        got = [m["step"] for m in result.metrics_history]
        assert got == list(range(steps)), (
            f"step history wrong: {len(got)} reports, "
            f"missing={sorted(set(range(steps)) - set(got))[:10]}, "
            f"dups={sorted({s for s in got if got.count(s) > 1})[:10]}"
        )
        # ---- the gang provably shrank and recovered: world sizes form
        # exactly the 2 -> 1 -> 2 envelope.
        worlds = [m["world"] for m in result.metrics_history]
        segments = [w for i, w in enumerate(worlds)
                    if i == 0 or worlds[i - 1] != w]
        assert segments == [2, 1, 2], (
            f"world-size history {segments} != [2, 1, 2]"
        )
        report["world_segments"] = segments
        # ---- bounded lost steps: re-executed (checkpointed-past) work
        # per re-mesh is at most the in-flight step + the undrained
        # report window per rank; across two episodes a generous cap
        # still proves checkpoint resume did its job.
        counts = _train_step_counts(log_path)
        by_rank_step: Dict[tuple, int] = {}
        for (rank, _w, step), n in counts.items():
            by_rank_step[(rank, step)] = by_rank_step.get((rank, step), 0) + n
        lost = sum(n - 1 for n in by_rank_step.values() if n > 1)
        report["lost_steps_reexecuted"] = lost
        assert lost <= 24, (
            f"{lost} steps re-executed — checkpoint resume is not bounding "
            "lost work"
        )
        # ---- recovery attribution: every stage of both episodes landed
        # in the remesh_seconds histogram (driver-side — fit() ran here).
        from ray_tpu._private import telemetry

        snap = telemetry.remesh_histogram().snapshot()
        stages = {dict(k).get("stage"): v for k, v in snap.items()}
        report["remesh_stages"] = {
            s: {"count": v["count"], "sum_s": round(v["sum"], 3)}
            for s, v in sorted(stages.items())
        }
        for stage in ("detect", "teardown", "replan", "respawn", "resume",
                      "total"):
            assert stages.get(stage, {}).get("count", 0) >= 2, (
                f"remesh stage {stage!r} missing from the histogram: "
                f"{report['remesh_stages']} (expected one sample per "
                "episode, 2 episodes)"
            )
        # Every episode's end-to-end recovery fits the 60s deadline (the
        # histogram's >60s buckets stay empty).
        h = telemetry.remesh_histogram()
        over_idx = h.boundaries.index(60.0)
        total_buckets = stages["total"]["buckets"]
        assert sum(total_buckets[over_idx + 1:]) == 0, (
            f"a re-mesh took >60s: total buckets {total_buckets} over "
            f"boundaries {h.boundaries}"
        )
        # ---- the ledger converges: no leaked objects from the killed
        # host's in-flight work.
        from ray_tpu.util import state as state_api

        mem = None
        mem_deadline = time.monotonic() + 90
        while time.monotonic() < mem_deadline:
            try:
                mem = state_api.memory_summary(top=0)
            except Exception:
                time.sleep(1.0)
                continue
            if mem["leak_suspects"] == 0:
                break
            time.sleep(1.0)
        report["memory"] = {
            "leak_suspects": mem["leak_suspects"] if mem else None,
            "objects": mem["objects"] if mem else None,
        }
        assert mem is not None and mem["leak_suspects"] == 0, (
            f"object ledger did not converge after the host kill: {mem}"
        )
        if watch_locks:
            wd = lock_watchdog.collect_dir_reports(watchdog_dir)
            wd.extend(f"driver: {r}" for r in lock_watchdog.reports())
            report["lock_watchdog"]["reports"] = wd
            assert not wd, f"lock watchdog reports under re-mesh: {wd}"
        report["result"] = "PASS"
        return report
    except BaseException:
        print(
            "\n=== ELASTIC-TRAINER SOAK FAILED — replay with:\n"
            f"    python scripts/chaos_soak.py --trainer --seed {seed}\n"
            f"    (session dir kept at {workdir})",
            file=sys.stderr,
            flush=True,
        )
        raise
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for proc in (gang_a, gang_b, head):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if watch_locks:
            lock_watchdog._enable_for_tests(
                os.environ.get("RAY_TPU_LOCK_WATCHDOG") == "1"
            )
        if out and report.get("result"):
            with open(out, "w") as f:
                json.dump(report, f, indent=1, sort_keys=True)
                f.write("\n")


def run_autoscale_soak(
    seed: int = 12,
    out: Optional[str] = None,
    watch_locks: bool = True,
) -> Dict:
    """The elastic-capacity scenario (report: CHAOS_r12.json).

    Timeline: the head boots with the demand-driven autoscaler ON
    (min=1/max=4, LocalProcessProvider) -> serve replicas + a 1-CPU task
    wave push demand and the fleet grows to max -> sole-copy shm objects
    are pinned onto two autoscaled nodes -> node A is drained and its
    daemon SIGKILLed MID-EVACUATION (the spec delays every evacuation
    pull, widening the window) -> the death path + lineage re-derive A's
    results -> node B is drained and the HEAD is SIGKILLed mid-drain ->
    the relaunched head replays every journaled lifecycle transition,
    the resumed reconciler finishes B's evacuation with a clean ledger
    (zero lost bytes: B's producers run exactly once) -> the idle fleet
    drains itself back to the floor.  PASS requires zero lost results,
    zero lost sole-copy bytes, a converged object ledger, and a silent
    lock watchdog."""
    from ray_tpu._private import faults, lock_watchdog
    from ray_tpu._private.head import launch_head_subprocess
    from ray_tpu.util import state as state_api
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    # The only spec clause: stretch each evacuation pull so the daemon
    # SIGKILL and the head SIGKILL both land INSIDE the evacuation loop.
    spec = "node.evacuate:delay=0.3"
    faults.configure(spec, seed)
    faults.disable()  # driver stays clean; the head enables from env

    workdir = tempfile.mkdtemp(prefix=f"chaos-autoscale-{seed}-")
    log_path = os.path.join(workdir, "executions.log")
    session = f"elastic{seed}x{os.getpid():x}"
    saved_env = {
        k: os.environ.get(k)
        for k in (
            "RAY_TPU_FAULT_SPEC",
            "RAY_TPU_FAULT_SEED",
            "RAY_TPU_RECONNECT_WINDOW_S",
            "RAY_TPU_TRACE",
            "RAY_TPU_FLIGHT_DIR",
            "RAY_TPU_LOCK_WATCHDOG",
            "RAY_TPU_LOCK_WATCHDOG_DIR",
            "RAY_TPU_LOCK_HOLD_S",
            "RAY_TPU_METRICS_PUSH_MS",
            "RAY_TPU_AUTOSCALE_ENABLED",
            "RAY_TPU_AUTOSCALE_INTERVAL_S",
            "RAY_TPU_AUTOSCALE_MIN_NODES",
            "RAY_TPU_AUTOSCALE_MAX_NODES",
            "RAY_TPU_AUTOSCALE_UP_WAIT_S",
            "RAY_TPU_AUTOSCALE_IDLE_S",
            "RAY_TPU_AUTOSCALE_LAUNCH_TIMEOUT_S",
            "RAY_TPU_AUTOSCALE_DRAIN_TIMEOUT_S",
        )
    }
    os.environ["RAY_TPU_FAULT_SPEC"] = spec
    os.environ["RAY_TPU_FAULT_SEED"] = str(seed)
    os.environ["RAY_TPU_RECONNECT_WINDOW_S"] = "45"
    # Elastic knobs: every head incarnation (launch_head_subprocess copies
    # os.environ) runs the embedded reconciler with the same aggressive
    # cadence, so the post-bounce head resumes B's drain on its own.
    os.environ["RAY_TPU_AUTOSCALE_ENABLED"] = "1"
    os.environ["RAY_TPU_AUTOSCALE_INTERVAL_S"] = "0.25"
    os.environ["RAY_TPU_AUTOSCALE_MIN_NODES"] = "1"
    os.environ["RAY_TPU_AUTOSCALE_MAX_NODES"] = "4"
    os.environ["RAY_TPU_AUTOSCALE_UP_WAIT_S"] = "0.5"
    # Long enough that autonomous idle-drain never races the scripted
    # chaos on A/B, short enough that wind-down fits the soak budget.
    os.environ["RAY_TPU_AUTOSCALE_IDLE_S"] = "15"
    os.environ["RAY_TPU_AUTOSCALE_LAUNCH_TIMEOUT_S"] = "20"
    os.environ["RAY_TPU_AUTOSCALE_DRAIN_TIMEOUT_S"] = "6"
    flight_dir = os.path.join(workdir, "flight")
    os.makedirs(flight_dir, exist_ok=True)
    os.environ["RAY_TPU_TRACE"] = "1"
    os.environ["RAY_TPU_FLIGHT_DIR"] = flight_dir
    os.environ.setdefault("RAY_TPU_METRICS_PUSH_MS", "1000")
    watchdog_dir = os.path.join(workdir, "watchdog")
    if watch_locks:
        os.makedirs(watchdog_dir, exist_ok=True)
        os.environ["RAY_TPU_LOCK_WATCHDOG"] = "1"
        os.environ["RAY_TPU_LOCK_WATCHDOG_DIR"] = watchdog_dir
        os.environ.setdefault("RAY_TPU_LOCK_HOLD_S", "2.0")
        lock_watchdog._enable_for_tests(True)

    report: Dict = {
        "seed": seed,
        "scenario": "elastic-autoscale",
        "spec": spec,
        "kills": {"head": 0, "daemon": 0},
        "lock_watchdog": {"enabled": watch_locks, "reports": []},
        "result": "FAIL",
    }
    RANK = {
        "REQUESTED": 0, "STARTING": 1, "ACTIVE": 2,
        "DRAINING": 3, "DEPARTED": 4,
    }
    PINS = 4
    head = None
    daemon_pids: Dict[str, int] = {}
    import ray_tpu

    try:
        head, head_json = launch_head_subprocess(
            workdir, num_cpus=2, session=session
        )
        ray_tpu.init(address=head_json)
        t0 = time.monotonic()

        def note(msg):
            print(f"[elastic t={time.monotonic() - t0:6.1f}s] {msg}",
                  flush=True)

        def _req(op, payload=None):
            from ray_tpu._private.worker_proc import get_worker_runtime

            return get_worker_runtime().request(op, payload)

        def lifecycle() -> Dict[str, Dict]:
            try:
                return _req("node_lifecycle")
            except Exception:
                return {}  # head mid-bounce: answer again next poll

        def managed(*states) -> Dict[str, Dict]:
            return {
                nid: rec
                for nid, rec in lifecycle().items()
                if rec.get("src") == "autoscaler"
                and (not states or rec.get("state") in states)
            }

        def wait_for(cond, what, deadline_s):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                try:
                    # Conditions poll THROUGH head bounces: a dropped
                    # request is "not yet", never a verdict.
                    if cond():
                        return time.monotonic() - t0
                except Exception:
                    pass
                time.sleep(0.25)
            raise AssertionError(
                f"timed out after {deadline_s}s waiting for {what}"
            )

        def _counts(prefix: str) -> Dict[str, int]:
            c: Dict[str, int] = {}
            try:
                with open(log_path) as f:
                    for line in f:
                        line = line.strip()
                        if line.startswith(prefix + ":"):
                            c[line] = c.get(line, 0) + 1
            except FileNotFoundError:
                pass
            return c

        def _note_pids():
            for row in state_api.list_nodes():
                if row.get("daemon_pid"):
                    daemon_pids[row["node_id"]] = row["daemon_pid"]

        # ---- phase 1: the floor launch (min_nodes=1, zero demand).
        t_floor = wait_for(
            lambda: len(managed("ACTIVE")) >= 1, "the floor node", 30
        )
        note("floor node ACTIVE")

        # ---- phase 2: demand wave.  Serve replica targets land in the
        # demand summary; a 1-CPU task wave outlives the up-wait window
        # and the reconciler grows the fleet to max.
        from ray_tpu import serve as serve_mod

        serve_mod.start(http_options={"host": "127.0.0.1", "port": 0})

        @serve_mod.deployment(
            name="elastic",
            num_replicas=2,
            ray_actor_options={"max_restarts": 100},
        )
        def elastic_dep(body=None):
            return {"ok": True}

        serve_mod.run(elastic_dep.bind())
        wait_for(
            lambda: "elastic" in state_api.demand_summary()["serve_targets"],
            "serve replica targets in the demand summary", 30,
        )
        note("serve targets visible in demand summary")

        wave_refs = [wave_work.remote(i, 1.5, log_path) for i in range(24)]
        t_max = wait_for(
            lambda: len(managed("ACTIVE")) >= 4,
            "the fleet to reach max_nodes=4", 90,
        )
        note(f"fleet at max ({t_max - t_floor:.1f}s after the floor)")
        _note_pids()
        wave_out = ray_tpu.get(wave_refs, timeout=240)
        assert sorted(wave_out) == list(range(24)), (
            f"lost wave results: {sorted(wave_out)}"
        )
        del wave_refs, wave_out
        serve_mod.shutdown()  # replicas off the fleet before the chaos

        # ---- phase 3: pin sole-copy shm objects onto two autoscaled
        # nodes (soft affinity; ARR int64 payloads are store-sealed).
        fleet = sorted(managed("ACTIVE"))
        assert len(fleet) >= 3, f"fleet shrank early: {fleet}"
        node_a, node_b = fleet[0], fleet[1]

        def _fleet_idle():
            # Serve teardown + wave lease expiry are asynchronous; pins
            # only target a node reliably once its CPU is back in the pool.
            rws = {r["node_id"]: r for r in state_api.list_nodes()}
            return all(
                rws[nid]["available"].get("CPU")
                == rws[nid]["resources"].get("CPU")
                for nid in fleet
            )

        wait_for(_fleet_idle, "the fleet to go idle before pinning", 30)

        def _pin(nid, tag):
            # SERIAL submissions: the target has 1 CPU, and soft affinity
            # spills a busy node's overflow elsewhere — one in flight at
            # a time keeps every pin (and its lease reuse) on the target.
            strat = NodeAffinitySchedulingStrategy(nid, soft=True)
            refs = []
            for i in range(PINS):
                r = produce.options(scheduling_strategy=strat).remote(
                    i, tag, log_path
                )
                ready, _ = ray_tpu.wait(
                    [r], timeout=60,
                    fetch_local=False,  # a driver fetch breaks sole-copy-ness
                )
                assert ready, f"pin {tag}:{i} did not finish"
                refs.append(r)
            return refs

        pin_a = _pin(node_a, "pinA")
        pin_b = _pin(node_b, "pinB")
        rows = {r["node_id"]: r for r in state_api.list_nodes()}
        for nid in (node_a, node_b):
            assert rows[nid]["store_bytes"] >= PINS * ARR * 8, (
                f"pins did not land on {nid}: {rows[nid]}"
            )
        _note_pids()

        # ---- phase 4: drain A, SIGKILL its daemon mid-evacuation.  The
        # drain must fall back to the DEATH path: lineage re-derives A's
        # sole copies on the survivors.
        pid_a = rows[node_a]["daemon_pid"]
        assert pid_a, f"no daemon pid for {node_a}"
        assert _req("node_drain", node_a) is True
        wait_for(
            lambda: lifecycle().get(node_a, {}).get("state")
            in ("DRAINING", "DEPARTED"),
            "A's drain to journal", 10,
        )
        time.sleep(0.7)  # quiesce beat + first delayed evacuation pulls
        note(f"SIGKILL {node_a} daemon mid-evacuation")
        os.kill(pid_a, signal.SIGKILL)
        report["kills"]["daemon"] += 1
        wait_for(
            lambda: lifecycle().get(node_a, {}).get("state") == "DEPARTED",
            "A to close DEPARTED via the death path", 30,
        )
        rec_a = lifecycle()[node_a]
        assert rec_a.get("reason") == "died", rec_a
        out_a = ray_tpu.get(pin_a, timeout=120)
        for i, arr in enumerate(out_a):
            assert arr.shape == (ARR,) and int(arr[0]) == i, (
                f"pinA[{i}] wrong after mid-evacuation kill"
            )
        report["pin_a_exec_counts"] = _counts("produce:pinA")
        note("A's results re-derived via lineage after the kill")
        del pin_a, out_a

        # ---- phase 5: drain B, SIGKILL the HEAD mid-drain.  The
        # relaunched head must replay every journaled transition and the
        # resumed reconciler must finish B's evacuation losslessly.
        pre = lifecycle()
        assert pre, "lifecycle table empty before the bounce"
        assert _req("node_drain", node_b) is True
        wait_for(
            lambda: lifecycle().get(node_b, {}).get("state") == "DRAINING",
            "B's drain to journal", 10,
        )
        time.sleep(0.6)  # land inside B's delayed evacuation loop
        note("SIGKILL head mid-drain (bounce mid-reconcile)")
        head.kill()
        try:
            head.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        report["kills"]["head"] += 1
        head, _ = launch_head_subprocess(workdir, num_cpus=2, session=session)
        note("head relaunched; waiting for lifecycle replay")
        wait_for(
            lambda: lifecycle().get(node_b, {}).get("state")
            in ("DRAINING", "DEPARTED"),
            "the restored lifecycle table", 60,
        )
        post = lifecycle()
        for nid, rec in pre.items():
            assert nid in post, f"journaled node {nid} lost in the bounce"
            assert RANK[post[nid]["state"]] >= RANK[rec["state"]], (
                f"{nid} regressed across the bounce: "
                f"{rec['state']} -> {post[nid]['state']}"
            )
            if rec.get("src"):
                assert post[nid].get("src") == rec["src"], (nid, post[nid])
        assert post[node_a].get("reason") == "died", post[node_a]
        report["lifecycle_replayed"] = {
            nid: post[nid]["state"] for nid in sorted(pre)
        }
        t_b = wait_for(
            lambda: lifecycle().get(node_b, {}).get("state") == "DEPARTED",
            "the resumed reconciler to finish B's drain", 60,
        )
        rec_b = lifecycle()[node_b]
        assert rec_b.get("reason") == "removed", (
            f"B's drain did not finish cleanly: {rec_b}"
        )
        note(f"B drained clean by the post-bounce reconciler (t={t_b:.1f}s)")
        # The evacuation ledger on the NEW head: B's final pass must
        # report remaining=0 (zero lost sole-copy bytes) and have moved
        # at least one object post-bounce.
        evs = [
            e
            for e in state_api.list_cluster_events(
                limit=200, source="autoscale"
            )
            if e.get("message") == "node evacuation"
            and e.get("node_id") == node_b
        ]
        assert evs, "no evacuation ledger events for B on the new head"
        assert evs[-1].get("remaining") == 0, f"lost bytes on B: {evs[-1]}"
        moved = sum(e.get("moved", 0) for e in evs)
        assert moved >= 1, f"nothing evacuated post-bounce: {evs}"
        report["evacuation"] = {
            "events": len(evs),
            "moved": moved,
            "moved_bytes": sum(e.get("moved_bytes", 0) for e in evs),
            "failed": sum(e.get("failed", 0) for e in evs),
        }
        # Zero lost bytes, PROVEN: B's results come back correct and its
        # producers ran exactly ONCE — the bytes moved, nothing re-ran.
        out_b = ray_tpu.get(pin_b, timeout=120)
        for i, arr in enumerate(out_b):
            assert arr.shape == (ARR,) and int(arr[0]) == i, (
                f"pinB[{i}] wrong after the drained depart"
            )
        cb = _counts("produce:pinB")
        assert len(cb) == PINS and all(v == 1 for v in cb.values()), (
            f"B's producers re-ran — evacuation lost bytes: {cb}"
        )
        report["pin_b_exec_counts"] = cb
        note("B's sole copies survived: values intact, zero re-executions")
        del pin_b, out_b

        # ---- phase 6: wind-down.  With demand gone the reconciler
        # idle-drains the surplus back to the floor on its own.
        t_down = wait_for(
            lambda: len(
                managed("REQUESTED", "STARTING", "ACTIVE", "DRAINING")
            ) <= 1,
            "the fleet to drain back to the floor", 120,
        )
        assert len(managed("ACTIVE")) == 1
        note(f"fleet back at the floor (t={t_down:.1f}s)")
        report["timeline"] = {
            "floor_at_s": round(t_floor, 2),
            "max_fleet_at_s": round(t_max, 2),
            "b_drained_at_s": round(t_b, 2),
            "floor_again_at_s": round(t_down, 2),
        }

        # ---- the stage histogram made it to the pushed-metrics plane.
        def _hist_count():
            agg = state_api.telemetry_summary()["aggregate"]
            return sum(
                v for k, v in agg.items()
                if k.startswith("autoscale_seconds_count")
            )

        wait_for(
            lambda: _hist_count() >= 1,
            "autoscale_seconds samples on the metrics plane", 30,
        )
        report["autoscale_seconds_samples"] = _hist_count()

        # ---- the object ledger converges after both kills.
        mem = None
        mem_deadline = time.monotonic() + 90
        while time.monotonic() < mem_deadline:
            try:
                mem = state_api.memory_summary(top=0)
            except Exception:
                time.sleep(1.0)
                continue
            if mem["leak_suspects"] == 0:
                break
            time.sleep(1.0)
        report["memory"] = {
            "leak_suspects": mem["leak_suspects"] if mem else None,
            "objects": mem["objects"] if mem else None,
        }
        assert mem is not None and mem["leak_suspects"] == 0, (
            f"object ledger did not converge after the chaos: {mem}"
        )

        # ---- every lifecycle state the soak produced is a known state.
        final = lifecycle()
        bad = {
            nid: rec for nid, rec in final.items()
            if rec.get("state") not in RANK
        }
        assert not bad, f"unknown lifecycle states: {bad}"
        report["final_lifecycle"] = {
            nid: {"state": rec["state"], "reason": rec.get("reason")}
            for nid, rec in sorted(final.items())
        }

        if watch_locks:
            wd = lock_watchdog.collect_dir_reports(watchdog_dir)
            wd.extend(f"driver: {r}" for r in lock_watchdog.reports())
            report["lock_watchdog"]["reports"] = wd
            assert not wd, f"lock watchdog reports under autoscale: {wd}"
        report["result"] = "PASS"
        return report
    except BaseException:
        print(
            "\n=== ELASTIC-AUTOSCALE SOAK FAILED — replay with:\n"
            f"    python scripts/chaos_soak.py --autoscale --seed {seed}\n"
            f"    (session dir kept at {workdir})",
            file=sys.stderr,
            flush=True,
        )
        raise
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        if head is not None and head.poll() is None:
            head.terminate()
            try:
                head.wait(timeout=10)
            except subprocess.TimeoutExpired:
                head.kill()
        # Autoscaled daemons are children of (possibly SIGKILLed) head
        # incarnations — reap any stragglers so the box stays clean.
        for nid, pid in daemon_pids.items():
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        if watch_locks:
            lock_watchdog._enable_for_tests(
                os.environ.get("RAY_TPU_LOCK_WATCHDOG") == "1"
            )
        if out and report.get("result"):
            with open(out, "w") as f:
                json.dump(report, f, indent=1, sort_keys=True)
                f.write("\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--duration", type=float, default=75.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--spec", default=DEFAULT_SPEC)
    ap.add_argument("--out", default=None)
    ap.add_argument("--no-serve", action="store_true")
    ap.add_argument("--num-cpus", type=int, default=4)
    ap.add_argument("--no-lock-watchdog", action="store_true")
    ap.add_argument(
        "--trainer", action="store_true",
        help="run the elastic SPMD gang re-mesh scenario instead "
             "(report: CHAOS_r11.json)",
    )
    ap.add_argument(
        "--autoscale", action="store_true",
        help="run the elastic-capacity autoscaler scenario instead "
             "(report: CHAOS_r12.json)",
    )
    args = ap.parse_args(argv)
    if args.autoscale:
        report = run_autoscale_soak(
            seed=args.seed if args.seed != 7 else 12,
            out=args.out or "CHAOS_r12.json",
            watch_locks=not args.no_lock_watchdog,
        )
        print(json.dumps(report, indent=1, sort_keys=True))
        return 0
    if args.trainer:
        report = run_trainer_soak(
            seed=args.seed if args.seed != 7 else 11,
            out=args.out or "CHAOS_r11.json",
            num_cpus=args.num_cpus,
            watch_locks=not args.no_lock_watchdog,
        )
        print(json.dumps(report, indent=1, sort_keys=True))
        return 0
    report = run_soak(
        duration=args.duration,
        seed=args.seed,
        spec=args.spec,
        out=args.out,
        use_serve=not args.no_serve,
        num_cpus=args.num_cpus,
        watch_locks=not args.no_lock_watchdog,
    )
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
