"""Component timing on the real chip: where does the train step spend time?

Times (a) pure-matmul proxy of the model's param flops, (b) attention
forward, (c) attention fwd+bwd, (d) full train step fwd+bwd.  Run on the
axon TPU to locate the MFU gap before optimizing.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(fn, *args, iters=5, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready") else x, out
    )
    # axon: block_until_ready may not sync; force a host fetch
    leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "shape")]
    if leaves:
        float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "shape")]
    if leaves:
        float(jnp.sum(leaves[0].astype(jnp.float32).ravel()[0]))
    return (time.perf_counter() - t0) / iters


def main():
    dev = jax.devices()[0]
    print("device:", dev.device_kind)

    B, S, H, Dh, E, F, V, L = 8, 2048, 12, 128, 1536, 4096, 32000, 24

    # (a) pure matmul proxy: one big bf16 matmul, report achieved TFLOP/s
    m, k, n = 8192, 8192, 8192
    a = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    dt = timeit(mm, a, b)
    print(f"matmul {m}x{k}x{n} bf16: {2*m*k*n/dt/1e12:.1f} TFLOP/s ({dt*1e3:.2f} ms)")

    # (b/c) attention fwd and fwd+bwd
    from ray_tpu.ops.pallas.flash_attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, Dh), jnp.bfloat16)
    k_ = jax.random.normal(jax.random.PRNGKey(3), (B, S, H, Dh), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(4), (B, S, H, Dh), jnp.bfloat16)

    fa = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    dt = timeit(fa, q, k_, v)
    attn_flops = 4 * B * H * S * S * Dh / 2  # causal halves the work
    print(f"flash fwd: {dt*1e3:.2f} ms  ({attn_flops/dt/1e12:.1f} TFLOP/s)  x{L} layers = {L*dt*1e3:.1f} ms")

    def loss_fn(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True).astype(jnp.float32))

    fab = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))
    dt = timeit(fab, q, k_, v)
    print(f"flash fwd+bwd(grad): {dt*1e3:.2f} ms  x{L} layers = {L*dt*1e3:.1f} ms")

    # reference: xla attention fwd+bwd
    from ray_tpu.ops.attention import blockwise_attention

    def loss_bw(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, causal=True).astype(jnp.float32))

    bwb = jax.jit(jax.grad(loss_bw, argnums=(0, 1, 2)))
    dt = timeit(bwb, q, k_, v)
    print(f"blockwise fwd+bwd(grad): {dt*1e3:.2f} ms  x{L} layers = {L*dt*1e3:.1f} ms")

    # plain softmax attention fwd+bwd (XLA fused)
    def plain(q, k, v):
        qf = q.astype(jnp.float32) * (Dh ** -0.5)
        logits = jnp.einsum("bshd,bthd->bhst", qf, k.astype(jnp.float32))
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1).astype(jnp.bfloat16)
        return jnp.einsum("bhst,bthd->bshd", p, v)

    pb = jax.jit(jax.grad(lambda q, k, v: jnp.sum(plain(q, k, v).astype(jnp.float32)), argnums=(0, 1, 2)))
    try:
        dt = timeit(pb, q, k_, v)
        print(f"plain-xla fwd+bwd(grad): {dt*1e3:.2f} ms  x{L} layers = {L*dt*1e3:.1f} ms")
    except Exception as e:
        print("plain-xla OOM/fail:", type(e).__name__)

    # (d) full train step (current bench config)
    from ray_tpu.models import LMTrainContext, TransformerConfig
    from ray_tpu.parallel import MeshSpec, build_mesh

    cfg = TransformerConfig(
        vocab_size=V, d_model=E, n_layers=L, n_heads=H, n_kv_heads=H,
        d_ff=F, max_seq_len=S, param_dtype=jnp.bfloat16, remat=True,
    )
    mesh = build_mesh(MeshSpec(data=1), devices=[dev])
    ctx = LMTrainContext(cfg, mesh=mesh, strategy="dp")
    state = ctx.init_state(seed=0)
    toks = jax.random.randint(jax.random.PRNGKey(5), (B, S + 1), 0, V)
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}

    for _ in range(2):
        state, metrics = ctx.train_step(state, batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(5):
        state, metrics = ctx.train_step(state, batch)
    float(metrics["loss"])
    dt = (time.perf_counter() - t0) / 5
    n_params = cfg.num_params()
    tokens_per_s = B * S / dt
    print(f"full step: {dt*1e3:.1f} ms  {tokens_per_s:.0f} tok/s  mfu={6*n_params*tokens_per_s/197e12:.3f}")


if __name__ == "__main__":
    main()
