"""Scale-envelope benchmarks (ray: release/benchmarks/ many_tasks /
many_actors / many_pgs + scalability/single_node.json shapes).

Reproduces the reference's release-qualification shapes at single-host CI
scale and records throughputs with honest hardware caveats (the reference
ran these on 64-node AWS clusters; this host is usually 1 vCPU):

  many_actors      N actors created + first call acked, then killed
  many_tasks       M tasks queued at once, drained through the pool
  many_pgs         P placement groups created (ready) then removed
  many_objects     K driver puts, then one bulk get of all K
  broadcast        100MB object pulled by 3 isolated-store daemon nodes

Run: python scripts/scale_bench.py [--actors 1000] [--tasks 10000]
     [--pgs 200] [--objects 10000] [--output BENCH_scale.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-only workers must boot fast (no jax import via sitecustomize).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _rss_gb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / 1024 / 1024
    except OSError:
        pass
    return 0.0


def bench_many_actors(n: int, wave: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.001)
    class Tiny:
        def ping(self):
            return 1

    t0 = time.monotonic()
    peak_live = 0
    created = 0
    handles = []
    for start in range(0, n, wave):
        batch = [Tiny.remote() for _ in range(min(wave, n - start))]
        ray_tpu.get([a.ping.remote() for a in batch], timeout=600)
        created += len(batch)
        handles.extend(batch)
        peak_live = max(peak_live, len(handles))
    dt = time.monotonic() - t0
    t1 = time.monotonic()
    for a in handles:
        ray_tpu.kill(a)
    kill_dt = time.monotonic() - t1
    return {
        "actors_created": created,
        "actors_per_s": round(created / dt, 1),
        "peak_live_actors": peak_live,
        "kill_s": round(kill_dt, 1),
    }


def bench_many_tasks(m: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.5)
    def noop(i):
        return i

    t0 = time.monotonic()
    refs = [noop.remote(i) for i in range(m)]
    submit_dt = time.monotonic() - t0
    out = ray_tpu.get(refs, timeout=1200)
    total_dt = time.monotonic() - t0
    assert out[-1] == m - 1 and len(out) == m
    return {
        "tasks_queued": m,
        "submit_per_s": round(m / submit_dt, 1),
        "drain_per_s": round(m / total_dt, 1),
    }


def bench_backlog(n: int, spill_after: int) -> dict:
    """Absorb an n-task backlog on one head with BOUNDED RSS (reference:
    '1M queued tasks on one node', SURVEY.md §6 stress_tests).

    Methodology: measure steady-state head RSS after a small warmup,
    submit n dependency-free noop tasks as fast as the submit path goes
    (specs beyond ready_queue_spill_after overflow to the disk segment —
    runtime._ReadySpill), sample RSS throughout the drain, and prove
    completion by counter delta: every 1000th task carries num_returns=1
    and its value is asserted; the rest run with num_returns=0 (zero
    result objects — the backlog stresses the QUEUE, not the store).
    Zero lost results == tasks_finished advanced by exactly n and every
    sampled return value is correct."""
    import ray_tpu
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()

    @ray_tpu.remote(num_cpus=0.5, max_retries=5)
    def nought():
        return None

    @ray_tpu.remote(num_cpus=0.5, max_retries=5)
    def probe(i):
        return i

    # Warmup: workers booted, pools warm, THEN the steady-state floor.
    ray_tpu.get([probe.remote(i) for i in range(200)], timeout=300)
    time.sleep(1.0)
    steady_gb = _rss_gb()
    base_finished = rt.metrics["tasks_finished"] + rt.metrics["tasks_failed"]

    peak_gb = steady_gb
    probes = []
    t0 = time.monotonic()
    for i in range(n):
        if i % 1000 == 999:
            probes.append((i, probe.remote(i)))
            peak_gb = max(peak_gb, _rss_gb())
        else:
            nought.options(num_returns=0).remote()
    submit_dt = time.monotonic() - t0
    spill = rt._ready_spill
    spilled_peak = spill.appended if spill is not None else 0
    backlog_peak = len(rt.tasks) + (spill.count if spill is not None else 0)

    # Drain, sampling RSS once a second.
    deadline = time.monotonic() + 3600
    while time.monotonic() < deadline:
        done = (
            rt.metrics["tasks_finished"] + rt.metrics["tasks_failed"]
            - base_finished
        )
        peak_gb = max(peak_gb, _rss_gb())
        if done >= n:
            break
        time.sleep(1.0)
    total_dt = time.monotonic() - t0
    finished = rt.metrics["tasks_finished"] - base_finished
    failed = rt.metrics["tasks_failed"]
    vals = ray_tpu.get([r for _i, r in probes], timeout=600)
    assert vals == [i for i, _r in probes], "probe results corrupted"
    return {
        "backlog_tasks": n,
        "spill_after": spill_after,
        "submit_per_s": round(n / submit_dt, 1),
        "drain_per_s": round(n / total_dt, 1),
        "specs_spilled": spilled_peak,
        "backlog_peak": backlog_peak,
        "tasks_finished": finished,
        "tasks_failed": failed,
        "lost_results": n - finished - failed,
        "probes_verified": len(probes),
        "steady_rss_gb": round(steady_gb, 3),
        "peak_rss_gb": round(peak_gb, 3),
        "rss_ratio": round(peak_gb / steady_gb, 2) if steady_gb else None,
    }


def bench_many_pgs(p: int) -> dict:
    import ray_tpu

    t0 = time.monotonic()
    pgs = [
        ray_tpu.util.placement_group([{"CPU": 0.001}], strategy="PACK")
        for _ in range(p)
    ]
    for pg in pgs:
        pg.wait(timeout_seconds=120)
    create_dt = time.monotonic() - t0
    t1 = time.monotonic()
    for pg in pgs:
        ray_tpu.util.remove_placement_group(pg)
    remove_dt = time.monotonic() - t1
    return {
        "pgs": p,
        "pgs_per_s": round(p / create_dt, 1),
        "remove_per_s": round(p / remove_dt, 1),
    }


def bench_many_objects(k: int) -> dict:
    import ray_tpu

    t0 = time.monotonic()
    refs = [ray_tpu.put(i) for i in range(k)]
    put_dt = time.monotonic() - t0
    t1 = time.monotonic()
    vals = ray_tpu.get(refs, timeout=600)
    get_dt = time.monotonic() - t1
    assert vals[k - 1] == k - 1
    return {
        "objects": k,
        "puts_per_s": round(k / put_dt, 1),
        "gets_per_s": round(k / get_dt, 1),
    }


def bench_actor_churn(
    n_live: int, waves: int, wave_size: int, traffic_actors: int = 4
) -> dict:
    """ROADMAP item 2's churn scenario: create/kill waves against a live
    actor pool WHILE background traffic keeps calling survivors — the
    many_actors shape measures a quiet cluster, this one measures
    creation under load.  Creation latency is attributed PER STAGE from
    the new task-lifecycle records (`util/state.task_summary`): the
    report says whether a slow wave spent its time queued, leasing
    (worker spawn), or running __init__ — the evidence the actors/s hunt
    starts from, instead of one opaque wall number."""
    import threading

    import ray_tpu
    from ray_tpu.util import state as state_api

    @ray_tpu.remote(num_cpus=0.001)
    class Churn:
        def ping(self):
            return 1

    # Steady pool + background traffic over it.
    pool = [Churn.remote() for _ in range(n_live)]
    ray_tpu.get([a.ping.remote() for a in pool], timeout=600)
    stop = threading.Event()
    traffic_calls = [0]

    def _traffic():
        i = 0
        while not stop.is_set():
            batch = [
                pool[(i + j) % len(pool)].ping.remote()
                for j in range(traffic_actors)
            ]
            try:
                ray_tpu.get(batch, timeout=120)
            except Exception:
                pass  # a killed actor mid-wave: traffic keeps going
            traffic_calls[0] += len(batch)
            i += traffic_actors

    t = threading.Thread(target=_traffic, daemon=True)
    t.start()

    wave_lat: list = []
    t0 = time.monotonic()
    for _w in range(waves):
        w0 = time.monotonic()
        fresh = [Churn.remote() for _ in range(wave_size)]
        ray_tpu.get([a.ping.remote() for a in fresh], timeout=600)
        wave_lat.append(time.monotonic() - w0)
        # Kill the oldest wave-size actors; the fresh ones replace them.
        victims, pool = pool[:wave_size], pool[wave_size:] + fresh
    churn_dt = time.monotonic() - t0
    stop.set()
    t.join(timeout=30)

    # Per-stage creation latency from the attribution plane: only
    # actor-creation records (event["creation"]) from this run's window.
    summary = state_api.task_summary(slow=2000)
    creations = [r for r in summary["slow"] if r.get("creation")]
    stage_tot: dict = {}
    for r in creations:
        for k, v in (r["durations"] or {}).items():
            stage_tot.setdefault(k, []).append(v)
    per_stage = {
        k: {
            "mean_s": round(sum(v) / len(v), 6),
            "p95_s": round(sorted(v)[int(0.95 * (len(v) - 1))], 6),
            "n": len(v),
        }
        for k, v in sorted(stage_tot.items())
    }
    for a in pool:
        ray_tpu.kill(a)
    created = waves * wave_size
    return {
        "live_pool": n_live,
        "waves": waves,
        "wave_size": wave_size,
        "created_under_load": created,
        "churn_creations_per_s": round(created / churn_dt, 1),
        "wave_latency_s": [round(x, 3) for x in wave_lat],
        "traffic_calls_during_churn": traffic_calls[0],
        "creation_stage_latency": per_stage,
        "creation_records_seen": len(creations),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--actors", type=int, default=1000)
    ap.add_argument("--actor-wave", type=int, default=200,
                    help="actors created+acked per wave (bounds the spawn "
                         "burst; all waves stay alive until the kill phase)")
    ap.add_argument("--tasks", type=int, default=10000)
    ap.add_argument("--pgs", type=int, default=200)
    ap.add_argument("--objects", type=int, default=10000)
    ap.add_argument("--skip-broadcast", action="store_true")
    ap.add_argument(
        "--churn", action="store_true",
        help="ONLY the churn scenario: create/kill waves under live "
             "traffic, per-stage creation latency from task_summary",
    )
    ap.add_argument("--churn-live", type=int, default=60,
                    help="steady actor pool size during churn")
    ap.add_argument("--churn-waves", type=int, default=5)
    ap.add_argument("--churn-wave-size", type=int, default=20)
    ap.add_argument(
        "--backlog", type=int, default=0, metavar="N",
        help="ONLY the backlog scenario: absorb N queued tasks on one "
             "head with bounded RSS (ready-queue disk overflow), then "
             "drain to completion with zero lost results",
    )
    ap.add_argument(
        "--spill-after", type=int, default=10000,
        help="ready_queue_spill_after for the backlog scenario (in-memory "
             "backlog cap before specs overflow to disk; ~1.2KB of head "
             "RSS per in-memory task is the knob's direct meaning)",
    )
    ap.add_argument("--output", default=None)
    args = ap.parse_args(argv)

    if args.backlog:
        # Must be exported before ray_tpu.init resolves the knob.
        os.environ["RAY_TPU_READY_QUEUE_SPILL_AFTER"] = str(args.spill_after)

    import ray_tpu

    # Logical CPUs sized for the actor count: the envelope measures control
    # plane + process supervision, not core count (reference runs declare
    # the hardware alongside the numbers the same way).
    ray_tpu.init(num_cpus=max(8, 4))
    out = {
        "nproc": os.cpu_count(),
        "note": (
            "single host; reference numbers for these shapes come from "
            "64-node clusters (release/benchmarks/README.md)"
        ),
    }
    if args.backlog:
        out["backlog"] = bench_backlog(args.backlog, args.spill_after)
        print(json.dumps({"backlog": out["backlog"]}), flush=True)
        ray_tpu.shutdown()
        line = json.dumps(out)
        print(line)
        if args.output:
            with open(args.output, "w") as f:
                f.write(line + "\n")
        return 0
    if args.churn:
        out["actor_churn"] = bench_actor_churn(
            args.churn_live, args.churn_waves, args.churn_wave_size
        )
        print(json.dumps({"actor_churn": out["actor_churn"]}), flush=True)
        ray_tpu.shutdown()
        line = json.dumps(out)
        print(line)
        if args.output:
            with open(args.output, "w") as f:
                f.write(line + "\n")
        return 0
    out["many_tasks"] = bench_many_tasks(args.tasks)
    print(json.dumps({"many_tasks": out["many_tasks"]}), flush=True)
    out["many_objects"] = bench_many_objects(args.objects)
    print(json.dumps({"many_objects": out["many_objects"]}), flush=True)
    out["many_pgs"] = bench_many_pgs(args.pgs)
    print(json.dumps({"many_pgs": out["many_pgs"]}), flush=True)
    out["many_actors"] = bench_many_actors(args.actors, args.actor_wave)
    out["many_actors"]["rss_gb_after"] = round(_rss_gb(), 2)
    print(json.dumps({"many_actors": out["many_actors"]}), flush=True)
    if not args.skip_broadcast:
        from ray_tpu._private.ray_perf import bench_broadcast_cross_node

        out["broadcast"] = bench_broadcast_cross_node(n_nodes=3, mb=100)
        print(json.dumps({"broadcast": out["broadcast"]}), flush=True)
    ray_tpu.shutdown()
    line = json.dumps(out)
    print(line)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
