#!/usr/bin/env python
"""Static-analysis lint CLI — ray_tpu's TSAN/clang-annotation stand-in.

    python scripts/ray_tpu_lint.py [ray_tpu/] [--fix-allowlist] [-v] [--json]

Runs the eleven analysis passes (blocking-under-lock, lock-order,
fault-registry, hot-send, gcs-mutation, journal-coverage, metric-names,
span-names, copy-coverage, wire-schema, knob-registry — see
ray_tpu/_private/analysis/) over the package and exits non-zero on any
violation not covered by the reviewed allowlist
(ray_tpu/_private/analysis/allowlist.txt).  Tier-1 tests run this same
entry point (tests/test_concurrency_lint.py), so a new blocking call
under a lock — or a frame send that drifts from wire.SCHEMAS — fails CI
before it costs a chaos soak to find.

--fix-allowlist regenerates the allowlist DELIBERATELY (the only
sanctioned way to grow it): current findings become the key set, existing
justifications are preserved, new keys are marked "TODO: justify" (which
the lint then reports until a human writes the reason).  It also rewrites
the generated catalogs (fault_points.txt, metric_names.txt,
span_names.txt, knob_names.txt); a committed catalog that doesn't match
regeneration fails the lint (the "forgot to regenerate" gap).

--json emits a machine-readable report (per-pass findings/new counts,
per-pass timing in seconds, every violation) instead of the text report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from ray_tpu._private.analysis import PASSES, run_analysis  # noqa: E402
from ray_tpu._private.analysis import allowlist as allowlist_mod  # noqa: E402
from ray_tpu._private.analysis import fault_registry  # noqa: E402
from ray_tpu._private.analysis import knob_registry  # noqa: E402
from ray_tpu._private.analysis import metric_names  # noqa: E402
from ray_tpu._private.analysis import span_names  # noqa: E402
from ray_tpu._private.analysis.common import iter_py_files  # noqa: E402

_ANALYSIS_DIR = os.path.join(_REPO_ROOT, "ray_tpu", "_private", "analysis")
DEFAULT_ALLOWLIST = os.path.join(_ANALYSIS_DIR, "allowlist.txt")
DEFAULT_CATALOG = os.path.join(_ANALYSIS_DIR, "fault_points.txt")
DEFAULT_METRIC_CATALOG = os.path.join(_ANALYSIS_DIR, "metric_names.txt")
DEFAULT_SPAN_CATALOG = os.path.join(_ANALYSIS_DIR, "span_names.txt")
DEFAULT_KNOB_CATALOG = os.path.join(_ANALYSIS_DIR, "knob_names.txt")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "roots", nargs="*", default=[os.path.join(_REPO_ROOT, "ray_tpu")],
        help="package dirs/files to analyze (default: ray_tpu/)",
    )
    ap.add_argument(
        "--spec-roots", nargs="*",
        default=[os.path.join(_REPO_ROOT, "tests"), os.path.join(_REPO_ROOT, "scripts")],
        help="where fault-spec literals and knob env names are validated "
        "(default: tests/ scripts/)",
    )
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    ap.add_argument("--catalog", default=DEFAULT_CATALOG)
    ap.add_argument("--metric-catalog", default=DEFAULT_METRIC_CATALOG)
    ap.add_argument("--span-catalog", default=DEFAULT_SPAN_CATALOG)
    ap.add_argument("--knob-catalog", default=DEFAULT_KNOB_CATALOG)
    ap.add_argument(
        "--no-catalog-check", action="store_true",
        help="skip the generated-catalog staleness checks (fixture trees)",
    )
    ap.add_argument(
        "--fix-allowlist", action="store_true",
        help="regenerate allowlist keys + the generated catalogs from "
        "current findings (preserves existing justifications)",
    )
    ap.add_argument(
        "--json", action="store_true", dest="json_out",
        help="machine-readable report: per-pass counts + timings, every "
        "violation, overall ok",
    )
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print allowlisted findings")
    args = ap.parse_args(argv)

    result = run_analysis(
        args.roots,
        spec_roots=args.spec_roots,
        allowlist_path=args.allowlist,
        catalog_path=None if args.no_catalog_check else args.catalog,
        metric_catalog_path=None if args.no_catalog_check else args.metric_catalog,
        span_catalog_path=None if args.no_catalog_check else args.span_catalog,
        knob_catalog_path=None if args.no_catalog_check else args.knob_catalog,
    )

    if args.fix_allowlist:
        files = [f for root in args.roots for f in iter_py_files(root)]
        points = fault_registry.collect_points(files)
        fault_registry.write_catalog(points, args.catalog)
        metrics = metric_names.collect_metrics(files)
        metric_names.write_catalog(metrics, args.metric_catalog)
        spans = span_names.collect_spans(files)
        span_names.write_catalog(spans, args.span_catalog)
        n_knobs = knob_registry.write_catalog(args.knob_catalog)
        # Catalog staleness violations are cured by the rewrites above, so
        # they never become allowlist entries.
        keys = sorted(
            {
                v.key
                for v in result.violations
                if not v.key.startswith("fault-registry:catalog:")
                and not v.key.startswith("metric-names:catalog:")
                and not v.key.startswith("span-names:catalog:")
                and not v.key.startswith("knob-registry:catalog:")
            }
        )
        existing = result.allowlist
        merged, added, dropped = allowlist_mod.regenerate(existing, keys)
        allowlist_mod.save(args.allowlist, merged)
        print(f"allowlist: {len(merged)} entries "
              f"(+{len(added)} new, -{len(dropped)} stale) -> {args.allowlist}")
        for k in added:
            print(f"  NEW (justify me): {k}")
        print(f"catalog: {len(points)} fault points -> {args.catalog}")
        print(
            f"catalog: {len(metrics)} metric names -> {args.metric_catalog}"
        )
        print(f"catalog: {len(spans)} span names -> {args.span_catalog}")
        print(f"catalog: {n_knobs} knob/wiring names -> {args.knob_catalog}")
        return 0

    todo = allowlist_mod.unjustified(result.allowlist)
    by_pass = {}
    for v in result.violations:
        by_pass.setdefault(v.pass_name, []).append(v)

    if args.json_out:
        report = {
            "ok": bool(not result.new and not todo),
            "passes": {
                p: {
                    "findings": len(by_pass.get(p, [])),
                    "allowlisted": sum(
                        1 for v in by_pass.get(p, [])
                        if v.key in result.allowlist
                    ),
                    "new": sum(
                        1 for v in by_pass.get(p, [])
                        if v.key not in result.allowlist
                    ),
                    "seconds": round(result.timings.get(p, 0.0), 4),
                }
                for p in PASSES
            },
            "violations": [
                {
                    "pass": v.pass_name,
                    "file": v.rel,
                    "line": v.line,
                    "key": v.key,
                    "message": v.message,
                    "allowlisted": v.key in result.allowlist,
                }
                for v in result.violations
            ],
            "unjustified_allowlist": todo,
            "stale_allowlist": result.stale_allowlist,
        }
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0 if report["ok"] else 1

    for pass_name in PASSES:
        vs = by_pass.get(pass_name, [])
        new = [v for v in vs if v.key not in result.allowlist]
        print(
            f"[{pass_name}] {len(vs)} finding(s), "
            f"{len(vs) - len(new)} allowlisted, {len(new)} new "
            f"({result.timings.get(pass_name, 0.0):.3f}s)"
        )
        for v in new:
            print(f"  NEW: {v.message}")
        if args.verbose:
            for v in vs:
                if v.key in result.allowlist:
                    print(f"  allowlisted: {v.message}")
                    print(f"    reason: {result.allowlist[v.key]}")

    for k in todo:
        print(f"  UNJUSTIFIED allowlist entry (write a reason): {k}")
    for k in result.stale_allowlist:
        print(f"  note: stale allowlist entry (no longer fires): {k}")

    if result.new or todo:
        print(
            f"\nFAIL: {len(result.new)} new violation(s), "
            f"{len(todo)} unjustified allowlist entr(ies).  Fix the code, or "
            "review + run --fix-allowlist and write a justification."
        )
        return 1
    print("\nOK: no new static-analysis violations.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
