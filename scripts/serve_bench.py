"""Serve data-plane micro-benchmark: QPS + p50/p99 latency.

ray: release/serve_tests/workloads/serve_micro_benchmark.py — handle-path
and HTTP-path throughput/latency on a trivial deployment (measures the
runtime, not the model).  Writes one JSON line; CI/driver can redirect to
BENCH_serve_r3.json.  Numbers are host-bound: record nproc with them.

Run: python scripts/serve_bench.py [--requests 300] [--concurrency 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(xs, p):
    xs = sorted(xs)
    return xs[min(int(len(xs) * p), len(xs) - 1)]


def bench_handle(handle, n: int, concurrency: int):
    import ray_tpu

    lat = []
    lock = threading.Lock()

    def worker(count):
        for _ in range(count):
            t0 = time.monotonic()
            ray_tpu.get(handle.remote(1), timeout=60)
            dt = time.monotonic() - t0
            with lock:
                lat.append(dt)

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(n // concurrency,))
        for _ in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    return len(lat) / wall, lat


def bench_http(addr: str, n: int, concurrency: int):
    # Persistent connection per client thread (the proxy speaks HTTP/1.1
    # keep-alive): a fresh TCP connection per request measures the
    # kernel's connect path, not the serve data plane — the reference's
    # serve benchmarks reuse sessions the same way.
    import http.client
    from urllib.parse import urlparse

    parsed = urlparse(addr)
    lat = []
    lock = threading.Lock()

    def worker(count):
        conn = http.client.HTTPConnection(
            parsed.hostname, parsed.port, timeout=60
        )
        for _ in range(count):
            t0 = time.monotonic()
            conn.request("GET", "/echo?x=1")
            conn.getresponse().read()
            dt = time.monotonic() - t0
            with lock:
                lat.append(dt)
        conn.close()

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(n // concurrency,))
        for _ in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    return len(lat) / wall, lat


def bench_http_under_idle_load(addr: str, n: int, concurrency: int,
                               idle_conns: int):
    """p99 of active requests while `idle_conns` extra keep-alive
    connections sit open — the asyncio proxy must hold them at flat
    latency (a thread-per-connection server degrades as idle_conns grows;
    ray: uvicorn's event loop has the same property)."""
    import http.client
    import socket
    from urllib.parse import urlparse

    parsed = urlparse(addr)
    idle = []
    try:
        for _ in range(idle_conns):
            s = socket.create_connection(
                (parsed.hostname, parsed.port), timeout=30
            )
            # One real request primes the connection as keep-alive.
            s.sendall(b"GET /echo?x=1 HTTP/1.1\r\nHost: x\r\n\r\n")
            idle.append(s)
        for s in idle:
            s.recv(65536)  # drain the priming response; conn stays open
        qps, lat = bench_http(addr, n, concurrency)
    finally:
        for s in idle:
            try:
                s.close()
            except OSError:
                pass
    return qps, lat


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=300)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--idle-conns", type=int, default=0,
                    help="sweep: hold N idle keep-alive conns during the "
                         "HTTP bench and report latency under that load")
    ap.add_argument("--output", default=None)
    args = ap.parse_args(argv)

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    serve.start(http_options={"host": "127.0.0.1", "port": 0})

    @serve.deployment(name="echo", num_replicas=2, max_concurrent_queries=32)
    def echo(body=None):
        return {"ok": True}

    handle = serve.run(echo.bind())
    ray_tpu.get(handle.remote(0), timeout=60)  # warm both paths
    addr = serve.get_http_address()

    hqps, hlat = bench_handle(handle, args.requests, args.concurrency)
    wqps, wlat = bench_http(addr, args.requests, args.concurrency)

    out = {
        "nproc": os.cpu_count(),
        "requests": args.requests,
        "concurrency": args.concurrency,
        "handle_qps": round(hqps, 1),
        "handle_p50_ms": round(_percentile(hlat, 0.50) * 1e3, 2),
        "handle_p99_ms": round(_percentile(hlat, 0.99) * 1e3, 2),
        "http_qps": round(wqps, 1),
        "http_p50_ms": round(_percentile(wlat, 0.50) * 1e3, 2),
        "http_p99_ms": round(_percentile(wlat, 0.99) * 1e3, 2),
    }
    if args.idle_conns:
        iqps, ilat = bench_http_under_idle_load(
            addr, args.requests, args.concurrency, args.idle_conns
        )
        out.update(
            {
                "idle_conns": args.idle_conns,
                "http_qps_under_idle": round(iqps, 1),
                "http_p99_ms_under_idle": round(
                    _percentile(ilat, 0.99) * 1e3, 2
                ),
            }
        )
    line = json.dumps(out)
    print(line)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")
    serve.shutdown()
    ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
