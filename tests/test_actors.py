"""Actor tests (modeled on ray: python/ray/tests/test_actor.py,
test_actor_failures.py)."""

import os
import time

import pytest

import ray_tpu


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, k=1):
        self.n += k
        return self.n

    def value(self):
        return self.n

    def crash(self):
        os._exit(1)

    def bye(self):
        ray_tpu.exit_actor()


def test_actor_basic(ray_start_regular):
    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote()) == 11
    assert ray_tpu.get(c.incr.remote(5)) == 16
    assert ray_tpu.get(c.value.remote()) == 16


def test_actor_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_actor_method_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise KeyError("nope")

    b = Bad.remote()
    with pytest.raises(ray_tpu.exceptions.TaskError):
        ray_tpu.get(b.fail.remote())


def test_actor_creation_error(ray_start_regular):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("cannot build")

        def f(self):
            return 1

    b = Broken.remote()
    with pytest.raises(
        (ray_tpu.exceptions.TaskError, ray_tpu.exceptions.ActorDiedError)
    ):
        ray_tpu.get(b.f.remote(), timeout=20)


def test_named_actor(ray_start_regular):
    c = Counter.options(name="global_counter").remote()
    ray_tpu.get(c.incr.remote())
    c2 = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(c2.value.remote()) == 1


def test_get_if_exists(ray_start_regular):
    a = Counter.options(name="singleton", get_if_exists=True).remote()
    ray_tpu.get(a.incr.remote())
    b = Counter.options(name="singleton", get_if_exists=True).remote()
    assert ray_tpu.get(b.value.remote()) == 1


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    ray_tpu.get(c.incr.remote())
    ray_tpu.kill(c)
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(c.incr.remote(), timeout=20)


def test_actor_crash_no_restart(ray_start_regular):
    c = Counter.remote()
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(c.crash.remote(), timeout=20)
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(c.value.remote(), timeout=20)


def test_actor_restart(ray_start_regular):
    c = Counter.options(max_restarts=2).remote(100)
    assert ray_tpu.get(c.incr.remote()) == 101
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(c.crash.remote(), timeout=20)
    # restarted: state re-initialized from creation args (ray FSM semantics,
    # gcs_actor_manager.h:258)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            assert ray_tpu.get(c.value.remote(), timeout=20) == 100
            break
        except ray_tpu.exceptions.ActorDiedError:
            time.sleep(0.1)
    else:
        pytest.fail("actor did not restart")


def test_exit_actor(ray_start_regular):
    c = Counter.remote()
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(c.bye.remote(), timeout=20)
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(c.value.remote(), timeout=20)


def test_actor_handle_to_task(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(counter):
        return ray_tpu.get(counter.incr.remote())

    assert ray_tpu.get(bump.remote(c), timeout=20) == 1
    assert ray_tpu.get(c.value.remote()) == 1


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class AsyncWorker:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.05)
            return x * 2

    a = AsyncWorker.remote()
    refs = [a.work.remote(i) for i in range(8)]
    t0 = time.monotonic()
    assert sorted(ray_tpu.get(refs, timeout=20)) == [0, 2, 4, 6, 8, 10, 12, 14]
    # 8 calls x 50ms must overlap on the actor's event loop
    assert time.monotonic() - t0 < 2.0


def test_threaded_actor(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Sleeper:
        def nap(self):
            time.sleep(0.3)
            return 1

    s = Sleeper.remote()
    ray_tpu.get(s.nap.remote(), timeout=30)  # warm up: actor worker boot
    t0 = time.monotonic()
    assert sum(ray_tpu.get([s.nap.remote() for _ in range(4)], timeout=20)) == 4
    assert time.monotonic() - t0 < 1.1  # 4 overlapped naps ≪ 1.2s serial


def test_actor_pending_calls_queued_before_alive(ray_start_regular):
    @ray_tpu.remote
    class Slow:
        def __init__(self):
            time.sleep(0.5)
            self.ok = True

        def check(self):
            return self.ok

    s = Slow.remote()
    # submitted while still PENDING_CREATION
    assert ray_tpu.get(s.check.remote(), timeout=20) is True
