"""Pipeline parallelism: forward + gradient parity with sequential
execution, composition with the data axis, HLO collective check.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel import MeshSpec, build_mesh
from ray_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _sequential(stacked, x):
    for i in range(stacked["w"].shape[0]):
        x = _stage_fn({"w": stacked["w"][i], "b": stacked["b"][i]}, x)
    return x


@pytest.fixture(scope="module")
def setup():
    P_, D, B = 4, 8, 16
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    stacked = {
        "w": jax.random.normal(k1, (P_, D, D)) * 0.5,
        "b": jax.random.normal(k2, (P_, D)) * 0.1,
    }
    x = jax.random.normal(k3, (B, D))
    mesh = build_mesh(MeshSpec(data=2, pipeline=4))
    return stacked, x, mesh


def test_pipeline_forward_matches_sequential(setup):
    stacked, x, mesh = setup
    ref = _sequential(stacked, x)
    out = jax.jit(
        lambda p, h: pipeline_apply(_stage_fn, p, h, mesh, n_microbatches=4)
    )(stacked, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential(setup):
    """Reverse-mode AD through the schedule = the backward pipeline."""
    stacked, x, mesh = setup

    def loss_pipe(p, h):
        return jnp.sum(pipeline_apply(_stage_fn, p, h, mesh, n_microbatches=4) ** 2)

    def loss_seq(p, h):
        return jnp.sum(_sequential(p, h) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked, x)
    g_seq = jax.grad(loss_seq)(stacked, x)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        ),
        g_pipe,
        g_seq,
    )


def test_pipeline_microbatch_counts(setup):
    stacked, x, mesh = setup
    ref = _sequential(stacked, x)
    # per-data-shard batch is 16/2 = 8; non-divisors (3 -> 2, 32 -> 8)
    # auto-adapt to the largest feasible count — results identical always
    for m in (1, 2, 3, 4, 8, 32):
        out = pipeline_apply(_stage_fn, stacked, x, mesh, n_microbatches=m)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=1e-5, rtol=1e-5
        )


def test_pipeline_compiles_to_collective_permute(setup):
    stacked, x, mesh = setup
    hlo = (
        jax.jit(lambda p, h: pipeline_apply(_stage_fn, p, h, mesh, n_microbatches=4))
        .lower(stacked, x)
        .compile()
        .as_text()
    )
    assert "collective-permute" in hlo, "stage hops should ride ppermute"


# -- round 4: 1F1B schedule ---------------------------------------------------


def test_1f1b_matches_gpipe_grads():
    """Fused 1F1B train step must produce the same loss and gradients as
    autodiff through the GPipe schedule (the 'loss parity' gate)."""
    import numpy as np

    from ray_tpu.parallel.pipeline import pipeline_train_step_1f1b

    mesh = build_mesh(MeshSpec(data=2, pipeline=4))
    key = jax.random.PRNGKey(0)
    P_, D, B = 4, 8, 16
    stacked = {
        "w": jax.random.normal(key, (P_, D, D)) * 0.3,
        "b": jnp.zeros((P_, D)),
    }
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (B, D))

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    def gpipe_loss(p):
        y = pipeline_apply(_stage_fn, p, x, mesh, n_microbatches=8)
        return loss_fn(y, tgt)

    ref_loss, ref_grads = jax.jit(jax.value_and_grad(gpipe_loss))(stacked)

    loss, grads = jax.jit(
        lambda p: pipeline_train_step_1f1b(
            _stage_fn, loss_fn, p, x, tgt, mesh, n_microbatches=8
        )
    )(stacked)

    assert np.allclose(float(loss), float(ref_loss), rtol=1e-5), (loss, ref_loss)
    for k in stacked:
        assert np.allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]), rtol=1e-4, atol=1e-5
        ), k


def test_1f1b_lower_peak_memory_than_gpipe():
    """The schedule's point: compiled peak memory must be LOWER than
    autodiff-through-GPipe at a microbatch count where GPipe's stored
    activations dominate (the memory_analysis gate)."""
    from ray_tpu.parallel.pipeline import pipeline_train_step_1f1b

    mesh = build_mesh(MeshSpec(data=2, pipeline=4))
    P_, D, B, M = 4, 256, 64, 32
    stacked = {
        "w": jnp.zeros((P_, D, D)),
        "b": jnp.zeros((P_, D)),
    }
    x = jnp.zeros((B, D))
    tgt = jnp.zeros((B, D))

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    def gpipe_loss(p):
        y = pipeline_apply(_stage_fn, p, x, mesh, n_microbatches=M)
        return loss_fn(y, tgt)

    gpipe = jax.jit(jax.value_and_grad(gpipe_loss)).lower(stacked).compile()
    f1b = (
        jax.jit(
            lambda p: pipeline_train_step_1f1b(
                _stage_fn, loss_fn, p, x, tgt, mesh, n_microbatches=M
            )
        )
        .lower(stacked)
        .compile()
    )

    def peak(compiled):
        ma = compiled.memory_analysis()
        if isinstance(ma, list):
            return sum(m.temp_size_in_bytes for m in ma)
        return ma.temp_size_in_bytes

    g_peak, f_peak = peak(gpipe), peak(f1b)
    assert f_peak < g_peak, (f_peak, g_peak)
