"""Regression tests for the round-5 ADVICE findings (recovery-path
correctness), one per fix:

  * relayed-actor restart re-drives in-flight calls in submission order;
  * a mid-flush re-drive failure charges retry budget only for calls that
    actually hit the socket;
  * a pid-less zygote handle reads dead after the fork grace even while
    the zygote lives (lost ("forked", ...) reply);
  * once + wildcard pubsub subscriptions are consumed on both the head
    and the worker side;
  * the spill freed-race delete is queued to the reclaim thread, never
    run under the store lock.
"""

import os
import queue
import threading
import time
import types

import pytest

import ray_tpu


def _rt():
    from ray_tpu._private.runtime import get_runtime

    return get_runtime()


# ------------------------------------------------- ordered relayed re-drive


def test_relayed_actor_requeue_preserves_submission_order(
    ray_start_regular, tmp_path
):
    """Kill an actor worker with many relayed calls in flight: the
    retry-budgeted requeue must replay them in per-caller submission
    order on the restarted instance (previously a Set[str] iterated in
    hash order)."""
    path = str(tmp_path / "order.log")

    @ray_tpu.remote(max_restarts=2, max_task_retries=5)
    class Recorder:
        def record(self, i, path, sleep=0.0):
            with open(path, "a") as f:
                f.write(f"{i}\n")
            if sleep:
                time.sleep(sleep)
            return i

    a = Recorder.remote()
    # First call blocks the single-threaded executor; the rest pile up
    # in flight behind it (pushed, unacked).
    refs = [a.record.remote(0, path, sleep=2.0)]
    refs += [a.record.remote(i, path) for i in range(1, 8)]

    # Wait for the first call to be mid-execution, then SIGKILL the
    # actor's worker while all 8 calls are in flight.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not os.path.exists(path):
        time.sleep(0.02)
    assert os.path.exists(path), "actor never started executing"
    rt = _rt()
    with rt.lock:
        target = None
        for h in rt.workers.values():
            if h.state == "actor" and h.proc is not None:
                target = h
                break
    assert target is not None
    target.proc.kill()

    assert ray_tpu.get(refs, timeout=180) == list(range(8))
    with open(path) as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    # The re-driven replay (last 8 entries) runs in submission order.
    assert lines[-8:] == [str(i) for i in range(8)], lines


# ------------------------------------------- uncharged unsent re-drive tail


class _FlakyConn:
    """Peer conn whose send fails after `ok_sends` successes."""

    def __init__(self, ok_sends):
        self.ok_sends = ok_sends
        self.sent = []
        self.dead = False

    def send(self, msg):
        if len(self.sent) >= self.ok_sends:
            return False
        self.sent.append(msg)
        return True


class _FakeSpec:
    def __init__(self, task_id):
        self.task_id = task_id
        self.attempt = 0
        self.max_retries = 5
        self.retry_exceptions = False
        self.contained_refs = []

    def return_ids(self):
        return []


def test_recover_actor_flush_charges_only_sent_prefix():
    """_recover_actor's re-drive flush dies mid-send: only the specs that
    hit the socket are charged an attempt; the unsent tail re-buffers
    uncharged, behind the re-driven prefix, in order."""
    from ray_tpu._private.peer import ActorRoute, DirectTransport

    resolved = threading.Event()
    release_second = threading.Event()
    calls = {"n": 0}

    class FakeWR:
        authkey = b"k"
        task_event_sink = None

        def request(self, op, payload, timeout=None):
            calls["n"] += 1
            if calls["n"] == 1:
                return ("direct", None, ("127.0.0.1", 9), True)
            # The post-failure background recovery: park until the test
            # has asserted the buffer, then declare the actor dead.
            resolved.set()
            release_second.wait(timeout=30)
            return ("dead", None, None, False)

        def oneway(self, msg, droppable=False):
            pass

        def borrow_ref(self, c):
            pass

        def unborrow_ref(self, c):
            pass

    t = DirectTransport(FakeWR())
    conn = _FlakyConn(ok_sends=2)
    t._conn_to = lambda ep: conn

    specs = [_FakeSpec(f"t{i}") for i in range(4)]
    r = ActorRoute(conn, restartable=True)
    r.state = "recovering"
    r.conn = None
    r.recover_started = True
    r.buffered = list(specs)
    t.routes["a1"] = r
    for s in specs:
        t.inflight[s.task_id] = ("a1", s, None, None)

    t._recover_actor("a1")  # flush: t0, t1 sent; t2 fails mid-send

    assert resolved.wait(timeout=30), "death path never re-entered recovery"
    # Sent prefix charged exactly once; never-sent tail uncharged.
    assert [s.attempt for s in specs] == [1, 1, 0, 0]
    # Buffer rebuilt in submission order: re-driven prefix first.
    with t.lock:
        assert [s.task_id for s in r.buffered] == ["t0", "t1", "t2", "t3"]
    release_second.set()


# ------------------------------------------------- pid-less zygote handles


def test_pidless_zygote_handle_dies_after_grace_with_live_zygote():
    """A handle whose ("forked", ...) reply was lost reads dead after the
    grace window EVEN while the zygote process is alive, so the reaper
    reschedules its lease (previously: alive forever)."""
    from ray_tpu._private import config as _config
    from ray_tpu._private.runtime import _ZygoteProcHandle

    class LiveZygote:
        def poll(self):
            return None  # still running

    h = _ZygoteProcHandle(LiveZygote())
    assert h.is_alive()  # fresh request: within grace
    h._created -= _config.get("zygote_fork_grace_s") + 1.0
    assert not h.is_alive()  # grace lapsed: fork reply is lost
    # A (late) pid attribution flips liveness back to the real process.
    h.set_pid(os.getpid())
    assert h.is_alive()


# ------------------------------------------- once+wildcard pubsub consumption


def test_head_once_wildcard_subscription_consumed():
    """Head side: a once=True wildcard subscription fires exactly once
    (previously the consume pass only popped exact-key entries)."""
    from ray_tpu._private.runtime import Runtime

    fake = types.SimpleNamespace(
        lock=threading.RLock(),
        remote_subs={("ch", "*"): {"w_once": True, "w_persist": False}},
        _pub_queue=queue.Queue(),
    )
    publish = Runtime._remote_publish.__get__(fake)

    publish("ch", "k1", ("a",))
    publish("ch", "k2", ("b",))

    per_wid = {}
    while not fake._pub_queue.empty():
        wid, _msg = fake._pub_queue.get_nowait()
        per_wid[wid] = per_wid.get(wid, 0) + 1
    assert per_wid == {"w_once": 1, "w_persist": 2}
    assert fake.remote_subs == {("ch", "*"): {"w_persist": False}}


def test_head_exact_once_still_consumed_and_resub_survives():
    """The pre-existing exact-key semantics hold: once consumed, a
    persistent re-subscription that landed before the consume pass is
    kept."""
    from ray_tpu._private.runtime import Runtime

    fake = types.SimpleNamespace(
        lock=threading.RLock(),
        remote_subs={("ch", "k"): {"w1": True}},
        _pub_queue=queue.Queue(),
    )
    publish = Runtime._remote_publish.__get__(fake)
    publish("ch", "k", ())
    assert ("ch", "k") not in fake.remote_subs
    # once entry upgraded to persistent mid-send must survive: simulate by
    # re-registering between publishes.
    fake.remote_subs[("ch", "k")] = {"w1": False}
    publish("ch", "k", ())
    assert fake.remote_subs == {("ch", "k"): {"w1": False}}


def test_worker_once_wildcard_subscription_consumed():
    """Worker side: _on_pub prunes a once=True wildcard sub after its
    first delivery (previously it fired on every later key forever)."""
    from ray_tpu._private.worker_proc import WorkerRuntime

    wr = WorkerRuntime.__new__(WorkerRuntime)  # skip store setup
    wr._subs_lock = threading.Lock()
    fired = []
    wr._subs = {
        ("ch", "*"): [
            (lambda key, *a: fired.append(("once", key)), True),
            (lambda key, *a: fired.append(("persist", key)), False),
        ]
    }
    wr._on_pub("ch", "k1", ())
    wr._on_pub("ch", "k2", ())
    assert fired == [("once", "k1"), ("persist", "k1"), ("persist", "k2")]
    remaining = wr._subs[("ch", "*")]
    assert len(remaining) == 1 and remaining[0][1] is False


# ------------------------------------------- reconnect budget per incident


def test_request_budget_refreshes_after_each_healed_reconnect(monkeypatch):
    """Soak-found (chaos_soak seed 7): a long-lived request that rides
    SEVERAL head bounces — each healed by a successful reconnect — must
    get a fresh give-up budget per incident.  The old time-gap heuristic
    treated bounces spaced under window+10s as one continuous outage and
    gave up mid-heal."""
    import time as _t

    from ray_tpu._private.worker_proc import WorkerRuntime

    clock = {"t": 0.0}
    monkeypatch.setattr(_t, "monotonic", lambda: clock["t"])
    monkeypatch.setattr(
        _t, "sleep", lambda s: clock.__setitem__("t", clock["t"] + s)
    )

    wr = WorkerRuntime.__new__(WorkerRuntime)
    wr.reconnect_window_override = 45.0
    wr._conn_generation = 0

    # Script: the request's conn dies at t=0, 30, 61 (bounces spaced well
    # under window+10=55s apart); each bounce heals (generation bumps)
    # before the next; the reply finally lands on the 4th try.
    script = iter([(0.0, 0), (30.0, 1), (61.0, 2)])

    def once(op, payload, timeout):
        for t, gen in script:
            clock["t"] = t
            wr._conn_generation = gen
            raise ConnectionError("head connection was reset (head restart)")
        return "ok"

    wr._request_once = once
    # Old logic: gives up at the THIRD bounce (61 > 0+55).  New logic:
    # every healed reconnect refreshes the budget, so the request rides
    # all three bounces and resolves.
    assert wr.request("get_object", "oid") == "ok"


def test_request_gives_up_when_outage_never_heals(monkeypatch):
    """The give-up still fires for one CONTINUOUS outage: no successful
    reconnect (generation frozen), failures past window+10s."""
    import time as _t

    import pytest as _pytest

    from ray_tpu._private.worker_proc import WorkerRuntime

    clock = {"t": 0.0}
    monkeypatch.setattr(_t, "monotonic", lambda: clock["t"])
    monkeypatch.setattr(
        _t, "sleep", lambda s: clock.__setitem__("t", clock["t"] + s)
    )

    wr = WorkerRuntime.__new__(WorkerRuntime)
    wr.reconnect_window_override = 45.0
    wr._conn_generation = 0

    def once(op, payload, timeout):
        clock["t"] += 30.0  # failures at 30, 60, 90... same generation
        raise ConnectionError("head connection lost mid-send")

    wr._request_once = once
    with _pytest.raises(ConnectionError, match="reconnect window"):
        wr.request("get_object", "oid")


# ------------------------------------------------- spill freed-race delete


def test_spill_freed_race_delete_queued_not_synchronous(tmp_path):
    """OwnerStore.spill()'s freed-race path must queue the stored-image
    delete for the reclaim thread instead of running it (a potentially
    blocking network call on URI backends) under the store lock."""
    import numpy as np

    from ray_tpu._private.store import OwnerStore

    store = OwnerStore(
        f"frtest-{os.getpid()}", spill_dir=str(tmp_path / "spill")
    )
    try:
        oid = "obj-freed-race"
        store.put(oid, np.zeros(300_000, dtype=np.uint8))  # shm-sealed
        assert oid in store._in_shm

        deletes = []
        real = store._spill_storage

        class Recording:
            def put(self, o, data):
                return real.put(o, data)

            def get(self, p):
                return real.get(p)

            def delete(self, p):
                deletes.append(threading.current_thread().name)
                real.delete(p)

            def destroy(self):
                real.destroy()

        store._spill_storage = Recording()
        # Simulate the race: the object is freed after spill() read the
        # segment but before it re-took the lock.
        with store._lock:
            store._in_shm.pop(oid)
        assert store.spill(oid) is None
        # The delete must not have run on this (caller) thread...
        me = threading.current_thread().name
        assert all(t != me for t in deletes)
        # ...but the reclaim thread performs it promptly.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not deletes:
            time.sleep(0.02)
        assert deletes and all(t != me for t in deletes)
    finally:
        store.destroy()


# ------------------------------------------- PR 2: blocking-under-lock fixes


def test_make_room_spill_io_runs_off_the_store_lock(tmp_path):
    """_make_room used to run the whole LRU spill — including the pluggable
    backend's put(), a network call on URI backends — inside self._lock,
    stalling every store operation behind admission control.  The
    concurrency lint flags that shape now; this regression pins the fix:
    while a strict put is spilling on a SLOW backend, concurrent readers
    of other objects must get through the store lock immediately."""
    import numpy as np

    from ray_tpu._private.store import OwnerStore

    store = OwnerStore(
        f"mrtest-{os.getpid()}",
        spill_dir=str(tmp_path / "spill"),
        capacity_bytes=500_000,
    )
    try:
        real = store._spill_storage

        class SlowStorage:
            def put(self, o, data):
                time.sleep(0.8)  # a slow network backend
                return real.put(o, data)

            def get(self, p):
                return real.get(p)

            def delete(self, p):
                real.delete(p)

            def destroy(self):
                real.destroy()

        store._spill_storage = SlowStorage()
        store.put("victim", np.zeros(300_000, dtype=np.uint8))  # shm-sealed
        store.put("tiny", 42)  # in-process memory store
        t0 = time.monotonic()

        worst = {"dt": 0.0}
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                r0 = time.monotonic()
                assert store.get_sealed("tiny") is not None
                worst["dt"] = max(worst["dt"], time.monotonic() - r0)
                time.sleep(0.005)

        t = threading.Thread(target=reader)
        t.start()
        try:
            # Triggers the LRU spill of "victim" through the slow backend.
            store.put("incoming", np.zeros(300_000, dtype=np.uint8))
        finally:
            stop.set()
            t.join(timeout=5)
        assert time.monotonic() - t0 >= 0.8  # the slow spill really ran
        assert "victim" in store._spilled and "incoming" in store._in_shm
        assert worst["dt"] < 0.4, (
            f"a reader stalled {worst['dt']:.3f}s behind the store lock "
            "while _make_room was spilling — spill I/O is back under the lock"
        )
        # Transparent restore still works after the off-lock spill.
        obj = store.get_sealed("victim")
        assert obj is not None and obj.deserialize().shape == (300_000,)
    finally:
        store.destroy()


def test_handshake_pending_send_flush_off_lock_preserves_order(
    ray_start_regular,
):
    """_dispatch_handshake used to flush pending_sends while holding the
    global runtime lock (pipe I/O under the control-plane lock).  The fix
    drains the backlog off-lock BEFORE publishing the conn; this pins the
    ordering contract: tasks queued to still-starting workers (the
    pending_sends path) all execute, results land correctly, and at least
    one flush actually exercised the drain loop."""
    rt = _rt()

    @ray_tpu.remote
    def bump(x):
        return x + 1

    # Burst past the connected pool immediately: spawned-but-unconnected
    # workers are leasable, so some of these queue into pending_sends and
    # ride the off-lock flush when the worker says "ready".
    for round_no in range(3):
        refs = [bump.remote(i) for i in range(12)]
        assert ray_tpu.get(refs, timeout=120) == [i + 1 for i in range(12)]
        if getattr(rt, "_pending_send_flushes", 0) > 0:
            break
    assert getattr(rt, "_pending_send_flushes", 0) > 0, (
        "no handshake ever drained a pending_sends backlog — the test "
        "never exercised the flush path"
    )


# ------------------------------------------------- GC-safe ref releases


def test_objectref_release_runs_on_drainer_thread_not_in_gc():
    """ObjectRef.__del__ must NEVER call the release hook synchronously:
    GC runs at arbitrary allocation points, possibly while the current
    thread holds the very locks the hook takes (DirectTransport.lock, a
    conn lock) — a self-deadlock on a plain lock, an ABBA inversion
    otherwise (the chaos soak's lock watchdog caught this under
    batch-flush allocation pressure).  Releases are queued and drained by
    a dedicated thread."""
    import time as _time

    from ray_tpu._private import refs as refs_mod

    released = []
    saved = (refs_mod._addref_hook, refs_mod._release_hook)
    refs_mod.set_ref_hooks(
        lambda oid: None,
        lambda oid: released.append(
            (oid, threading.current_thread().name)
        ),
    )
    try:
        r = refs_mod.ObjectRef("o-gc-test", _count=False)
        del r
        deadline = _time.monotonic() + 5.0
        while not released and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert released, "release hook never ran after GC"
        oid, thread_name = released[0]
        assert oid == "o-gc-test"
        assert thread_name == "raytpu-ref-release", (
            f"release ran on {thread_name!r} — synchronous __del__ hooks "
            "are the GC-context deadlock the drainer exists to prevent"
        )
    finally:
        refs_mod.set_ref_hooks(*saved)
