"""TorchTrainer: 2-worker gloo DDP (reference intents:
python/ray/train/tests/test_torch_trainer.py, test_torch_fsdp.py's
wrap-and-sync assertions on the CPU/gloo path).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air import ScalingConfig
from ray_tpu.train.torch import TorchConfig, TorchTrainer


@pytest.fixture
def rt():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_torch_ddp_two_workers_sync_params(rt):
    """DDP over gloo: after training, every rank holds IDENTICAL params and
    the loss went down."""

    def loop(config):
        import torch
        import torch.distributed as dist
        import torch.nn as nn

        from ray_tpu.train import session
        from ray_tpu.train.torch import prepare_model

        assert dist.is_initialized()
        assert dist.get_world_size() == 2
        rank = dist.get_rank()
        assert rank == session.get_world_rank()

        torch.manual_seed(1234 + rank)  # different init per rank pre-DDP
        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.05)

        g = torch.Generator().manual_seed(rank)  # different data per rank
        x = torch.randn(64, 4, generator=g)
        w_true = torch.tensor([[1.0, -2.0, 3.0, 0.5]]).T
        y = x @ w_true + 0.1

        first = None
        for step in range(30):
            opt.zero_grad()
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()  # DDP allreduces grads here
            opt.step()
            if first is None:
                first = float(loss)
        flat = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
        session.report(
            {
                "rank": rank,
                "first_loss": first,
                "last_loss": float(loss),
                "params": flat.numpy().tolist(),
            }
        )

    trainer = TorchTrainer(
        loop,
        torch_config=TorchConfig(backend="gloo"),
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    assert result.error is None
    m = result.metrics
    assert m["last_loss"] < m["first_loss"]

    # Verify identical post-DDP params across BOTH ranks via a second group.
    from ray_tpu.train.backend_executor import BackendExecutor

    ex = BackendExecutor(TorchConfig(backend="gloo"), ScalingConfig(num_workers=2))
    ex.start()
    try:
        def get_synced_weights():
            import torch
            import torch.distributed as dist
            import torch.nn as nn

            from ray_tpu.train.torch import prepare_model

            torch.manual_seed(100 + dist.get_rank())
            model = prepare_model(nn.Linear(3, 1))
            # one DDP step syncs gradients; params start broadcast from rank0
            return [p.detach().numpy().tolist() for p in model.parameters()]

        outs = ex.worker_group.execute(get_synced_weights, timeout=120)
        # DDP broadcasts rank-0 params at wrap time: ranks must match.
        for a, b in zip(outs[0], outs[1]):
            np.testing.assert_allclose(a, b)
    finally:
        ex.shutdown()
