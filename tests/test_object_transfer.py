"""Cross-node object transfer: isolated per-node stores + chunked pulls.

Reference intents: src/ray/object_manager tests (pull/push between object
managers), python test_object_spilling / test_plasma cross-node paths.
Each daemon node here gets a DISTINCT store root under /tmp, so no object
can possibly resolve through a shared filesystem path — every cross-node
read must ride the transfer plane (object_plane.py).
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import NodeAffinitySchedulingStrategy


@pytest.fixture
def two_isolated_nodes(ray_start_cluster, tmp_path):
    cluster = ray_start_cluster
    roots = [tmp_path / "nodeA", tmp_path / "nodeB"]
    for r in roots:
        r.mkdir()
    n1 = cluster.add_node(num_cpus=2, daemon=True, store_root=str(roots[0]))
    n2 = cluster.add_node(num_cpus=2, daemon=True, store_root=str(roots[1]))
    return cluster, n1, n2, roots


def _store_files(root) -> set:
    out = set()
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            out.add(os.path.join(dirpath, f))
    return out


@pytest.mark.slow  # 100MB pull is bandwidth-bound; the staggered-broadcast twin keeps the transfer plane tier-1
def test_worker_to_worker_transfer_100mb(two_isolated_nodes):
    """A >=100MB array produced on node A is consumed on node B with no
    shared store path between them."""
    _cluster, n1, n2, roots = two_isolated_nodes

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n1))
    def produce():
        # 100 MB of deterministic bytes
        return np.arange(100 * 1024 * 1024 // 8, dtype=np.int64)

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n2))
    def consume(arr):
        return (arr.nbytes, int(arr[0]), int(arr[-1]), int(arr.sum() % 1000003))

    ref = produce.remote()
    nbytes, first, last, chk = ray_tpu.get(consume.remote(ref), timeout=180)
    n = 100 * 1024 * 1024 // 8
    assert nbytes == 100 * 1024 * 1024
    assert (first, last) == (0, n - 1)
    assert chk == int(np.arange(n, dtype=np.int64).sum() % 1000003)
    # Both nodes now hold a copy in their OWN root (producer sealed, consumer
    # pulled) — proving the bytes moved rather than being path-shared.
    assert _store_files(roots[0]) and _store_files(roots[1])


def test_driver_gets_remote_object(two_isolated_nodes):
    _cluster, n1, _n2, _roots = two_isolated_nodes

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n1))
    def produce():
        return np.full((4 * 1024 * 1024,), 7, dtype=np.uint8)

    arr = ray_tpu.get(produce.remote(), timeout=60)
    assert arr.shape == (4 * 1024 * 1024,)
    assert int(arr[0]) == 7 and int(arr[-1]) == 7


def test_driver_put_pulled_by_remote_worker(two_isolated_nodes):
    """Driver-put large object (head store) consumed on a daemon node."""
    _cluster, _n1, n2, _roots = two_isolated_nodes

    big = np.arange(2 * 1024 * 1024, dtype=np.float32)
    ref = ray_tpu.put(big)

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n2))
    def consume(arr):
        return float(arr.sum())

    assert ray_tpu.get(consume.remote(ref), timeout=60) == float(big.sum())


def test_small_objects_inline_cross_node(two_isolated_nodes):
    _cluster, n1, n2, _roots = two_isolated_nodes

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n1))
    def produce():
        return {"tiny": list(range(10))}

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n2))
    def consume(d):
        return sum(d["tiny"])

    assert ray_tpu.get(consume.remote(produce.remote()), timeout=60) == 45


def test_free_propagates_to_remote_copies(ray_start_cluster, tmp_path, monkeypatch):
    # File-per-object backend so segment files are directly observable
    # (arena-backed segments live inside one heap file).  Daemons + their
    # workers inherit this env at spawn.
    monkeypatch.setenv("RAY_TPU_NATIVE_STORE", "0")
    cluster = ray_start_cluster
    roots = [tmp_path / "nodeA", tmp_path / "nodeB"]
    for r in roots:
        r.mkdir()
    n1 = cluster.add_node(num_cpus=2, daemon=True, store_root=str(roots[0]))
    n2 = cluster.add_node(num_cpus=2, daemon=True, store_root=str(roots[1]))

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n1))
    def produce():
        return np.zeros(1024 * 1024, dtype=np.uint8)

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n2))
    def touch(arr):
        return arr.nbytes

    ref = produce.remote()
    assert ray_tpu.get(touch.remote(ref), timeout=60) == 1024 * 1024
    # Both node stores hold a segment file for the object (producer seal +
    # consumer pulled copy).
    deadline = time.time() + 20
    while time.time() < deadline:
        if all(_store_files(r) for r in roots):
            break
        time.sleep(0.1)
    assert all(_store_files(r) for r in roots)

    del ref  # ownership release -> delete broadcast to holder nodes
    deadline = time.time() + 30
    while time.time() < deadline:
        if not any(_store_files(r) for r in roots):
            break
        time.sleep(0.2)
    assert not any(_store_files(r) for r in roots)


def test_node_death_then_reconstruction(two_isolated_nodes):
    """The only copy dies with its node; lineage re-executes the producer."""
    cluster, n1, _n2, _roots = two_isolated_nodes

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(n1, soft=True))
    def produce():
        return np.ones(1024 * 1024, dtype=np.uint8)

    ref = produce.remote()
    # Ensure it is sealed on n1 before the kill (readiness implies seal).
    ray_tpu.wait([ref], num_returns=1, timeout=60)
    cluster.kill_node_daemon(n1)
    time.sleep(1.0)
    arr = ray_tpu.get(ref, timeout=120)  # reconstructed via lineage
    assert int(arr.sum()) == 1024 * 1024


@pytest.fixture
def classic_staggered(monkeypatch):
    """Pin the legacy staggered-broadcast admission (relay_pipeline=0):
    these tests assert the park/grant mechanics the pipelined plan
    deliberately replaces."""
    from ray_tpu._private import config as _config

    monkeypatch.setenv("RAY_TPU_RELAY_PIPELINE", "0")
    _config._reset_for_tests()
    yield
    monkeypatch.delenv("RAY_TPU_RELAY_PIPELINE", raising=False)
    _config._reset_for_tests()


def test_broadcast_staggers_pulls_across_sources(ray_start_regular, classic_staggered):
    """8-node broadcast of one object under relay_pipeline=0: pull grants
    are capped at the number of source copies, excess pullers park until
    a new copy registers, and every node still lands the full bytes
    (VERDICT r4 item 6 — the 1 GiB x 50-node scalability row's topology
    fix; the pipelined transfer plan is tested separately below)."""
    import numpy as np

    from ray_tpu._private.runtime import get_runtime
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    rt = get_runtime()
    nids = [rt.add_daemon_node(num_cpus=1) for _ in range(8)]
    payload = np.arange(1 << 20, dtype=np.int64)  # 8MB
    ref = ray_tpu.put(payload)

    @ray_tpu.remote
    def land(x):
        return int(x.sum())

    @ray_tpu.remote
    def warm():
        return 1

    ray_tpu.get(
        [
            warm.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(n)
            ).remote()
            for n in nids
        ],
        timeout=300,
    )
    before_parks = rt.metrics["pull_parks"]
    outs = ray_tpu.get(
        [
            land.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(n)
            ).remote(ref)
            for n in nids
        ],
        timeout=300,
    )
    expect = int(payload.sum())
    assert outs == [expect] * 8
    # 8 simultaneous pullers vs 1 initial source: someone must have parked.
    assert rt.metrics["pull_parks"] > before_parks
    # Every node registered its copy (the directory grew to all 8).
    locs = rt.object_locations.get(ref.id, set())
    assert len(locs) == 8, locs
    for nid in nids:
        rt.remove_node(nid)


def test_admit_pull_caps_grants_and_rotates(ray_start_regular, classic_staggered):
    """_admit_pull (relay_pipeline=0): grants are capped at the source
    count; replies rotate the endpoint list; object_copied frees a grant
    (unit-level checks of the staggered-broadcast admission)."""
    from ray_tpu._private.runtime import _PARKED, get_runtime

    rt = get_runtime()
    eps = [("h1", 1), ("h2", 2)]
    oid = "o:unit-admit:0"
    r1 = rt._admit_pull("w1", 1, oid, list(eps))
    r2 = rt._admit_pull("w2", 2, oid, list(eps))
    assert r1[0] == "pull" and r2[0] == "pull"
    assert r1[1] != r2[1], "endpoint rotation must spread pullers"
    # Third puller vs two sources: parked.
    r3 = rt._admit_pull("w3", 3, oid, list(eps))
    assert r3 is _PARKED
    assert rt.metrics["pull_parks"] >= 1
    # A copy lands: one grant freed -> next admission succeeds.
    with rt.lock:
        grants = rt._pull_grants.get(oid)
        assert grants and len(grants) == 2
        grants.pop()
    r4 = rt._admit_pull("w4", 4, oid, list(eps))
    assert r4[0] == "pull"
    # Consume w3's park deterministically (its 5s fallback timer must not
    # fire into a torn-down runtime after the fixture exits): make the
    # object resolvable, then publish the wake-up the park waits on.
    rt.store.put_error(oid, RuntimeError("unit-test cleanup"))
    deferred = rt.pubsub.publish("object_copied", oid, oid)
    for cb in deferred:
        cb(oid)
    time.sleep(0.2)  # the deferred serve replies (to a nonexistent wid)
    with rt.lock:
        rt._pull_grants.pop(oid, None)


# ---------------------------------------------------------------------------
# pipelined tree/chain broadcast (relay transfer plans)


def test_transfer_plan_builds_relay_chain(ray_start_regular):
    """_admit_pull (relay_pipeline=1): every admitted puller immediately
    registers its node as a feed; sealed sources fill to fanout first,
    then the tree chains off in-flight relays — and nobody parks."""
    from ray_tpu._private import config as _config
    from ray_tpu._private.runtime import get_runtime

    rt = get_runtime()
    assert _config.get("relay_fanout") == 2  # the shape below assumes it
    oid = "o:unit-plan:0"
    src = ("src", 1)
    with rt.lock:
        rt.driver_nodes.update(
            {"pw1": "pnodeA", "pw2": "pnodeB", "pw3": "pnodeC"}
        )
        rt.node_object_endpoints.update(
            {"pnodeA": ("hA", 10), "pnodeB": ("hB", 11), "pnodeC": ("hC", 12)}
        )
    try:
        r1 = rt._admit_pull("pw1", 1, oid, [src])
        assert r1[0] == "pull" and tuple(r1[1][0]) == src
        # Sealed-first: the source still has fanout headroom, so the
        # second puller fills it rather than chaining immediately.
        r2 = rt._admit_pull("pw2", 2, oid, [src])
        assert r2[0] == "pull"
        assert tuple(r2[1][0]) == src, r2[1]
        # Third: the source is saturated (fanout 2) — the tree chains
        # off the first puller's in-flight relay, sealed fallback tail.
        r3 = rt._admit_pull("pw3", 3, oid, [src])
        assert tuple(r3[1][0]) == ("hA", 10), r3[1]
        assert [tuple(e) for e in r3[1]].count(src) == 1  # sealed fallback
        # A completed pull releases its feed slot.
        with rt.lock:
            st = rt._xfer_plans[oid]
            assert st["feeds"][("hA", 10)]["load"] == 1
            rt._release_pull_slot_locked(oid, "pnodeC")
            assert rt._xfer_plans[oid]["feeds"][("hA", 10)]["load"] == 0
    finally:
        with rt.lock:
            rt._xfer_plans.pop(oid, None)
            for w in ("pw1", "pw2", "pw3"):
                rt.driver_nodes.pop(w, None)
            for n in ("pnodeA", "pnodeB", "pnodeC"):
                rt.node_object_endpoints.pop(n, None)


def test_transfer_plan_parks_without_relay_capacity(ray_start_regular):
    """Nodes with no object endpoint (remote drivers) cannot relay: once
    every feed is at fanout, the next puller parks exactly like the
    classic staggered admission."""
    from ray_tpu._private import config as _config
    from ray_tpu._private.runtime import _PARKED, get_runtime

    rt = get_runtime()
    fanout = _config.get("relay_fanout")
    oid = "o:unit-park:0"
    src = ("src2", 1)
    with rt.lock:
        for i in range(fanout + 1):
            rt.driver_nodes[f"qw{i}"] = f"qnode{i}"  # no object endpoints
    try:
        for i in range(fanout):
            assert rt._admit_pull(f"qw{i}", i, oid, [src])[0] == "pull"
        parks0 = rt.metrics["pull_parks"]
        assert rt._admit_pull(f"qw{fanout}", fanout, oid, [src]) is _PARKED
        assert rt.metrics["pull_parks"] == parks0 + 1
        # Consume the park (same cleanup dance as the staggered test).
        rt.store.put_error(oid, RuntimeError("unit-test cleanup"))
        deferred = rt.pubsub.publish("object_copied", oid, oid)
        for cb in deferred:
            cb(oid)
        time.sleep(0.2)
    finally:
        with rt.lock:
            rt._xfer_plans.pop(oid, None)
            for i in range(fanout + 1):
                rt.driver_nodes.pop(f"qw{i}", None)


def _mk_store(tmp_path, name):
    from ray_tpu._private.store import ShmStore

    d = tmp_path / name
    d.mkdir()
    return ShmStore(f"xfer-{name}-{os.getpid()}", capacity=64 * 1024 * 1024,
                    dir_path=str(d))


def test_relay_serves_in_flight_pull(tmp_path):
    """A downstream fetch against a node whose pull is STILL IN FLIGHT
    streams the landed prefix mid-transfer (via == "relay"), chunk crcs
    verify, and the downstream seals byte-identical data."""
    import threading

    from ray_tpu._private import object_plane

    store_a = _mk_store(tmp_path, "relayA")
    store_b = _mk_store(tmp_path, "relayB")
    authkey = b"relay-test-key"
    server = object_plane.ObjectServer(
        store_a.get_raw, authkey, advertise_host="127.0.0.1",
        bind_host="127.0.0.1", read_board=store_a.read_board,
    )
    oid = "o:relaytest:0"
    payload = os.urandom(1 << 20)  # 1MB, 8 chunks of 128KB below
    chunk = 128 * 1024
    started = threading.Event()

    def upstream_writer():
        sink = store_a.start_pull(oid, len(payload))
        off = 0
        while off < len(payload):
            n = min(chunk, len(payload) - off)
            sink.view[off : off + n] = payload[off : off + n]
            sink.advance(n)
            off += n
            started.set()
            time.sleep(0.05)  # the downstream chases this watermark
        sink.commit()

    w = threading.Thread(target=upstream_writer, daemon=True)
    try:
        from ray_tpu._private import telemetry as _telemetry

        c0 = _telemetry.copy_counter_snapshot()
        w.start()
        assert started.wait(5.0)
        r = object_plane.fetch_object(
            server.endpoint, authkey, oid, store_b.start_pull, timeout=30.0
        )
        assert r is not None
        total, via = r
        assert via == "relay", f"expected a mid-flight relay, got {via}"
        assert total == len(payload)
        buf, keep = store_b.get_raw(oid)
        assert bytes(buf) == payload
        del buf, keep
        w.join(10.0)
        # The bytes-per-copy honesty counters: EXACTLY ONE relay copy of
        # exactly the payload's packed size, and zero classic pulls —
        # pipelining must not silently multiply copies.
        c1 = _telemetry.copy_counter_snapshot()

        def delta(path, field):
            return c1.get(path, {}).get(field, 0.0) - c0.get(path, {}).get(field, 0.0)

        assert delta("relay", "copies") == 1.0
        assert delta("relay", "bytes") == len(payload)
        assert delta("pull", "copies") == 0.0
    finally:
        server.close()
        store_a.destroy()
        store_b.destroy()


def test_relay_death_falls_back_to_sealed_source(tmp_path, monkeypatch):
    """A relay that dies mid-serve (board fails, conn closes) costs the
    downstream one fallback hop: pull_from_any lands the object from the
    sealed source in the plan tail — re-plan, not wedge."""
    from ray_tpu._private import config as _config
    from ray_tpu._private import object_plane

    monkeypatch.setenv("RAY_TPU_RELAY_STALL_TIMEOUT_S", "1.0")
    _config._reset_for_tests()
    try:
        store_dead = _mk_store(tmp_path, "dead")
        store_src = _mk_store(tmp_path, "src")
        store_dst = _mk_store(tmp_path, "dst")
        authkey = b"relay-dead-key"
        payload = os.urandom(256 * 1024)
        oid = "o:relaydead:0"
        # The sealed source has the real object.
        store_src.create(oid, payload, [])
        src_raw, _k = store_src.get_raw(oid)
        total = len(src_raw)
        # The dying relay: a board that lands a prefix then FAILS.
        sink = store_dead.start_pull(oid, total)
        sink.view[: 64 * 1024] = bytes(src_raw[: 64 * 1024])
        sink.advance(64 * 1024)
        dead_srv = object_plane.ObjectServer(
            store_dead.get_raw, authkey, advertise_host="127.0.0.1",
            bind_host="127.0.0.1", read_board=store_dead.read_board,
        )
        src_srv = object_plane.ObjectServer(
            store_src.get_raw, authkey, advertise_host="127.0.0.1",
            bind_host="127.0.0.1", read_board=store_src.read_board,
        )
        import threading

        killer = threading.Timer(0.3, sink.abort)
        killer.daemon = True
        killer.start()
        try:
            r = object_plane.pull_from_any(
                [dead_srv.endpoint, src_srv.endpoint], authkey, oid,
                store_dst.start_pull, timeout=30.0,
            )
            assert r is not None
            _total, via = r
            assert via == "pull", f"fallback must land from the sealed source, got {via}"
            buf, keep = store_dst.get_raw(oid)
            assert bytes(buf) == bytes(src_raw)
            del buf, keep
        finally:
            killer.cancel()
            dead_srv.close()
            src_srv.close()
            store_dead.destroy()
            store_src.destroy()
            store_dst.destroy()
    finally:
        monkeypatch.delenv("RAY_TPU_RELAY_STALL_TIMEOUT_S", raising=False)
        _config._reset_for_tests()


def test_broadcast_relay_one_sealed_copy_per_node(ray_start_regular):
    """The BENCH_objmem invariant extended to the pipelined path: a cold
    N-node broadcast lands EXACTLY ONE sealed copy per receiving node —
    pipelining must not silently multiply copies or re-read the source.
    Counter-asserted via the head's ledger events (one transfer|relay
    event per node, none duplicated)."""
    import numpy as np

    from ray_tpu._private.runtime import get_runtime
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    rt = get_runtime()
    n_nodes = 4
    nids = [rt.add_daemon_node(num_cpus=1) for _ in range(n_nodes)]
    payload = np.arange(1 << 20, dtype=np.int64)  # 8MB
    ref = ray_tpu.put(payload)

    @ray_tpu.remote
    def land(x):
        return int(x.sum())

    @ray_tpu.remote
    def warm():
        return 1

    ray_tpu.get(
        [
            warm.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(n)
            ).remote()
            for n in nids
        ],
        timeout=300,
    )
    outs = ray_tpu.get(
        [
            land.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(n)
            ).remote(ref)
            for n in nids
        ],
        timeout=300,
    )
    assert outs == [int(payload.sum())] * n_nodes
    # Every node holds exactly one copy, registered exactly once: the
    # object_copied oneways ride the same FIFO conns as the done frames,
    # so they have all landed by the time get() returns.
    locs = rt.object_locations.get(ref.id, set())
    assert len(locs) == n_nodes, locs
    landings = [
        e for e in rt.object_events
        if e["oid"] == ref.id and e["event"] in ("transfer", "relay")
    ]
    per_node = {}
    for e in landings:
        per_node[e["node"]] = per_node.get(e["node"], 0) + 1
    assert per_node == {n: 1 for n in nids}, (
        f"pipelined broadcast must land exactly 1 sealed copy per node: "
        f"{per_node}"
    )
    # Plan state quiesced (slots released by the object_copied reports).
    with rt.lock:
        st = rt._xfer_plans.get(ref.id)
        assert st is None or not st["pulling"], st
    for nid in nids:
        rt.remove_node(nid)
